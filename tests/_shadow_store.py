"""The SEED lock-based StateStore, frozen as the MVCC shadow oracle.

This is the pre-ISSUE-16 store verbatim (single RLock, COW-shared
table snapshots). tests/test_mvcc_store.py replays every randomized
op stream against BOTH stores and asserts bit-identical post-state —
the MVCC rebuild must be a pure representation change. Do not "fix"
or modernize this file; its value is that it does not move.
"""

from __future__ import annotations

import pickle
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from nomad_tpu.structs import consts
from nomad_tpu.structs.alloc import Allocation
from nomad_tpu.structs.eval_plan import Deployment, Evaluation, Plan, PlanResult
from nomad_tpu.utils.witness import witness_lock


class SchedulerConfiguration:
    """Runtime-mutable scheduler config (reference structs.go
    SchedulerConfiguration; stored in raft, schema.go:65)."""

    def __init__(self) -> None:
        self.scheduler_algorithm = consts.SCHEDULER_ALGORITHM_BINPACK
        self.preemption_system_enabled = True
        self.preemption_batch_enabled = False
        self.preemption_service_enabled = False
        self.memory_oversubscription_enabled = False
        self.pause_eval_broker = False

    def effective_algorithm(self) -> str:
        return self.scheduler_algorithm

    def preemption_enabled(self, scheduler_type: str) -> bool:
        return {
            consts.JOB_TYPE_SERVICE: self.preemption_service_enabled,
            consts.JOB_TYPE_BATCH: self.preemption_batch_enabled,
            consts.JOB_TYPE_SYSTEM: self.preemption_system_enabled,
            consts.JOB_TYPE_SYSBATCH: self.preemption_system_enabled,
        }.get(scheduler_type, False)


class WatchStats:
    """Blocking-query wakeup accounting (ISSUE 11): how many watchers
    ``block_until`` currently holds parked, how often they wake for a
    real index advance vs spuriously (a shared Event set by an
    unrelated table's commit callback racing the re-check), and how
    many waits expire. The serving plane is mostly reads and watches —
    without these counters a fleet-scale watch storm is invisible in
    every exposition surface."""

    __slots__ = ("_lock", "held", "wakeups", "spurious", "timeouts")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.held = 0
        self.wakeups = 0
        self.spurious = 0
        self.timeouts = 0

    def enter(self) -> None:
        with self._lock:
            self.held += 1

    def leave(self) -> None:
        with self._lock:
            self.held -= 1

    def note_wakeup(self, spurious: bool) -> None:
        with self._lock:
            if spurious:
                self.spurious += 1
            else:
                self.wakeups += 1

    def note_timeout(self) -> None:
        with self._lock:
            self.timeouts += 1

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "held_watchers": self.held,
                "wakeups": self.wakeups,
                "spurious_wakeups": self.spurious,
                "timeouts": self.timeouts,
            }

    def reset_stats(self) -> None:
        """Counters only; the held gauge tracks live waiters."""
        with self._lock:
            self.wakeups = 0
            self.spurious = 0
            self.timeouts = 0


#: process-wide (every StateStore's block_until feeds it; exported as
#: nomad_tpu_watch_* and ridden into TRACE_DECOMP's serving section)
watch_stats = WatchStats()


#: tables a snapshot shares copy-on-write with the store. Index tables
#: (allocs_by_*) hold immutable frozenset values so sharing the dict is
#: enough; every mutator replaces values instead of mutating them.
_COW_TABLES = (
    "nodes", "jobs", "job_versions", "evals", "allocs", "deployments",
    "allocs_by_job", "allocs_by_node", "allocs_by_eval", "csi_volumes",
)


class StateSnapshot:
    """A point-in-time read view (memdb Snapshot analog).

    Implements the scheduler's ``State`` interface
    (reference scheduler/scheduler.go:67-141).

    Construction is O(1): the snapshot takes REFERENCES to the store's
    tables and marks them shared; the first mutation of a shared table
    copies that table (``StateStore._own``). This is the dict analog of
    go-memdb's immutable-radix snapshots — the reference's snapshots
    are free (state_store.go Snapshot), and at C2M scale (100k allocs)
    eager per-snapshot table copies were the next scaling wall.
    """

    def __init__(self, store: "StateStore") -> None:
        with store._lock:
            self.index = store._index
            store._shared.update(_COW_TABLES)
            self._nodes = store._nodes
            self._jobs = store._jobs
            self._job_versions = store._job_versions
            self._evals = store._evals
            self._allocs = store._allocs
            self._deployments = store._deployments
            self._allocs_by_job = store._allocs_by_job
            self._allocs_by_node = store._allocs_by_node
            self._allocs_by_eval = store._allocs_by_eval
            self._csi_volumes = store._csi_volumes
            self.scheduler_config = store.scheduler_config
            # live utilization planes for the scheduler fast path
            # (state/usage.py); cached until the next mutation
            self.usage = store.usage.planes_copy()

    # --- State interface (scheduler.go:67-141) ---

    def nodes(self) -> List:
        return list(self._nodes.values())

    def node_by_id(self, node_id: str):
        return self._nodes.get(node_id)

    def ready_nodes_in_pool(self, pool: str = "default") -> List:
        return [n for n in self._nodes.values() if n.ready()]

    def job_by_id(self, namespace: str, job_id: str):
        return self._jobs.get((namespace, job_id))

    def job_by_id_and_version(self, namespace: str, job_id: str, version: int):
        return self._job_versions.get((namespace, job_id, version))

    def jobs(self) -> List:
        return list(self._jobs.values())

    def eval_by_id(self, eval_id: str):
        return self._evals.get(eval_id)

    def evals_iter(self):
        return self._evals.values()

    def evals_by_job(self, namespace: str, job_id: str) -> List[Evaluation]:
        return [
            e for e in self._evals.values()
            if e.namespace == namespace and e.job_id == job_id
        ]

    def allocs_by_job(self, namespace: str, job_id: str, anyCreateIndex: bool = True) -> List[Allocation]:
        ids = self._allocs_by_job.get((namespace, job_id), ())
        return [self._allocs[i] for i in ids]

    def allocs_by_node(self, node_id: str) -> List[Allocation]:
        ids = self._allocs_by_node.get(node_id, ())
        return [self._allocs[i] for i in ids]

    def allocs_by_node_terminal(self, node_id: str, terminal: bool) -> List[Allocation]:
        return [a for a in self.allocs_by_node(node_id) if a.terminal_status() == terminal]

    def allocs_by_eval(self, eval_id: str) -> List[Allocation]:
        ids = self._allocs_by_eval.get(eval_id, ())
        return [self._allocs[i] for i in ids]

    def alloc_by_id(self, alloc_id: str):
        return self._allocs.get(alloc_id)

    def allocs_iter(self):
        return self._allocs.values()

    def latest_deployment_by_job_id(self, namespace: str, job_id: str):
        best = None
        for d in self._deployments.values():
            if d.namespace == namespace and d.job_id == job_id:
                if best is None or d.create_index > best.create_index:
                    best = d
        return best

    def deployments_by_job_id(self, namespace: str, job_id: str) -> List[Deployment]:
        return [
            d for d in self._deployments.values()
            if d.namespace == namespace and d.job_id == job_id
        ]

    def deployment_by_id(self, deployment_id: str):
        return self._deployments.get(deployment_id)

    def deployments_iter(self):
        return self._deployments.values()

    def csi_volume_by_id(self, namespace: str, volume_id: str):
        return self._csi_volumes.get((namespace, volume_id))

    def csi_volumes_iter(self):
        return self._csi_volumes.values()

    def latest_index(self) -> int:
        return self.index


class StateStore:
    """The writable store. One per server; FSM applies Raft entries here."""

    def __init__(self) -> None:
        from nomad_tpu.state.usage import UsageIndex

        self._lock = witness_lock("StateStore._lock", rlock=True)
        self._index = 0
        # incrementally-scattered per-node utilization planes; every
        # alloc/node mutation below routes its transition through it
        self.usage = UsageIndex()
        self._nodes: Dict[str, object] = {}
        self._jobs: Dict[Tuple[str, str], object] = {}
        self._job_versions: Dict[Tuple[str, str, int], object] = {}
        self._evals: Dict[str, Evaluation] = {}
        self._allocs: Dict[str, Allocation] = {}
        self._deployments: Dict[str, Deployment] = {}
        # index tables hold FROZENSET values (immutable): updates
        # replace the value, so snapshots can share the dict by
        # reference (see _COW_TABLES)
        self._allocs_by_job: Dict[Tuple[str, str], frozenset] = {}
        self._allocs_by_node: Dict[str, frozenset] = {}
        self._allocs_by_eval: Dict[str, frozenset] = {}
        # tables currently shared by-reference with >=1 snapshot; a
        # mutator copies the table first (_own) — copy-on-write
        self._shared: set = set()
        # aux tables (schema.go:50-72: namespaces, scaling_event,
        # scaling_policy, acl_policy, acl_token)
        self._namespaces: Dict[str, object] = {}
        self._scaling_events: Dict[Tuple[str, str], List] = {}
        self._acl_policies: Dict[str, object] = {}
        self._acl_tokens: Dict[str, object] = {}
        # CSI volumes keyed (namespace, id) (schema.go csi_volumes;
        # plugins are derived from node fingerprints on read)
        self._csi_volumes: Dict[Tuple[str, str], object] = {}
        # native service registrations keyed by instance id
        # (schema.go service_registrations)
        self._services: Dict[str, object] = {}
        # one-time ACL tokens keyed by one-time secret
        # (schema.go one_time_token): {"accessor_id", "expires_at"}
        self._one_time_tokens: Dict[str, Dict] = {}
        # periodic launch ledger keyed (namespace, job_id) -> last
        # launch unix time (schema.go periodic_launch)
        self._periodic_launches: Dict[Tuple[str, str], float] = {}
        # WAN federation registry: region -> HTTP address of a server
        # there (serf WAN member list analog; replicated so failover
        # keeps forwarding + ACL replication working)
        self._regions: Dict[str, str] = {}
        # autopilot config (schema.go autopilot-config)
        self.autopilot_config: Dict = {
            "cleanup_dead_servers": True,
            "last_contact_threshold_s": 10.0,
            "server_stabilization_time_s": 10.0,
        }
        self.scheduler_config = SchedulerConfiguration()
        # table name -> [callback(index)]; fired outside the lock
        self._watchers: Dict[str, List[Callable[[int], None]]] = {}
        # table name -> index of its last commit (memdb per-table index
        # rows; lets blocking queries ignore unrelated tables)
        self._table_indexes: Dict[str, int] = {}

    # --- infrastructure ---

    def snapshot(self) -> StateSnapshot:
        return StateSnapshot(self)

    def latest_index(self) -> int:
        with self._lock:
            return self._index

    def watch(self, table: str, cb: Callable[[int], None]) -> Callable[[], None]:
        """Register a commit callback for a table; returns unwatch fn."""
        with self._lock:
            self._watchers.setdefault(table, []).append(cb)

        def unwatch() -> None:
            with self._lock:
                lst = self._watchers.get(table, [])
                if cb in lst:
                    lst.remove(cb)

        return unwatch

    def _notify(self, tables: List[str], index: int) -> None:
        cbs: List[Callable[[int], None]] = []
        with self._lock:
            for t in tables:
                self._table_indexes[t] = max(self._table_indexes.get(t, 0), index)
                cbs.extend(self._watchers.get(t, ()))
        for cb in cbs:
            cb(index)

    def table_index(self, tables: List[str]) -> int:
        """Highest commit index across the given tables."""
        with self._lock:
            return max((self._table_indexes.get(t, 0) for t in tables), default=0)

    def _next_index(self) -> int:
        self._index += 1
        return self._index

    def has_draining_nodes(self) -> bool:
        """Cheap pre-check for the drainer: whether ANY node is
        draining, without constructing a snapshot (snapshot
        construction copies the usage planes — too expensive to pay
        on every alloc commit just to discover there is no drain)."""
        with self._lock:
            return any(getattr(n, "drain", False)
                       for n in self._nodes.values())

    def csi_volume_count(self) -> int:
        """Cheap pre-check for the volume watcher (same rationale as
        has_draining_nodes)."""
        with self._lock:
            return len(self._csi_volumes)

    def node_by_id_direct(self, node_id: str):
        """Direct locked read of one node row (no COW snapshot): for
        hot paths that need a single node — building a snapshot marks
        every table shared and forces whole-table copies on the next
        mutation. Rows are replaced (never mutated) on update, so
        handing one out is safe."""
        with self._lock:
            return self._nodes.get(node_id)

    def alloc_by_id_direct(self, alloc_id: str):
        """Direct locked read of one alloc row (same rationale as
        node_by_id_direct)."""
        with self._lock:
            return self._allocs.get(alloc_id)

    def allocs_by_node_direct(self, node_id: str) -> List:
        """Direct locked read of one node's alloc rows (no COW
        snapshot) — the plan applier's per-plan view reads exactly one
        node's list; rows are replaced, never mutated, so handing them
        out is safe (graftcheck R4: this accessor replaces raw
        ``_allocs_by_node`` reaching from server/plan_apply.py)."""
        with self._lock:
            ids = self._allocs_by_node.get(node_id, ())
            return [self._allocs[i] for i in ids]

    def with_usage_view(self, fn):
        """Run ``fn(planes, allocs)`` under the store lock: ``planes``
        is the cached utilization planes copy (state/usage.py),
        ``allocs`` the live alloc table — both READ-ONLY to the
        callee. The plan applier's group checker uses this to fold
        in-flight plan results against a planes snapshot that is
        CONSISTENT with its per-alloc liveness reads: a commit landing
        between the two reads would otherwise double-count its
        allocs (server/plan_apply._GroupFitChecker)."""
        with self._lock:
            return fn(self.usage.planes_copy(), self._allocs)

    def with_allocs(self, fn):
        """Run ``fn(allocs)`` under the store lock with the live alloc
        table (READ-ONLY to the callee) — ``with_usage_view`` without
        the planes copy, for callers that only need consistent
        per-alloc liveness reads."""
        with self._lock:
            return fn(self._allocs)

    def _own(self, *tables: str) -> None:
        """Copy-on-write: detach the named tables from any snapshots
        sharing them. Call under the lock BEFORE mutating a table."""
        for name in tables:
            if name in self._shared:
                setattr(self, "_" + name, dict(getattr(self, "_" + name)))
                self._shared.discard(name)

    def block_until(self, tables: List[str], min_index: int, timeout: float) -> int:
        """Block until one of `tables` commits past min_index or the
        timeout passes; returns those tables' current index. This is the
        memdb WatchSet + min-index contract behind blocking queries
        (reference rpc.go:808 blockingRPC). Keyed on per-table indexes
        so unrelated commits don't wake every watcher."""
        if self.table_index(tables) > min_index or timeout <= 0:
            return max(self.table_index(tables), min_index)
        event = threading.Event()
        unwatchers = [self.watch(t, lambda _i: event.set()) for t in tables]
        watch_stats.enter()
        try:
            deadline = time.time() + timeout
            idx = self.table_index(tables)
            while idx <= min_index:
                remaining = deadline - time.time()
                if remaining <= 0:
                    watch_stats.note_timeout()
                    break
                woke = event.wait(remaining)
                event.clear()
                # ONE index read per wakeup serves both the spurious
                # check and the loop condition (the watch path is the
                # store-lock traffic this PR is measuring — no second
                # acquisition per wakeup)
                idx = self.table_index(tables)
                if woke:
                    # spurious = a commit callback fired but the watched
                    # tables' index has not actually advanced (callback
                    # raced the registration, or a second wait loop
                    # consumed a stale set) — re-park without progress
                    watch_stats.note_wakeup(spurious=idx <= min_index)
            return max(idx, min_index)
        finally:
            watch_stats.leave()
            for unwatch in unwatchers:
                unwatch()

    # --- snapshot persist/restore (fsm.go:1393 Snapshot, :1407 Restore) -

    # --- aux tables: namespaces / scaling / ACL / stability -------------

    def upsert_namespace(self, ns) -> int:
        with self._lock:
            idx = self._next_index()
            self._namespaces[ns.name] = ns
        self._notify(["namespaces"], idx)
        return idx

    def delete_namespace(self, name: str) -> int:
        with self._lock:
            if any(key[0] == name for key in self._jobs):
                raise ValueError(f"namespace '{name}' has registered jobs")
            idx = self._next_index()
            self._namespaces.pop(name, None)
        self._notify(["namespaces"], idx)
        return idx

    def namespaces(self) -> List:
        with self._lock:
            return list(self._namespaces.values())

    def namespace_by_name(self, name: str):
        with self._lock:
            return self._namespaces.get(name)

    def record_scaling_event(self, namespace: str, job_id: str, group: str,
                             event: Dict) -> int:
        """state_store.go UpsertScalingEvent (bounded history per group)."""
        with self._lock:
            idx = self._next_index()
            event = dict(event)
            event.setdefault("task_group", group)
            events = self._scaling_events.setdefault((namespace, job_id), [])
            events.insert(0, event)
            del events[20:]  # structs.go JobTrackedScalingEvents
        self._notify(["scaling_event"], idx)
        return idx

    def scaling_events(self, namespace: str, job_id: str) -> List[Dict]:
        with self._lock:
            return list(self._scaling_events.get((namespace, job_id), []))

    def scaling_policies(self) -> List[Dict]:
        """Derived view: one policy per task group with a scaling stanza
        (reference stores these in a table keyed by target; deriving
        from the jobs table keeps them trivially consistent)."""
        with self._lock:
            out = []
            for (ns, jid), job in self._jobs.items():
                for tg in job.task_groups:
                    if tg.scaling is not None:
                        out.append({
                            "id": f"{ns}/{jid}/{tg.name}",
                            "namespace": ns, "job_id": jid, "group": tg.name,
                            "policy": tg.scaling, "enabled": tg.scaling.enabled,
                        })
            return out

    def scaling_policy_by_id(self, policy_id: str):
        for p in self.scaling_policies():
            if p["id"] == policy_id:
                return p
        return None

    def set_job_stability(self, namespace: str, job_id: str, version: int,
                          stable: bool) -> int:
        with self._lock:
            idx = self._next_index()
            job = self._job_versions.get((namespace, job_id, version))
            if job is not None:
                job.stable = stable
                job.modify_index = idx
        self._notify(["jobs"], idx)
        return idx

    def upsert_acl_policy(self, policy) -> int:
        with self._lock:
            idx = self._next_index()
            self._acl_policies[policy.name] = policy
        self._notify(["acl_policy"], idx)
        return idx

    def delete_acl_policy(self, name: str) -> int:
        with self._lock:
            idx = self._next_index()
            self._acl_policies.pop(name, None)
        self._notify(["acl_policy"], idx)
        return idx

    def acl_policies(self) -> List:
        with self._lock:
            return list(self._acl_policies.values())

    def acl_policy_by_name(self, name: str):
        with self._lock:
            return self._acl_policies.get(name)

    def deployment_by_id(self, deployment_id: str):
        """Direct locked read (no COW snapshot): for hot paths that
        need one row — a snapshot here would mark every table shared
        and force whole-table copies on the next mutation."""
        with self._lock:
            return self._deployments.get(deployment_id)

    def active_deployments(self) -> List[Deployment]:
        """Direct locked read of the active deployment rows (no COW
        snapshot): the deployments watcher polls this on every state
        change, and rows are replaced (never mutated) on update, so
        handing them out is safe."""
        with self._lock:
            return [d for d in self._deployments.values() if d.active()]

    def multiregion_terminal_deployment_ids(self) -> List[str]:
        """Ids of terminal multiregion deployments (the candidates for
        cross-region kicks) — the cheap gate that lets the watcher skip
        whole-state snapshots when there is no multiregion work."""
        with self._lock:
            return [
                d.id for d in self._deployments.values()
                if d.is_multiregion and d.status in (
                    consts.DEPLOYMENT_STATUS_SUCCESSFUL,
                    consts.DEPLOYMENT_STATUS_FAILED,
                )
            ]

    def upsert_acl_token(self, token) -> int:
        with self._lock:
            idx = self._next_index()
            self._acl_tokens[token.accessor_id] = token
        self._notify(["acl_token"], idx)
        return idx

    def delete_acl_token(self, accessor_id: str) -> int:
        with self._lock:
            idx = self._next_index()
            self._acl_tokens.pop(accessor_id, None)
        self._notify(["acl_token"], idx)
        return idx

    def acl_tokens(self) -> List:
        with self._lock:
            return list(self._acl_tokens.values())

    def acl_token_by_accessor(self, accessor_id: str):
        with self._lock:
            return self._acl_tokens.get(accessor_id)

    def acl_token_by_secret(self, secret_id: str):
        with self._lock:
            for t in self._acl_tokens.values():
                if t.secret_id == secret_id:
                    return t
            return None

    # --- CSI volumes (state_store.go UpsertCSIVolume/CSIVolumeClaim) ----

    def upsert_csi_volumes(self, volumes: List) -> int:
        with self._lock:
            idx = self._next_index()
            self._own("csi_volumes")
            for v in volumes:
                existing = self._csi_volumes.get((v.namespace, v.id))
                if existing is not None:
                    # re-register keeps live claims (csi_endpoint.go
                    # Register merge semantics)
                    v.read_claims = existing.read_claims
                    v.write_claims = existing.write_claims
                    v.past_claims = existing.past_claims
                    v.create_index = existing.create_index
                else:
                    v.create_index = idx
                v.modify_index = idx
                self._csi_volumes[(v.namespace, v.id)] = v
        self._notify(["csi_volumes"], idx)
        return idx

    def csi_volume_deregister(self, namespace: str, volume_id: str,
                              force: bool = False) -> int:
        with self._lock:
            vol = self._csi_volumes.get((namespace, volume_id))
            if vol is None:
                raise ValueError(f"volume not found: {volume_id}")
            if vol.in_use() and not force:
                raise ValueError(f"volume in use: {volume_id}")
            idx = self._next_index()
            self._own("csi_volumes")
            del self._csi_volumes[(namespace, volume_id)]
        self._notify(["csi_volumes"], idx)
        return idx

    def csi_volume_claim(self, namespace: str, volume_id: str, claim) -> int:
        """Apply a claim transition copy-on-write (state_store.go
        CSIVolumeClaim)."""
        with self._lock:
            vol = self._csi_volumes.get((namespace, volume_id))
            if vol is None:
                raise ValueError(f"volume not found: {volume_id}")
            vol = vol.copy()
            vol.claim(claim)
            idx = self._next_index()
            self._own("csi_volumes")
            vol.modify_index = idx
            self._csi_volumes[(namespace, volume_id)] = vol
        self._notify(["csi_volumes"], idx)
        return idx

    def csi_volumes(self) -> List:
        with self._lock:
            return list(self._csi_volumes.values())

    def csi_volume_by_id(self, namespace: str, volume_id: str):
        with self._lock:
            return self._csi_volumes.get((namespace, volume_id))

    def csi_volumes_by_plugin(self, plugin_id: str) -> List:
        with self._lock:
            return [v for v in self._csi_volumes.values()
                    if v.plugin_id == plugin_id]

    # --- service registrations (state_store_service_registration.go) ----

    def upsert_service_registrations(self, regs: List) -> int:
        with self._lock:
            idx = self._next_index()
            for r in regs:
                existing = self._services.get(r.id)
                r.create_index = existing.create_index if existing else idx
                r.modify_index = idx
                self._services[r.id] = r
        self._notify(["services"], idx)
        return idx

    def delete_service_registration(self, reg_id: str) -> int:
        with self._lock:
            if reg_id not in self._services:
                raise ValueError(f"service registration not found: {reg_id}")
            idx = self._next_index()
            del self._services[reg_id]
        self._notify(["services"], idx)
        return idx

    def delete_service_registrations_by_alloc(self, alloc_ids: List[str]) -> int:
        """Client dereg batches + alloc GC
        (DeleteServiceRegistrationByAllocID)."""
        doomed_allocs = set(alloc_ids)
        with self._lock:
            doomed = [r.id for r in self._services.values()
                      if r.alloc_id in doomed_allocs]
            if not doomed:
                return self._index
            idx = self._next_index()
            for rid in doomed:
                del self._services[rid]
        self._notify(["services"], idx)
        return idx

    def delete_service_registrations_by_node(self, node_id: str) -> int:
        """Node down/deregister reaping (DeleteServiceRegistrationByNodeID)."""
        with self._lock:
            doomed = [r.id for r in self._services.values()
                      if r.node_id == node_id]
            if not doomed:
                return self._index
            idx = self._next_index()
            for rid in doomed:
                del self._services[rid]
        self._notify(["services"], idx)
        return idx

    def service_registrations(self, namespace: str = "*") -> List:
        with self._lock:
            return [r for r in self._services.values()
                    if namespace in ("*", r.namespace)]

    def service_registrations_by_name(self, namespace: str, name: str) -> List:
        with self._lock:
            return [r for r in self._services.values()
                    if r.namespace == namespace and r.service_name == name]

    def service_registration_by_id(self, reg_id: str):
        with self._lock:
            return self._services.get(reg_id)

    # --- one-time tokens (state_store.go UpsertOneTimeToken) -----------

    def upsert_one_time_token(self, ott: Dict) -> int:
        with self._lock:
            idx = self._next_index()
            self._one_time_tokens[ott["one_time_secret_id"]] = dict(ott)
        self._notify(["one_time_token"], idx)
        return idx

    def one_time_token_by_secret(self, secret: str):
        with self._lock:
            return self._one_time_tokens.get(secret)

    def delete_one_time_tokens(self, secrets: List[str]) -> int:
        with self._lock:
            idx = self._next_index()
            for s in secrets:
                self._one_time_tokens.pop(s, None)
        self._notify(["one_time_token"], idx)
        return idx

    def expire_one_time_tokens(self, now: float) -> List[str]:
        with self._lock:
            return [s for s, t in self._one_time_tokens.items()
                    if t.get("expires_at", 0) <= now]

    # --- periodic launch ledger (state_store.go UpsertPeriodicLaunch) ---

    def upsert_periodic_launch(self, namespace: str, job_id: str,
                               launch_time: float) -> int:
        with self._lock:
            idx = self._next_index()
            self._periodic_launches[(namespace, job_id)] = launch_time
        self._notify(["periodic_launch"], idx)
        return idx

    def delete_periodic_launch(self, namespace: str, job_id: str) -> int:
        with self._lock:
            idx = self._next_index()
            self._periodic_launches.pop((namespace, job_id), None)
        self._notify(["periodic_launch"], idx)
        return idx

    def periodic_launch_by_id(self, namespace: str, job_id: str) -> float:
        with self._lock:
            return self._periodic_launches.get((namespace, job_id), 0.0)

    # --- federation registry --------------------------------------------

    def upsert_region(self, region: str, http_addr: str) -> int:
        with self._lock:
            idx = self._next_index()
            self._regions[region] = http_addr
        self._notify(["regions"], idx)
        return idx

    def regions(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._regions)

    # --- autopilot config (state_store.go AutopilotConfig) --------------

    def set_autopilot_config(self, config: Dict) -> int:
        with self._lock:
            idx = self._next_index()
            self.autopilot_config = dict(config)
        self._notify(["autopilot-config"], idx)
        return idx

    def to_snapshot_bytes(self) -> bytes:
        """Serialize every table for raft snapshots / operator backup."""
        with self._lock:
            payload = {
                "index": self._index,
                "nodes": dict(self._nodes),
                "jobs": dict(self._jobs),
                "job_versions": dict(self._job_versions),
                "evals": dict(self._evals),
                "allocs": dict(self._allocs),
                "deployments": dict(self._deployments),
                "allocs_by_job": {k: set(v) for k, v in self._allocs_by_job.items()},
                "allocs_by_node": {k: set(v) for k, v in self._allocs_by_node.items()},
                "allocs_by_eval": {k: set(v) for k, v in self._allocs_by_eval.items()},
                "scheduler_config": self.scheduler_config,
                "namespaces": dict(self._namespaces),
                "scaling_events": {k: list(v) for k, v in self._scaling_events.items()},
                "acl_policies": dict(self._acl_policies),
                "acl_tokens": dict(self._acl_tokens),
                "csi_volumes": dict(self._csi_volumes),
                "services": dict(self._services),
                "one_time_tokens": dict(self._one_time_tokens),
                "periodic_launches": dict(self._periodic_launches),
                "autopilot_config": dict(self.autopilot_config),
                "regions": dict(self._regions),
            }
        # serialize OUTSIDE the lock (graftcheck R2): the payload holds
        # shallow table copies and rows are replaced, never mutated, so
        # pickling them unlocked reads a consistent snapshot — and a
        # large cluster's dump no longer stalls every store reader for
        # the whole serialization
        return pickle.dumps(payload)

    def restore_from_bytes(self, data: bytes) -> None:
        payload = pickle.loads(data)
        with self._lock:
            self._index = payload["index"]
            self._nodes = payload["nodes"]
            self._jobs = payload["jobs"]
            self._job_versions = payload["job_versions"]
            self._evals = payload["evals"]
            self._allocs = payload["allocs"]
            self._deployments = payload["deployments"]
            self._allocs_by_job = {
                k: frozenset(v) for k, v in payload["allocs_by_job"].items()}
            self._allocs_by_node = {
                k: frozenset(v) for k, v in payload["allocs_by_node"].items()}
            self._allocs_by_eval = {
                k: frozenset(v) for k, v in payload["allocs_by_eval"].items()}
            # replaced wholesale: nothing is shared with snapshots now
            self._shared.clear()
            self.scheduler_config = payload["scheduler_config"]
            self._namespaces = payload.get("namespaces", {})
            self._scaling_events = payload.get("scaling_events", {})
            self._acl_policies = payload.get("acl_policies", {})
            self._acl_tokens = payload.get("acl_tokens", {})
            self._csi_volumes = payload.get("csi_volumes", {})
            self._services = payload.get("services", {})
            self._one_time_tokens = payload.get("one_time_tokens", {})
            self._periodic_launches = payload.get("periodic_launches", {})
            self.autopilot_config = payload.get(
                "autopilot_config", self.autopilot_config
            )
            self._regions = payload.get("regions", {})
            self.usage.rebuild(self._nodes.values(), self._allocs.values())
        self._notify(
            ["nodes", "jobs", "evals", "allocs", "deployment",
             "scheduler_config", "csi_volumes", "services",
             # restored ACLs must bump their table indexes, or the
             # token resolver's index-keyed compiled-ACL cache keeps
             # serving pre-restore policies
             "acl_policy", "acl_token"],
            payload["index"],
        )

    # --- writes (FSM apply targets, fsm.go:194-280 dispatch) ---

    def upsert_node(self, node) -> int:
        with self._lock:
            idx = self._next_index()
            self._own("nodes")
            if not node.computed_class:
                node.compute_class()
            node.modify_index = idx
            if node.create_index == 0:
                node.create_index = idx
            existing = self._nodes.get(node.id)
            if existing is not None:
                # re-registration keeps OPERATOR intent (state_store.go
                # upsertNodeTxn): a client restarting — including one
                # whose server restarted underneath it (ISSUE 13) —
                # sends a fresh Node struct, but drain state and
                # scheduling eligibility were set through the drain/
                # eligibility endpoints and must survive it
                node.drain = existing.drain
                node.drain_strategy = existing.drain_strategy
                node.scheduling_eligibility = existing.scheduling_eligibility
                if node.create_index == idx:
                    node.create_index = existing.create_index
            self._nodes[node.id] = node
            self.usage.node_row(node.id)
            self.usage.note_node_change(node.id)
        self._notify(["nodes"], idx)
        return idx

    def delete_node(self, node_id: str) -> int:
        with self._lock:
            idx = self._next_index()
            self._own("nodes")
            self._nodes.pop(node_id, None)
            self.usage.drop_node(node_id)
        self._notify(["nodes"], idx)
        return idx

    def update_node_status(self, node_id: str, status: str) -> int:
        with self._lock:
            idx = self._next_index()
            self._own("nodes")
            node = self._nodes.get(node_id)
            if node is not None:
                node = node.copy()
                node.status = status
                node.modify_index = idx
                self._nodes[node_id] = node
                self.usage.note_node_change(node_id)
        self._notify(["nodes"], idx)
        return idx

    def update_node_eligibility(self, node_id: str, eligibility: str) -> int:
        with self._lock:
            idx = self._next_index()
            self._own("nodes")
            node = self._nodes.get(node_id)
            if node is not None:
                node = node.copy()
                node.scheduling_eligibility = eligibility
                node.modify_index = idx
                self._nodes[node_id] = node
                self.usage.note_node_change(node_id)
        self._notify(["nodes"], idx)
        return idx

    def update_node_drain(self, node_id: str, drain: bool, strategy=None,
                          mark_eligible: bool = True) -> int:
        with self._lock:
            idx = self._next_index()
            self._own("nodes")
            node = self._nodes.get(node_id)
            if node is not None:
                node = node.copy()
                node.drain = drain
                node.drain_strategy = strategy
                if drain or not mark_eligible:
                    # drain completion keeps the node ineligible until
                    # the operator re-enables (drainer semantics)
                    node.scheduling_eligibility = consts.NODE_SCHEDULING_INELIGIBLE
                else:
                    node.scheduling_eligibility = consts.NODE_SCHEDULING_ELIGIBLE
                node.modify_index = idx
                self._nodes[node_id] = node
                self.usage.note_node_change(node_id)
        self._notify(["nodes"], idx)
        return idx

    def upsert_job(self, job) -> int:
        """UpsertJob: bumps version when the spec changed
        (state_store.go upsertJobImpl semantics)."""
        with self._lock:
            idx = self._next_index()
            self._own("jobs", "job_versions")
            key = (job.namespace, job.id)
            existing = self._jobs.get(key)
            if existing is not None:
                if existing.spec_hash() != job.spec_hash():
                    job.version = existing.version + 1
                else:
                    job.version = existing.version
                job.create_index = existing.create_index
            else:
                job.create_index = idx
                job.version = 0
            job.modify_index = idx
            job.job_modify_index = idx
            job.status = _job_status(job)
            self._jobs[key] = job
            self._job_versions[(job.namespace, job.id, job.version)] = job
        self._notify(["jobs"], idx)
        return idx

    def delete_job(self, namespace: str, job_id: str) -> int:
        with self._lock:
            idx = self._next_index()
            self._own("jobs", "job_versions")
            self._jobs.pop((namespace, job_id), None)
            # purge version history too (state_store.go DeleteJobTxn
            # deletes from the job_version table)
            for key in [
                k for k in self._job_versions
                if k[0] == namespace and k[1] == job_id
            ]:
                del self._job_versions[key]
        self._notify(["jobs"], idx)
        return idx

    def upsert_evals(self, evals: List[Evaluation]) -> int:
        with self._lock:
            idx = self._next_index()
            self._own("evals")
            for e in evals:
                e.modify_index = idx
                if e.create_index == 0:
                    e.create_index = idx
                self._evals[e.id] = e
        self._notify(["evals"], idx)
        return idx

    def delete_evals(self, eval_ids: List[str]) -> int:
        with self._lock:
            idx = self._next_index()
            self._own("evals")
            for eid in eval_ids:
                self._evals.pop(eid, None)
        self._notify(["evals"], idx)
        return idx

    def upsert_allocs(self, allocs: List[Allocation]) -> int:
        dep_touched = False
        with self._lock:
            idx = self._next_index()
            for a in allocs:
                dep_touched |= self._upsert_alloc_locked(a, idx)
        self._notify(["allocs", "deployment"] if dep_touched
                     else ["allocs"], idx)
        return idx

    def _upsert_alloc_locked(self, a: Allocation, idx: int) -> bool:
        """Returns True when the upsert also wrote a deployment row."""
        self._own("allocs", "allocs_by_job", "allocs_by_node",
                  "allocs_by_eval")
        existing = self._allocs.get(a.id)
        if existing is not None:
            # merge client-only fields if this is a server-side update
            a.create_index = existing.create_index
            if a.job is None:
                a.job = existing.job
        else:
            a.create_index = idx
        a.modify_index = idx
        self._allocs[a.id] = a
        self.usage.alloc_changed(existing, a)
        dep_touched = self._update_deployment_with_alloc_locked(
            existing, a, idx)
        for table, key in (
            (self._allocs_by_job, (a.namespace, a.job_id)),
            (self._allocs_by_node, a.node_id),
            (self._allocs_by_eval, a.eval_id),
        ):
            ids = table.get(key)
            if ids is None or a.id not in ids:
                # frozenset replacement, never in-place (snapshots share)
                table[key] = (ids or frozenset()) | {a.id}
        return dep_touched

    def update_allocs_from_client(self, allocs: List[Allocation]) -> int:
        """Client status updates (state_store.go UpdateAllocsFromClient)."""
        dep_touched = False
        with self._lock:
            idx = self._next_index()
            self._own("allocs")
            for update in allocs:
                existing = self._allocs.get(update.id)
                if existing is None:
                    continue
                new = existing.copy_skip_job()
                new.client_status = update.client_status
                new.client_description = update.client_description
                new.task_states = dict(update.task_states)
                if update.deployment_status is not None:
                    new.deployment_status = update.deployment_status
                if update.network_status is not None:
                    new.network_status = update.network_status
                new.modify_index = idx
                new.modify_time_ns = update.modify_time_ns
                self._allocs[new.id] = new
                self.usage.alloc_changed(existing, new)
                # health transitions roll up into the deployment
                # (state_store.go updateDeploymentWithAlloc)
                dep_touched |= self._update_deployment_with_alloc_locked(
                    existing, new, idx)
        self._notify(["allocs", "deployment"] if dep_touched
                     else ["allocs"], idx)
        return idx

    def _update_deployment_with_alloc_locked(
        self, old: Optional[Allocation], new: Allocation, idx: int
    ) -> bool:
        """Bump DeploymentState counters on placement/health changes
        (state_store.go updateDeploymentWithAlloc). Returns True when a
        deployment row was actually written — callers notify the
        "deployment" table only then, so the deployments watcher's
        index-gated early-out actually fires on deployment-less
        placement bursts (the common case)."""
        if not new.deployment_id:
            return False
        d = self._deployments.get(new.deployment_id)
        if d is None or not d.active():
            return False
        state = d.task_groups.get(new.task_group)
        if state is None:
            return False
        placed = 1 if old is None else 0
        old_h = old.deployment_status.healthy \
            if old is not None and old.deployment_status is not None else None
        new_h = new.deployment_status.healthy \
            if new.deployment_status is not None else None
        d_healthy = (1 if new_h is True else 0) - (1 if old_h is True else 0)
        d_unhealthy = (1 if new_h is False else 0) - (1 if old_h is False else 0)
        if not (placed or d_healthy or d_unhealthy):
            return False
        self._own("deployments")
        d = d.copy()
        state = d.task_groups[new.task_group]
        state.placed_allocs += placed
        state.healthy_allocs += d_healthy
        state.unhealthy_allocs += d_unhealthy
        d.modify_index = idx
        self._deployments[d.id] = d
        return True

    def update_allocs_desired_transition(self, transitions: Dict[str, object], evals: List[Evaluation]) -> int:
        """{alloc_id: DesiredTransition} -- drainer/operator migrate
        requests (state_store.go UpdateAllocsDesiredTransitions)."""
        with self._lock:
            idx = self._next_index()
            self._own("allocs", "evals")
            for alloc_id, transition in transitions.items():
                existing = self._allocs.get(alloc_id)
                if existing is None:
                    continue
                new = existing.copy_skip_job()
                new.desired_transition = transition
                new.modify_index = idx
                self._allocs[alloc_id] = new
                self.usage.alloc_changed(existing, new)
            for e in evals:
                e.modify_index = idx
                if e.create_index == 0:
                    e.create_index = idx
                self._evals[e.id] = e
        self._notify(["allocs", "evals"], idx)
        return idx

    def stop_alloc(self, alloc_id: str, evals: List[Evaluation]) -> int:
        """Mark one alloc desired=stop (`nomad alloc stop`;
        state_store.go UpdateAllocDesiredTransition + stop)."""
        with self._lock:
            idx = self._next_index()
            self._own("allocs", "evals")
            existing = self._allocs.get(alloc_id)
            if existing is not None:
                new = existing.copy_skip_job()
                new.desired_status = consts.ALLOC_DESIRED_STOP
                new.modify_index = idx
                self._allocs[alloc_id] = new
                self.usage.alloc_changed(existing, new)
            for e in evals:
                e.modify_index = idx
                if e.create_index == 0:
                    e.create_index = idx
                self._evals[e.id] = e
        self._notify(["allocs", "evals"], idx)
        return idx

    def upsert_deployment(self, d: Deployment) -> int:
        with self._lock:
            idx = self._next_index()
            self._own("deployments")
            d.modify_index = idx
            if d.create_index == 0:
                d.create_index = idx
            self._deployments[d.id] = d
        self._notify(["deployment"], idx)
        return idx

    def update_deployment_status(self, deployment_id: str, status: str, description: str = "") -> int:
        with self._lock:
            idx = self._next_index()
            self._own("deployments")
            d = self._deployments.get(deployment_id)
            if d is not None:
                d = d.copy()
                d.status = status
                d.status_description = description or d.status_description
                d.modify_index = idx
                self._deployments[deployment_id] = d
        self._notify(["deployment"], idx)
        return idx

    def delete_allocs(self, alloc_ids: List[str]) -> int:
        """GC path (state_store.go DeleteEval also reaps allocs; service
        registrations of reaped allocs go with them)."""
        with self._lock:
            idx = self._next_index()
            self._own("allocs", "allocs_by_job", "allocs_by_node",
                      "allocs_by_eval")
            doomed = set(alloc_ids)
            for aid in alloc_ids:
                a = self._allocs.pop(aid, None)
                if a is None:
                    continue
                self.usage.alloc_changed(a, None)
                for table, key in (
                    (self._allocs_by_job, (a.namespace, a.job_id)),
                    (self._allocs_by_node, a.node_id),
                    (self._allocs_by_eval, a.eval_id),
                ):
                    ids = table.get(key)
                    if ids and aid in ids:
                        remaining = ids - {aid}
                        if remaining:
                            table[key] = remaining
                        else:
                            del table[key]
            stale_regs = [r.id for r in self._services.values()
                          if r.alloc_id in doomed]
            for rid in stale_regs:
                del self._services[rid]
        self._notify(["allocs", "services"] if stale_regs else ["allocs"], idx)
        return idx

    def delete_deployments(self, deployment_ids: List[str]) -> int:
        with self._lock:
            idx = self._next_index()
            self._own("deployments")
            for did in deployment_ids:
                self._deployments.pop(did, None)
        self._notify(["deployment"], idx)
        return idx

    def update_deployment_alloc_health(
        self,
        deployment_id: str,
        healthy_ids: List[str],
        unhealthy_ids: List[str],
        deployment_update: Optional[Dict] = None,
        evals: Optional[List[Evaluation]] = None,
    ) -> int:
        """state_store.go UpdateDeploymentAllocHealth: record per-alloc
        deployment health and bump the DeploymentState counters."""
        from nomad_tpu.structs.alloc import AllocDeploymentStatus

        with self._lock:
            idx = self._next_index()
            self._own("deployments", "allocs", "evals")
            d = self._deployments.get(deployment_id)
            if d is not None:
                d = d.copy()
                for aid, healthy in [(i, True) for i in healthy_ids] + [
                    (i, False) for i in unhealthy_ids
                ]:
                    a = self._allocs.get(aid)
                    if a is None:
                        continue
                    new = a.copy_skip_job()
                    new.job = a.job
                    status = new.deployment_status or AllocDeploymentStatus()
                    was = status.healthy
                    status.healthy = healthy
                    status.modify_index = idx
                    new.deployment_status = status
                    new.modify_index = idx
                    self._allocs[aid] = new
                    self.usage.alloc_changed(a, new)
                    state = d.task_groups.get(new.task_group)
                    if state is not None and was != healthy:
                        if healthy:
                            state.healthy_allocs += 1
                            if was is False:
                                state.unhealthy_allocs -= 1
                        else:
                            state.unhealthy_allocs += 1
                            if was is True:
                                state.healthy_allocs -= 1
                d.modify_index = idx
                if deployment_update:
                    d.status = deployment_update.get("status", d.status)
                    d.status_description = deployment_update.get(
                        "status_description", d.status_description
                    )
                self._deployments[deployment_id] = d
            for e in evals or []:
                e.modify_index = idx
                if e.create_index == 0:
                    e.create_index = idx
                self._evals[e.id] = e
        self._notify(["allocs", "deployment", "evals"], idx)
        return idx

    def update_deployment_promotion(
        self, deployment_id: str, groups: Optional[List[str]] = None,
        evals: Optional[List[Evaluation]] = None,
    ) -> int:
        """state_store.go UpdateDeploymentPromotion: mark canaries
        promoted for all (or the given) groups."""
        with self._lock:
            idx = self._next_index()
            self._own("deployments", "evals")
            d = self._deployments.get(deployment_id)
            if d is not None:
                d = d.copy()
                for name, state in d.task_groups.items():
                    if groups is None or name in groups:
                        state.promoted = True
                d.modify_index = idx
                self._deployments[deployment_id] = d
            for e in evals or []:
                e.modify_index = idx
                if e.create_index == 0:
                    e.create_index = idx
                self._evals[e.id] = e
        self._notify(["deployment", "evals"], idx)
        return idx

    def set_scheduler_config(self, config: SchedulerConfiguration) -> int:
        with self._lock:
            idx = self._next_index()
            self.scheduler_config = config
        self._notify(["scheduler_config"], idx)
        return idx

    # --- plan application (FSM ApplyPlanResults, fsm.go applyPlanResults) ---

    def upsert_plan_results(
        self,
        alloc_index: int,
        plan: Plan,
        node_allocation: Dict[str, List[Allocation]],
        node_update: Dict[str, List[Allocation]],
        node_preemptions: Dict[str, List[Allocation]],
        deployment: Optional[Deployment] = None,
        deployment_updates: Optional[List[Dict]] = None,
    ) -> int:
        """Commit one (possibly partial) plan the applier validated."""
        return self.upsert_plan_results_batch(alloc_index, [{
            "plan": plan,
            "node_allocation": node_allocation,
            "node_update": node_update,
            "node_preemptions": node_preemptions,
            "deployment": deployment,
            "deployment_updates": deployment_updates,
        }])

    def upsert_plan_results_batch(self, alloc_index: int,
                                  plans: List[Dict]) -> int:
        """Commit a batch of evaluated plans as ONE index bump / one
        watcher notification (the applier merges a burst of plans into
        one raft entry; fsm.go applyPlanResults semantics per plan,
        applied in batch order)."""
        dep_touched = False
        with self._lock:
            idx = self._next_index()
            self._own("deployments")
            for p in plans:
                plan = p["plan"]
                for allocs in p["node_update"].values():
                    for a in allocs:
                        dep_touched |= self._upsert_alloc_locked(a, idx)
                for allocs in p["node_preemptions"].values():
                    for a in allocs:
                        dep_touched |= self._upsert_alloc_locked(a, idx)
                for allocs in p["node_allocation"].values():
                    for a in allocs:
                        if a.job is None:
                            a.job = plan.job
                        dep_touched |= self._upsert_alloc_locked(a, idx)
                deployment = p.get("deployment")
                if deployment is not None:
                    deployment.modify_index = idx
                    if deployment.create_index == 0:
                        deployment.create_index = idx
                    self._deployments[deployment.id] = deployment
                    dep_touched = True
                for du in p.get("deployment_updates") or []:
                    d = self._deployments.get(du.get("deployment_id"))
                    if d is not None:
                        d = d.copy()
                        d.status = du.get("status", d.status)
                        d.status_description = du.get(
                            "status_description", d.status_description)
                        d.modify_index = idx
                        self._deployments[d.id] = d
                        dep_touched = True
        # notify "deployment" only when a row actually changed: the
        # deployments watcher's idle gate keys on this index, and a
        # deployment-less placement burst (the common case) must not
        # defeat it by bumping the index on every plan commit
        self._notify(["allocs", "deployment"] if dep_touched
                     else ["allocs"], idx)
        return idx


def _job_status(job) -> str:
    if job.stop:
        return consts.JOB_STATUS_DEAD
    return consts.JOB_STATUS_PENDING
