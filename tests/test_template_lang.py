"""Template language: the Go text/template subset consul-template
embeds (client/allocrunner/taskrunner/template/template.go).

Conditionals, ranges over lists and maps, with-blocks, variables,
pipelines, and the ls/service data sources — the features beyond bare
interpolation that the reference's jobs routinely use for config
files (e.g. ranging over service instances into an upstream list).
"""

import pytest

from nomad_tpu.client.template import (
    MissingKeyError,
    TemplateContext,
    TemplateSyntaxError,
    render,
    uses_live_data,
    uses_vault,
)


def ctx(**kw):
    kv = kw.pop("kv", {})
    services = kw.pop("services", {})
    return TemplateContext(
        kv_get=kv.get,
        kv_ls=lambda p: sorted((k, v) for k, v in kv.items()
                               if k.startswith(p)),
        services_get=lambda n: services.get(n, []),
        **kw,
    )


class TestConditionals:
    def test_if_else(self):
        c = ctx(env={"MODE": "prod"})
        t = '{{ if env "MODE" }}mode={{ env "MODE" }}{{ else }}dev{{ end }}'
        assert render(t, c) == "mode=prod"
        assert render(t, ctx(env={})) == "dev"

    def test_else_if_chain(self):
        t = ('{{ if env "A" }}a{{ else if env "B" }}b'
             '{{ else }}neither{{ end }}')
        assert render(t, ctx(env={"A": "1"})) == "a"
        assert render(t, ctx(env={"B": "1"})) == "b"
        assert render(t, ctx(env={})) == "neither"

    def test_keyordefault_truthiness(self):
        t = ('{{ if keyOrDefault "feature" "" }}on{{ else }}off{{ end }}')
        assert render(t, ctx(kv={"feature": "yes"})) == "on"
        assert render(t, ctx(kv={})) == "off"


class TestRange:
    def test_range_services_into_upstreams(self):
        """The canonical consul-template use: render a backend list."""
        c = ctx(services={"api": [
            {"Name": "api", "Address": "10.0.0.1", "Port": 8080},
            {"Name": "api", "Address": "10.0.0.2", "Port": 8081},
        ]})
        t = ('{{ range service "api" }}'
             'server {{ .Address }}:{{ .Port }};\n'
             '{{ end }}')
        assert render(t, c) == \
            "server 10.0.0.1:8080;\nserver 10.0.0.2:8081;\n"

    def test_range_ls_pairs(self):
        c = ctx(kv={"app/config/db": "pg", "app/config/cache": "redis",
                    "app/other": "x"})
        t = '{{ range ls "app/config" }}{{ .Key }}={{ .Value }} {{ end }}'
        assert render(t, c) == "cache=redis db=pg "

    def test_range_with_vars_and_else(self):
        c = ctx(services={"api": [{"Port": 1}, {"Port": 2}]})
        t = ('{{ range $i, $s := service "api" }}'
             '[{{ $i }}]={{ $s.Port }} {{ end }}')
        assert render(t, c) == "[0]=1 [1]=2 "
        t2 = '{{ range service "gone" }}x{{ else }}no instances{{ end }}'
        assert render(t2, c) == "no instances"

    def test_range_over_secret_map(self):
        c = TemplateContext(
            secret_get=lambda p: {"user": "u1", "pass": "p1"}
            if p == "db/creds" else None)
        t = ('{{ range $k, $v := secret "db/creds" }}'
             '{{ $k }}={{ $v }};{{ end }}')
        assert render(t, c) == "pass=p1;user=u1;"


class TestWithAndVars:
    def test_with_rebinds_dot(self):
        c = TemplateContext(secret_get=lambda p: {"addr": "db:5432"})
        t = ('{{ with secret "db" }}addr={{ .addr }}{{ else }}none'
             '{{ end }}')
        assert render(t, c) == "addr=db:5432"
        assert render(t, TemplateContext()) == "none"

    def test_variable_assignment(self):
        c = ctx(kv={"a": "hello"})
        t = '{{ $x := key "a" }}{{ $x }}-{{ $x | toUpper }}'
        assert render(t, c) == "hello-HELLO"

    def test_pipeline_functions(self):
        c = ctx(kv={"a": "  Mixed Case  "})
        assert render('{{ key "a" | trimSpace | toLower }}', c) == \
            "mixed case"


class TestErrorsAndStrict:
    def test_strict_missing_key_raises(self):
        with pytest.raises(MissingKeyError):
            render('{{ key "nope" }}', ctx(kv={}), strict=True)
        assert render('{{ key "nope" }}', ctx(kv={})) == ""

    def test_unterminated_block_is_syntax_error(self):
        with pytest.raises(TemplateSyntaxError):
            render('{{ if env "A" }}never closed', ctx(env={}))

    def test_unknown_function_is_syntax_error(self):
        with pytest.raises(TemplateSyntaxError):
            render("{{ frobnicate }}", ctx())


class TestDetection:
    def test_uses_live_data_sees_control_flow_sources(self):
        assert uses_live_data('{{ range service "api" }}{{ end }}')
        assert uses_live_data('{{ range ls "p" }}{{ end }}')
        assert uses_live_data('{{ if key "a" }}x{{ end }}')
        assert not uses_live_data('{{ env "HOME" }}')

    def test_uses_vault(self):
        assert uses_vault('{{ with secret "a" }}{{ end }}')
        assert not uses_vault('{{ key "a" }}')


class TestEndToEnd:
    def test_rendered_config_through_live_task(self):
        """A template with range/if over live KV renders into the task
        dir and re-renders when KV changes (change_mode analog covered
        by test_secrets)."""
        import os
        import sys
        import time

        from nomad_tpu import mock
        from nomad_tpu.api.agent import Agent, AgentConfig
        from nomad_tpu.structs.job import Template

        agent = Agent(AgentConfig.dev())
        agent.start()
        try:
            agent.server.consul.kv_put("backends/one", "10.1.1.1:80")
            agent.server.consul.kv_put("backends/two", "10.2.2.2:80")
            job = mock.simple_job(id="tmpl-lang-job")
            tg = job.task_groups[0]
            tg.count = 1
            task = tg.tasks[0]
            task.driver = "raw_exec"
            task.config = {"command": sys.executable,
                           "args": ["-S", "-c",
                                    "import time; time.sleep(300)"]}
            task.templates = [Template(
                embedded_tmpl=(
                    '{{ range ls "backends" }}'
                    "server {{ .Key }} {{ .Value }}\n"
                    "{{ end }}"
                    '{{ if keyOrDefault "tls" "" }}tls on{{ else }}'
                    "tls off{{ end }}\n"),
                dest_path="local/backends.conf",
            )]
            agent.server.job_register(job)
            deadline = time.time() + 60
            rendered = None
            while time.time() < deadline:
                snap = agent.server.state.snapshot()
                allocs = snap.allocs_by_job(job.namespace, job.id)
                if allocs:
                    ar = agent.client.allocs.get(allocs[0].id)
                    if ar:
                        p = os.path.join(ar.alloc_dir, task.name,
                                         "local", "backends.conf")
                        if os.path.exists(p):
                            rendered = open(p).read()
                            break
                time.sleep(0.2)
            assert rendered == ("server one 10.1.1.1:80\n"
                                "server two 10.2.2.2:80\n"
                                "tls off\n")
        finally:
            agent.shutdown()


class TestReviewEdges:
    def test_trim_markers(self):
        c = ctx(services={"api": [{"Address": "a", "Port": 1},
                                  {"Address": "b", "Port": 2}]})
        t = ('{{- range service "api" }}\n'
             '{{ .Address }}:{{ .Port }}\n'
             '{{- end }}\n')
        assert render(t, c) == "\na:1\nb:2\n"

    def test_ls_prefix_path_boundary(self):
        c = ctx(kv={"app/x": "1", "apple": "2"})
        t = '{{ range ls "app" }}{{ .Key }}={{ .Value }} {{ end }}'
        assert render(t, c) == "x=1 "

    def test_literals_do_not_classify_as_vault_or_live(self):
        # a Consul key literally named secret/... is not a Vault read
        assert not uses_vault('{{ key "secret/db" }}')
        assert uses_vault('{{ with secret "db" }}{{ end }}')
        # env/meta with suspicious literal names are not live
        assert not uses_live_data('{{ env "key" }}')
        assert not uses_live_data('{{ meta "service" }}')
        assert uses_live_data('{{ key "a" }}')

    def test_wrong_arity_is_syntax_error(self):
        with pytest.raises(TemplateSyntaxError):
            render("{{ key }}", ctx())
        with pytest.raises(TemplateSyntaxError):
            render('{{ env "A" "B" }}', ctx())

    def test_service_change_bumps_live_index(self):
        """Templates ranging over service() must re-render when
        instances register: the watcher's poll index moves on service
        registration changes."""
        from nomad_tpu.api.agent import Agent, AgentConfig
        from nomad_tpu.structs.services import ServiceRegistration

        agent = Agent(AgentConfig.dev())
        agent.start()
        try:
            secrets = agent.client.secrets
            before = secrets.live_data_index()
            agent.server.service_register([ServiceRegistration(
                id="tmpl-svc-1", service_name="api", namespace="default",
                node_id="n1", alloc_id="a1", address="10.0.0.9",
                port=8080)])
            assert secrets.live_data_index() > before
            assert any(s["Port"] == 8080
                       for s in secrets.services("default", "api"))
        finally:
            agent.shutdown()

    def test_service_template_rerenders_on_registration(self):
        """End to end: a template ranging over service() re-renders
        (through the live watcher) when a new instance registers."""
        import os
        import sys
        import time

        from nomad_tpu import mock
        from nomad_tpu.api.agent import Agent, AgentConfig
        from nomad_tpu.structs.job import Template
        from nomad_tpu.structs.services import ServiceRegistration

        agent = Agent(AgentConfig.dev())
        agent.start()
        try:
            agent.server.service_register([ServiceRegistration(
                id="svc-tmpl-0", service_name="backend",
                namespace="default", node_id="n1", alloc_id="a0",
                address="10.0.0.1", port=8080)])
            job = mock.simple_job(id="svc-tmpl-job")
            tg = job.task_groups[0]
            tg.count = 1
            task = tg.tasks[0]
            task.driver = "raw_exec"
            task.config = {"command": sys.executable,
                           "args": ["-S", "-c",
                                    "import time; time.sleep(300)"]}
            task.templates = [Template(
                embedded_tmpl=('{{ range service "backend" }}'
                               "up {{ .Address }}:{{ .Port }}\n"
                               "{{ end }}"),
                dest_path="local/upstreams.conf", change_mode="noop")]
            agent.server.job_register(job)

            def rendered():
                snap = agent.server.state.snapshot()
                allocs = snap.allocs_by_job(job.namespace, job.id)
                if not allocs:
                    return None
                ar = agent.client.allocs.get(allocs[0].id)
                if not ar:
                    return None
                p = os.path.join(ar.alloc_dir, task.name, "local",
                                 "upstreams.conf")
                return open(p).read() if os.path.exists(p) else None

            deadline = time.time() + 60
            while time.time() < deadline and rendered() is None:
                time.sleep(0.2)
            assert rendered() == "up 10.0.0.1:8080\n"

            # a NEW instance registers: the watcher re-renders
            agent.server.service_register([ServiceRegistration(
                id="svc-tmpl-1", service_name="backend",
                namespace="default", node_id="n2", alloc_id="a1",
                address="10.0.0.2", port=8081)])
            deadline = time.time() + 30
            while time.time() < deadline and \
                    (rendered() or "").count("up ") < 2:
                time.sleep(0.2)
            assert rendered() == ("up 10.0.0.1:8080\n"
                                  "up 10.0.0.2:8081\n")
        finally:
            agent.shutdown()
