"""Server runtime tests: broker, blocked evals, planner, workers.

Modeled on reference nomad/eval_broker_test.go, blocked_evals_test.go,
plan_apply_test.go, worker_test.go, and the in-process TestServer
pattern (nomad/testing.go:41).
"""

import time

import pytest

from nomad_tpu import mock, structs
from nomad_tpu.server import fsm as fsm_msgs
from nomad_tpu.server.blocked_evals import BlockedEvals
from nomad_tpu.server.eval_broker import FAILED_QUEUE, EvalBroker
from nomad_tpu.server.plan_apply import Planner
from nomad_tpu.server.plan_queue import PlanQueue
from nomad_tpu.server.server import Server, ServerConfig
from nomad_tpu.server.worker import Worker
from nomad_tpu.structs import consts
from nomad_tpu.structs.eval_plan import Evaluation, Plan


def make_eval(**kw):
    defaults = dict(
        type=consts.JOB_TYPE_SERVICE,
        job_id="job-1",
        namespace="default",
        priority=50,
        status=consts.EVAL_STATUS_PENDING,
    )
    defaults.update(kw)
    return Evaluation(**defaults)


def make_broker(**kw):
    kw.setdefault("nack_timeout", 5.0)
    b = EvalBroker(**kw)
    b.set_enabled(True)
    return b


class TestEvalBroker:
    def test_enqueue_dequeue_ack(self):
        # eval_broker_test.go TestEvalBroker_Enqueue_Dequeue_Nack_Ack
        b = make_broker()
        ev = make_eval()
        b.enqueue(ev)
        assert b.stats()["total_ready"] == 1
        out, token = b.dequeue([consts.JOB_TYPE_SERVICE], timeout=1)
        assert out.id == ev.id
        assert b.stats()["total_unacked"] == 1
        b.ack(ev.id, token)
        assert b.stats()["total_ready"] == 0
        assert b.stats()["total_unacked"] == 0

    def test_priority_ordering(self):
        b = make_broker()
        low = make_eval(priority=20, job_id="low")
        high = make_eval(priority=90, job_id="high")
        b.enqueue(low)
        b.enqueue(high)
        out, _ = b.dequeue([consts.JOB_TYPE_SERVICE], timeout=1)
        assert out.id == high.id

    def test_scheduler_type_filter(self):
        b = make_broker()
        b.enqueue(make_eval(type=consts.JOB_TYPE_BATCH, job_id="b"))
        out, _ = b.dequeue([consts.JOB_TYPE_SERVICE], timeout=0)
        assert out is None
        out, _ = b.dequeue([consts.JOB_TYPE_BATCH], timeout=1)
        assert out is not None

    def test_job_dedup_pending_promoted_on_ack(self):
        # eval_broker_test.go TestEvalBroker_Enqueue_Disable / pending
        b = make_broker()
        first = make_eval(job_id="j")
        second = make_eval(job_id="j", priority=70)
        b.enqueue(first)
        b.enqueue(second)
        # only one outstanding per job
        assert b.stats()["total_ready"] == 1
        assert b.stats()["total_pending"] == 1
        out, token = b.dequeue([consts.JOB_TYPE_SERVICE], timeout=1)
        assert out.id == first.id
        b.ack(first.id, token)
        out2, token2 = b.dequeue([consts.JOB_TYPE_SERVICE], timeout=1)
        assert out2.id == second.id
        b.ack(second.id, token2)

    def test_nack_requeues_then_fails(self):
        b = make_broker(
            delivery_limit=2, initial_nack_delay=0.0, subsequent_nack_delay=0.0
        )
        ev = make_eval()
        b.enqueue(ev)
        out, token = b.dequeue([consts.JOB_TYPE_SERVICE], timeout=1)
        b.nack(ev.id, token)
        out, token = b.dequeue([consts.JOB_TYPE_SERVICE], timeout=1)
        assert out.id == ev.id
        b.nack(ev.id, token)
        # delivery limit reached -> failed queue
        out, token = b.dequeue([FAILED_QUEUE], timeout=1)
        assert out.id == ev.id

    def test_token_mismatch_rejected(self):
        b = make_broker()
        ev = make_eval()
        b.enqueue(ev)
        out, token = b.dequeue([consts.JOB_TYPE_SERVICE], timeout=1)
        with pytest.raises(ValueError):
            b.ack(ev.id, "wrong-token")

    def test_delayed_eval(self):
        b = make_broker()
        ev = make_eval(wait_until_s=time.time() + 0.15)
        b.enqueue(ev)
        assert b.stats()["delayed_evals"] == 1
        out, _ = b.dequeue([consts.JOB_TYPE_SERVICE], timeout=0)
        assert out is None
        out, token = b.dequeue([consts.JOB_TYPE_SERVICE], timeout=2)
        assert out is not None and out.id == ev.id

    def test_disabled_drops(self):
        b = EvalBroker()
        b.enqueue(make_eval())
        assert b.stats()["total_ready"] == 0

    def test_dequeue_batch(self):
        b = make_broker()
        for i in range(5):
            b.enqueue(make_eval(job_id=f"j{i}"))
        batch = b.dequeue_batch([consts.JOB_TYPE_SERVICE], 3, timeout=1)
        assert len(batch) == 3
        for ev, token in batch:
            b.ack(ev.id, token)


class TestBlockedEvals:
    def make(self):
        released = []
        be = BlockedEvals(released.append)
        be.set_enabled(True)
        return be, released

    def test_block_unblock_class(self):
        be, released = self.make()
        ev = make_eval(status=consts.EVAL_STATUS_BLOCKED, snapshot_index=5)
        ev.class_eligibility = {"class-a": True}
        be.block(ev)
        assert be.stats()["total_blocked"] == 1
        n = be.unblock("class-a", index=10)
        assert n == 1
        assert released == [ev]
        assert be.stats()["total_blocked"] == 0

    def test_ineligible_class_not_unblocked(self):
        be, released = self.make()
        ev = make_eval(status=consts.EVAL_STATUS_BLOCKED, snapshot_index=5)
        ev.class_eligibility = {"class-a": False}
        be.block(ev)
        assert be.unblock("class-a", index=10) == 0
        # unseen class: optimistically unblock
        assert be.unblock("class-b", index=11) == 1

    def test_escaped_unblocks_on_any_change(self):
        be, released = self.make()
        ev = make_eval(status=consts.EVAL_STATUS_BLOCKED, snapshot_index=5)
        ev.escaped_computed_class = True
        be.block(ev)
        assert be.stats()["total_escaped"] == 1
        assert be.unblock("whatever", index=9) == 1

    def test_duplicate_per_job(self):
        be, released = self.make()
        first = make_eval(status=consts.EVAL_STATUS_BLOCKED, job_id="j")
        second = make_eval(status=consts.EVAL_STATUS_BLOCKED, job_id="j")
        be.block(first)
        be.block(second)
        assert be.stats()["total_blocked"] == 1
        dups = be.get_duplicates(timeout=0)
        assert dups == [first]

    def test_missed_unblock(self):
        # capacity changed after the scheduler snapshot but before Block
        be, released = self.make()
        be.unblock("class-a", index=100)
        ev = make_eval(status=consts.EVAL_STATUS_BLOCKED, snapshot_index=50)
        be.block(ev)
        assert released == [ev]
        assert be.stats()["total_blocked"] == 0


class TestPlanApply:
    def test_apply_commits_allocs(self):
        server = Server(ServerConfig(num_workers=0))
        node = mock.node()
        server.state.upsert_node(node)
        job = mock.job()
        alloc = mock.alloc(node_id=node.id, job=job)
        plan = Plan(priority=50, job=job, node_allocation={node.id: [alloc]})
        result = server.planner.apply_one(plan)
        assert result.refresh_index == 0
        assert server.state.snapshot().alloc_by_id(alloc.id) is not None

    def test_overcommit_rejected_partial(self):
        # plan_apply_test.go TestPlanApply_EvalPlan_Partial
        server = Server(ServerConfig(num_workers=0))
        node = mock.node()
        server.state.upsert_node(node)
        job = mock.job()
        good = mock.alloc(node_id=node.id, job=job)
        # a second node that does not exist -> that node's placements drop
        bad = mock.alloc(node_id="missing-node", job=job)
        plan = Plan(
            priority=50, job=job,
            node_allocation={node.id: [good], "missing-node": [bad]},
        )
        result = server.planner.apply_one(plan)
        assert node.id in result.node_allocation
        assert "missing-node" not in result.node_allocation
        assert result.refresh_index > 0

    def test_down_node_rejected(self):
        server = Server(ServerConfig(num_workers=0))
        node = mock.node(status=consts.NODE_STATUS_DOWN)
        server.state.upsert_node(node)
        job = mock.job()
        alloc = mock.alloc(node_id=node.id, job=job)
        plan = Plan(priority=50, job=job, node_allocation={node.id: [alloc]})
        result = server.planner.apply_one(plan)
        assert not result.node_allocation
        assert result.refresh_index > 0

    def test_pipelined_applier_overlaps_and_stays_correct(self):
        """plan_apply.go:159-184: while plan N's raft apply is in
        flight, plan N+1 is evaluated against an optimistic overlay of
        N's results — so a conflicting N+1 is rejected even though N
        hasn't committed yet, and wall-clock shows the overlap."""
        import threading
        import time as _time

        server = Server(ServerConfig(num_workers=0))
        node = mock.node()
        server.state.upsert_node(node)
        job = mock.job()

        # slow down the commit path to force evaluation overlap
        applied = []
        orig = server.planner._commit
        def slow_commit(plan, result):
            _time.sleep(0.15)
            applied.append(_time.perf_counter())
            return orig(plan, result)
        server.planner._commit = slow_commit
        server.plan_queue.set_enabled(True)
        server.planner.start()
        try:
            # plan A fills most of the node; conflicting plan B's ask
            # only fits if A's placements are invisible
            big = mock.alloc(node_id=node.id, job=job)
            big.allocated_resources.tasks["web"].cpu.cpu_shares = 3000
            conflict = mock.alloc(node_id=node.id, job=job)
            conflict.allocated_resources.tasks["web"].cpu.cpu_shares = 3000
            plan_a = Plan(priority=50, job=job,
                          node_allocation={node.id: [big]})
            plan_b = Plan(priority=50, job=job,
                          node_allocation={node.id: [conflict]})

            results = {}
            def submit(name, plan):
                results[name] = server.submit_plan(plan)
            ta = threading.Thread(target=submit, args=("a", plan_a))
            ta.start()
            _time.sleep(0.03)  # let A start its slow commit
            tb = threading.Thread(target=submit, args=("b", plan_b))
            tb.start()
            ta.join(5)
            tb.join(5)
            # A committed; B was rejected against the overlay (node
            # can't fit both 3000 MHz asks)
            assert node.id in results["a"].node_allocation
            assert node.id not in results["b"].node_allocation
            assert results["b"].refresh_index >= results["a"].alloc_index
            snap = server.state.snapshot()
            assert snap.alloc_by_id(big.id) is not None
            assert snap.alloc_by_id(conflict.id) is None
        finally:
            server.planner.stop()


class TestServerEndToEnd:
    def make_server(self, n_nodes=5, **cfg):
        cfg.setdefault("num_workers", 2)
        cfg.setdefault("heartbeat_ttl", 60.0)
        server = Server(ServerConfig(**cfg))
        server.start()
        for _ in range(n_nodes):
            server.node_register(mock.node())
        return server

    def wait_for(self, fn, timeout=10.0, msg="condition", server=None):
        deadline = time.time() + timeout
        while time.time() < deadline:
            if fn():
                return
            time.sleep(0.02)
        detail = ""
        if server is not None:
            errors = [w.last_error for w in server.workers if w.last_error]
            if errors:
                detail = f"; worker errors: {errors}"
        raise AssertionError(f"timeout waiting for {msg}{detail}")

    def test_job_register_places_allocs(self):
        server = self.make_server()
        try:
            job = mock.job()
            resp = server.job_register(job)
            assert resp["eval_id"]
            self.wait_for(
                lambda: len([
                    a for a in server.state.snapshot().allocs_by_job(
                        job.namespace, job.id)
                    if a.desired_status == consts.ALLOC_DESIRED_RUN
                ]) == 10,
                msg="10 allocs placed",
            )
            # the eval's COMPLETE status lands via a separate raft
            # apply moments after the plan commit that made the allocs
            # visible — wait for it rather than racing it
            self.wait_for(
                lambda: server.state.snapshot().eval_by_id(
                    resp["eval_id"]).status == consts.EVAL_STATUS_COMPLETE,
                msg="eval marked complete",
                server=server,
            )
        finally:
            server.shutdown()

    def test_exhausted_job_blocks_then_unblocks(self):
        server = self.make_server(n_nodes=1)
        try:
            job = mock.job()
            # each mock node fits at most 7 tasks (3900 MHz usable / 500)
            job.task_groups[0].count = 20
            server.job_register(job)
            self.wait_for(
                lambda: server.blocked_evals.stats()["total_blocked"] == 1,
                # a loaded suite process can stretch one scheduling
                # pass past the default 10s
                timeout=30.0,
                msg="blocked eval created",
                server=server,
            )
            placed = len(server.state.snapshot().allocs_by_job(job.namespace, job.id))
            assert placed < 20
            # capacity arrives: blocked eval unblocks and placement finishes
            for _ in range(4):
                server.node_register(mock.node())
            self.wait_for(
                lambda: len([
                    a for a in server.state.snapshot().allocs_by_job(
                        job.namespace, job.id)
                    if a.desired_status == consts.ALLOC_DESIRED_RUN
                ]) == 20,
                msg="all 20 allocs placed after unblock",
            )
        finally:
            server.shutdown()

    def test_job_deregister_stops_allocs(self):
        server = self.make_server()
        try:
            job = mock.job()
            server.job_register(job)
            self.wait_for(
                lambda: len(server.state.snapshot().allocs_by_job(
                    job.namespace, job.id)) == 10,
                msg="allocs placed",
            )
            server.job_deregister(job.namespace, job.id)
            self.wait_for(
                lambda: all(
                    a.desired_status == consts.ALLOC_DESIRED_STOP
                    for a in server.state.snapshot().allocs_by_job(
                        job.namespace, job.id)
                ),
                msg="allocs stopped",
            )
        finally:
            server.shutdown()

    def test_heartbeat_expiry_marks_node_down(self):
        server = Server(ServerConfig(num_workers=2, heartbeat_ttl=0.2))
        server.start()
        try:
            node = mock.node()
            server.node_register(node)
            self.wait_for(
                lambda: server.state.snapshot().node_by_id(node.id).status
                == consts.NODE_STATUS_DOWN,
                timeout=5,
                msg="node down after missed heartbeat",
            )
        finally:
            server.shutdown()

    def test_heartbeat_keeps_node_alive(self):
        server = Server(ServerConfig(num_workers=0, heartbeat_ttl=0.3))
        server.start()
        try:
            node = mock.node()
            server.node_register(node)
            for _ in range(4):
                time.sleep(0.1)
                server.node_heartbeat(node.id, consts.NODE_STATUS_READY)
            assert (
                server.state.snapshot().node_by_id(node.id).status
                == consts.NODE_STATUS_READY
            )
        finally:
            server.shutdown()

    def test_failed_eval_reaped_with_follow_up(self):
        server = Server(
            ServerConfig(num_workers=0, eval_delivery_limit=1)
        )
        server.eval_broker.initial_nack_delay = 0.0
        server.eval_broker.subsequent_nack_delay = 0.0
        server.start()
        try:
            ev = make_eval()
            server.raft_apply(fsm_msgs.EVAL_UPDATE, {"evals": [ev]})
            out, token = server.eval_broker.dequeue(
                [consts.JOB_TYPE_SERVICE], timeout=1
            )
            server.eval_broker.nack(out.id, token)
            self.wait_for(
                lambda: server.state.snapshot().eval_by_id(ev.id).status
                == consts.EVAL_STATUS_FAILED,
                msg="failed eval reaped",
            )
            follow_ups = [
                e for e in server.state.snapshot().evals_iter()
                if e.triggered_by == consts.EVAL_TRIGGER_FAILED_FOLLOW_UP
            ]
            assert len(follow_ups) == 1
        finally:
            server.shutdown()
