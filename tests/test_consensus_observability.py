"""Consensus-plane observability (ISSUE 15).

Covers the acceptance surface directly:
- waterfall raft segments partition the commit window exactly (no
  overlap, no double-claim against the applier batch envelope) across
  randomized wave/failover interleavings
- per-server metric attribution: two in-process servers'
  ``nomad_tpu_raft_*`` series are distinguishable (the make_cluster
  blending regression)
- exporter label hygiene: quotes/backslashes/newlines in label values
  survive exposition line-framing
- /v1/operator/cluster-health shape + ACL; /v1/operator/slow-raft
- the timeline builder: phase attribution, index-pinned causal order,
  artifact merging
- the tier-1 mini-timeline smoke: a single-server chaos smoke emits a
  valid CHAOS_TIMELINE with >= 0.90 failover attribution AND e2e
  waterfalls carrying the raft segments at >= 0.90 coverage
"""

import json
import os
import random
import sys
import urllib.error
import urllib.request

import pytest

from nomad_tpu import telemetry
from nomad_tpu.telemetry.exporter import (
    cluster_health_json,
    prometheus_text,
    slow_raft_json,
    _esc,
)
from nomad_tpu.telemetry.histogram import histograms
from nomad_tpu.telemetry.timeline import (
    build_timeline,
    merge_into_artifact,
    validate_timeline,
)
from nomad_tpu.telemetry.trace import ConsensusRecorder, Span, tracer
from nomad_tpu.telemetry.waterfall import SEGMENT_ORDER, build_waterfall

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "bench"))


def _span(name, trace_id, start, dur, thread="t"):
    return Span(name, trace_id, 0, 0, start, dur, 0.0, 0.0, 0.0, thread)


def _get(addr: str, path: str, token: str = ""):
    req = urllib.request.Request(addr + path)
    if token:
        req.add_header("X-Nomad-Token", token)
    with urllib.request.urlopen(req, timeout=10) as resp:
        return resp.status, resp.headers, resp.read()


class TestWaterfallRaftPartition:
    """Satellite: the raft segments partition the commit window
    exactly — greedy-interval claims, leaf-out priorities, no
    double-claim against the applier batch envelope."""

    def test_exact_partition_of_commit_window(self):
        trace = [
            _span("eval.e2e", "ev1", 0.0, 10.0),
            _span("plan.wait", "ev1", 0.0, 10.0),
        ]
        global_spans = [
            _span("plan.commit", "", 0.0, 10.0),
            _span("raft.fsync", "", 0.5, 1.5),       # [0.5, 2.0)
            _span("raft.replicate", "", 1.0, 2.0),   # [1.0, 3.0)
            _span("raft.quorum", "", 0.0, 5.5),      # [0.0, 5.5)
            _span("raft.apply", "", 5.5, 3.0),       # [5.5, 8.5)
            _span("fsm.apply", "", 6.0, 2.0),        # [6.0, 8.0)
        ]
        wf = build_waterfall(trace, global_spans)
        segs = wf["segments"]
        assert segs["raft-fsync"] == pytest.approx(1.5)
        # replicate keeps only what fsync left: [2.0, 3.0)
        assert segs["raft-replicate"] == pytest.approx(1.0)
        # quorum is the append->commit residue: [0, 0.5) + [3.0, 5.5)
        assert segs["raft-quorum"] == pytest.approx(3.0)
        assert segs["fsm"] == pytest.approx(2.0)
        # raft-apply is the dispatch residue around fsm (leaf-out)
        assert segs["raft-apply"] == pytest.approx(1.0)
        # commit keeps only what raft left: [8.5, 10.0)
        assert segs["commit"] == pytest.approx(1.5)
        assert sum(segs.values()) == pytest.approx(10.0)
        assert wf["coverage"] == pytest.approx(1.0)

    def test_random_interleavings_never_overlap_or_overclaim(self):
        """Property: across randomized wave/failover interleavings the
        claimed segments always partition the e2e window (sum ==
        e2e_s including ``other``; coverage <= 1)."""
        for seed in range(50):
            rng = random.Random(seed)
            n_evals = rng.randint(1, 4)
            global_spans = []
            # a wave's applier envelopes + raft spans, overlapping
            # arbitrary eval windows (failover = gaps + repeats)
            for _ in range(rng.randint(1, 3)):
                base = rng.uniform(0, 8)
                width = rng.uniform(0.5, 6)
                global_spans.append(
                    _span("plan.commit", "", base, width))
                for name in ("raft.fsync", "raft.replicate",
                             "raft.quorum", "raft.apply", "fsm.apply",
                             "plan.evaluate"):
                    if rng.random() < 0.8:
                        s = base + rng.uniform(-0.5, width)
                        global_spans.append(_span(
                            name, "", s, rng.uniform(0.1, width)))
            for i in range(n_evals):
                a = rng.uniform(0, 4)
                b = a + rng.uniform(1, 8)
                trace = [
                    _span("eval.e2e", f"ev{i}", a, b - a),
                    _span("eval.schedule", f"ev{i}", a + 0.1,
                          rng.uniform(0.1, 1.0)),
                    _span("plan.wait", f"ev{i}",
                          rng.uniform(a, b - 0.5), rng.uniform(0.2, 4)),
                ]
                wf = build_waterfall(trace, global_spans)
                assert wf is not None
                total = sum(wf["segments"].values())
                assert total == pytest.approx(wf["e2e_s"], abs=1e-9), \
                    (seed, i, wf)
                assert wf["coverage"] <= 1.0 + 1e-9, (seed, i, wf)
                assert wf["covered_s"] == pytest.approx(
                    wf["e2e_s"] - wf["segments"].get("other", 0.0),
                    abs=1e-9)
                for seg in wf["segments"]:
                    assert seg in SEGMENT_ORDER, seg


class TestPerServerSeries:
    """Satellite: two in-process servers' raft series must be
    distinguishable (the process-global blending regression)."""

    def test_cluster_servers_report_distinct_raft_series(self):
        from nomad_tpu.server.server import ServerConfig
        from nomad_tpu.server.testing import make_cluster, wait_for_leader

        servers, registry = make_cluster(3, ServerConfig(
            num_workers=0, heartbeat_ttl=60.0))
        try:
            leader = wait_for_leader(servers, timeout=10.0)
            leader.raft.barrier()
            text = prometheus_text()
            for sid in ("server-0", "server-1", "server-2"):
                assert f'nomad_tpu_raft_term{{server_id="{sid}"}}' \
                    in text, text[:400]
            # exactly one of the three reports leadership
            leaders = [
                line for line in text.splitlines()
                if line.startswith("nomad_tpu_raft_is_leader")
                and line.endswith(" 1")
            ]
            assert len(leaders) == 1
            # leader-side per-peer lag series carry (server_id, peer)
            lid = leader.raft.id
            assert f'nomad_tpu_raft_peer_lag_entries{{server_id="{lid}"' \
                in text
        finally:
            for s in servers:
                s.shutdown()

    def test_append_stamps_survive_until_slowest_peer_acks(self):
        """Review regression: pruning stamps at MAJORITY commit
        dropped the laggard's — its later ack found no stamp (no
        replication-lag sample) and cluster_health reported LagMs 0.0
        for the one peer actually behind. Stamps must live until
        EVERY peer has acked them."""
        import time as _time

        from nomad_tpu.raft.log import LogEntry
        from nomad_tpu.raft.node import LEADER, RaftConfig, RaftNode
        from nomad_tpu.raft.transport import (
            InmemTransport,
            TransportRegistry,
        )

        node = RaftNode(
            node_id="n0", peers=["n0", "n1", "n2"],
            transport=InmemTransport("n0", TransportRegistry()),
            fsm_apply=lambda t, r: 0, config=RaftConfig())
        try:
            for i in (1, 2, 3):
                node.log.append(LogEntry(index=i, term=1))
            stamp_t = _time.monotonic() - 0.05
            with node._lock:
                node.state = LEADER
                node.current_term = 1
                node.match_index = {"n0": 3, "n1": 3, "n2": 1}
                node._append_stamps = {1: stamp_t, 2: stamp_t,
                                       3: stamp_t}
                node._advance_commit_locked()
                assert node.commit_index == 3
                # entry 1 is acked by all; 2 and 3 await the laggard
                assert sorted(node._append_stamps) == [2, 3]
            # the laggard's oldest unacked entry still has its stamp,
            # so LagMs ages it instead of reading 0.0
            health = node.cluster_health()
            lag = {p["Id"]: p for p in health["Peers"]}
            assert lag["n2"]["LagEntries"] == 2
            assert lag["n2"]["LagMs"] >= 40.0
            assert lag["n1"]["LagEntries"] == 0
            # once the laggard acks, the stamps prune
            with node._lock:
                node.match_index["n2"] = 3
                node._advance_commit_locked()
                assert node._append_stamps == {}
        finally:
            node.transport.close()

    def test_wal_series_distinguish_owners(self, tmp_path):
        from nomad_tpu.raft.log import LogEntry
        from nomad_tpu.raft.wal import DurableLogStore, wal_stats

        stores = {}
        for owner, n in (("srv-a", 3), ("srv-b", 7)):
            store = DurableLogStore(str(tmp_path / owner), owner=owner)
            for i in range(1, n + 1):
                store.append(LogEntry(index=i, term=1, kind=0,
                                      data=("x", {})))
            store.sync()
            stores[owner] = store
        try:
            per = wal_stats.per_server()
            assert per["srv-a"]["frames"] == 3
            assert per["srv-b"]["frames"] == 7
            assert per["srv-a"]["fsyncs"] >= 1
            assert per["srv-b"]["fsync_batch_avg"] > 0
            # review regression: stable-store fsyncs (term persists,
            # covered_frames == 0 by construction) must not dilute the
            # group-fsync amortization gauge
            from nomad_tpu.raft.wal import StableStore

            before = per["srv-b"]["fsync_batch_avg"]
            stable = StableStore(str(tmp_path / "srv-b"), owner="srv-b")
            for term in (2, 3, 4, 5):
                stable.put(term, None)
            per = wal_stats.per_server()
            assert per["srv-b"]["fsync_batch_avg"] == before
            assert per["srv-b"]["fsyncs"] > per["srv-b"]["wal_fsyncs"]
            text = prometheus_text()
            assert 'nomad_tpu_raft_wal_frames_total' \
                '{server_id="srv-a"} 3' in text
            assert 'nomad_tpu_raft_wal_frames_total' \
                '{server_id="srv-b"} 7' in text
            assert 'nomad_tpu_raft_wal_pending_frames' \
                '{server_id="srv-a"} 0' in text
        finally:
            for store in stores.values():
                store.close()


class TestExporterLabelHygiene:
    """Satellite: every labeled series goes through one escaping
    helper; quotes/backslashes/newlines cannot break line framing."""

    def test_esc_escapes_quote_backslash_newline(self):
        assert _esc('a"b') == 'a\\"b'
        assert _esc("a\\b") == "a\\\\b"
        assert _esc("a\nb") == "a\\nb"

    def test_evil_label_values_stay_line_framed(self):
        evil = 'evil"op\\with\nnewline'
        telemetry.enable()
        try:
            histograms.get(evil).record(0.001)
            with tracer.span(evil):
                pass
            text = prometheus_text()
        finally:
            telemetry.disable()
            telemetry.reset()
        assert 'op="evil\\"op\\\\with\\nnewline"' in text
        assert 'span="evil\\"op\\\\with\\nnewline"' in text
        # no line may contain an unescaped quote run that breaks the
        # exposition: every non-comment line is `name{labels} value`
        # or `name value`
        import re

        line_re = re.compile(
            r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{([^"]|"([^"\\]|\\.)*")*\})? '
            r'[^ ]+$')
        for line in text.splitlines():
            if not line or line.startswith("#"):
                continue
            assert line_re.match(line), line


class TestClusterHealth:
    @pytest.fixture()
    def agent(self):
        from nomad_tpu.api.agent import Agent, AgentConfig

        a = Agent(AgentConfig(serf_enabled=False))
        a.start()
        try:
            yield a
        finally:
            a.shutdown()

    def test_endpoint_shape_single_process(self, agent):
        status, _, body = _get(agent.http.addr,
                               "/v1/operator/cluster-health")
        assert status == 200
        data = json.loads(body)
        for key in ("ServerId", "State", "Term", "Peers", "Wal",
                    "Faults", "Transitions", "Latency", "SlowRaft"):
            assert key in data, sorted(data)
        assert data["State"] == "leader"
        assert data["Peers"] == []
        assert data["Faults"]["Armed"] in (False, True)

    def test_live_cluster_reports_per_peer_lag(self):
        from nomad_tpu.server.server import ServerConfig
        from nomad_tpu.server.testing import make_cluster, wait_for_leader

        servers, _registry = make_cluster(3, ServerConfig(
            num_workers=0, heartbeat_ttl=60.0))
        try:
            leader = wait_for_leader(servers, timeout=10.0)
            for _ in range(3):
                leader.raft.barrier()
            # a barrier resolves at MAJORITY commit; give the slower
            # peer a beat to ack the newest entry before asserting a
            # fully-caught-up view
            import time as _time

            deadline = _time.time() + 5.0
            health = cluster_health_json(leader)
            while _time.time() < deadline:
                health = cluster_health_json(leader)
                if all(p["LagEntries"] == 0 for p in health["Peers"]):
                    break
                _time.sleep(0.05)
            assert health["State"] == "leader"
            assert len(health["Peers"]) == 2
            for peer in health["Peers"]:
                assert peer["MatchIndex"] >= 1
                assert peer["LagEntries"] == 0
                assert peer["LastContactMs"] is not None
                assert peer["Healthy"] is True
            # a follower's view names the leader
            follower = next(s for s in servers if s is not leader)
            fh = cluster_health_json(follower)
            assert fh["State"] == "follower"
            assert fh["Leader"] == leader.raft.id
        finally:
            for s in servers:
                s.shutdown()

    def test_slow_raft_endpoint_shape(self, agent):
        status, _, body = _get(agent.http.addr,
                               "/v1/operator/slow-raft")
        assert status == 200
        data = json.loads(body)
        for key in ("Captured", "Retained", "ThresholdsMs", "Trees"):
            assert key in data


class TestClusterHealthACL:
    @pytest.fixture()
    def acl_agent(self):
        from nomad_tpu.acl.policy import ACLPolicy, ACLToken
        from nomad_tpu.api.agent import Agent, AgentConfig
        from nomad_tpu.server import fsm as fsm_msgs

        agent = Agent(AgentConfig(acl_enabled=True, serf_enabled=False))
        agent.start()
        server = agent.server
        mgmt = ACLToken.create(name="mgmt", type="management")
        server.raft_apply(fsm_msgs.ACL_TOKEN_UPSERT, {"tokens": [mgmt]})
        policy = ACLPolicy(name="job-read",
                           rules='namespace "default" { policy = "read" }')
        server.raft_apply(fsm_msgs.ACL_POLICY_UPSERT,
                          {"policies": [policy]})
        weak = ACLToken.create(name="weak", type="client",
                               policies=["job-read"])
        server.raft_apply(fsm_msgs.ACL_TOKEN_UPSERT, {"tokens": [weak]})
        try:
            yield agent, mgmt.secret_id, weak.secret_id
        finally:
            agent.shutdown()

    def test_weak_and_anonymous_rejected_management_allowed(
            self, acl_agent):
        agent, mgmt, weak = acl_agent
        for path in ("/v1/operator/cluster-health",
                     "/v1/operator/slow-raft"):
            for token in ("", weak):
                with pytest.raises(urllib.error.HTTPError) as ei:
                    _get(agent.http.addr, path, token=token)
                assert ei.value.code == 403
            status, _, body = _get(agent.http.addr, path, token=mgmt)
            assert status == 200
            assert json.loads(body)


class TestConsensusRecorder:
    def test_adaptive_capture_past_threshold(self):
        rec = ConsensusRecorder()
        rec.min_capture_interval_s = 0.0
        op = "raft_append"
        h = histograms.get(op)
        try:
            # the histogram (threshold source) sees ~1ms ops; the
            # observations stay clearly below its p99 bar
            for _ in range(64):
                h.record(0.001)
                rec.observe(op, 0.0001, server_id="s0")
            assert rec.captured == 0
            assert rec.observe(op, 0.5, server_id="s0") is True
            trees = rec.trees()
            assert trees[-1]["Op"] == op
            assert trees[-1]["ServerId"] == "s0"
            assert trees[-1]["DurMs"] == pytest.approx(500.0)
            assert trees[-1]["ThresholdMs"] > 0
            snap = rec.snapshot()
            assert snap["captured"] == 1
            assert op in snap["thresholds_ms"]
        finally:
            h.reset()

    def test_disarmed_until_min_samples(self):
        rec = ConsensusRecorder()
        rec.min_capture_interval_s = 0.0
        op = "raft_election"
        h = histograms.get(op)
        try:
            for _ in range(8):
                h.record(0.001)
                assert rec.observe(op, 10.0, server_id="s0") is False
        finally:
            h.reset()

    def test_json_body_shape(self):
        body = slow_raft_json()
        assert set(body) >= {"Captured", "Retained", "ThresholdsMs",
                             "Observed", "Trees"}


class TestTimelineBuilder:
    def test_failover_phase_attribution(self):
        events = [
            {"t": 10.0, "server": "a", "kind": "stepdown", "term": 3,
             "detail": {"was_leader": True}},
            {"t": 10.4, "server": "b", "kind": "election_start",
             "term": 4},
            {"t": 10.6, "server": "b", "kind": "leader_won", "term": 4},
            {"t": 10.9, "server": "b", "kind": "established",
             "term": 4},
        ]
        tl = build_timeline(events, converged_mono=11.4, cell="unit")
        assert validate_timeline(tl) == []
        assert len(tl["failovers"]) == 1
        fo = tl["failovers"][0]
        assert fo["leader_from"] == "a"
        assert fo["leader_to"] == "b"
        assert fo["phases_ms"]["detect"] == pytest.approx(400, abs=1)
        assert fo["phases_ms"]["elect"] == pytest.approx(200, abs=1)
        assert fo["phases_ms"]["replay"] == pytest.approx(300, abs=1)
        assert fo["phases_ms"]["converge"] == pytest.approx(500, abs=1)
        assert fo["attributed_share"] == pytest.approx(1.0)
        assert tl["attribution"]["share"] == pytest.approx(1.0)

    def test_partitioned_leader_failover_tracked_from_winner(self):
        """ISSUE 18 lease-partition schedule: a partitioned leader
        never emits a loss event (it still thinks it leads), so the
        failover is detected from the winner's side — backdated to the
        winner's election start — and the stale leader's stepdown at
        heal time must NOT open a second, never-resolving window."""
        events = [
            {"t": 1.0, "server": "a", "kind": "leader_won", "term": 3},
            {"t": 1.1, "server": "a", "kind": "established", "term": 3},
            # partition: b elects itself away from a silent leader a
            {"t": 5.0, "server": "b", "kind": "election_start",
             "term": 4},
            {"t": 5.3, "server": "b", "kind": "leader_won", "term": 4},
            {"t": 5.6, "server": "b", "kind": "established", "term": 4},
            # heal: a learns of term 4 and corrects itself — leadership
            # already moved, this is not a new loss
            {"t": 7.0, "server": "a", "kind": "stepdown", "term": 4,
             "detail": {"was_leader": True}},
        ]
        tl = build_timeline(events, converged_mono=7.5, cell="unit")
        assert validate_timeline(tl) == []
        assert len(tl["failovers"]) == 1
        fo = tl["failovers"][0]
        assert fo["loss_kind"] == "partition"
        assert fo["leader_from"] == "a"
        assert fo["leader_to"] == "b"
        assert fo["resolved"]
        assert fo["phases_ms"]["elect"] == pytest.approx(300, abs=1)
        assert fo["phases_ms"]["replay"] == pytest.approx(300, abs=1)
        assert fo["attributed_share"] == pytest.approx(1.0)
        assert tl["attribution"]["share"] == pytest.approx(1.0)

    def test_non_leader_stepdown_is_not_a_failover(self):
        events = [
            {"t": 1.0, "server": "a", "kind": "stepdown", "term": 2,
             "detail": {}},
            {"t": 1.5, "server": "b", "kind": "election_start",
             "term": 3},
        ]
        tl = build_timeline(events)
        assert tl["failovers"] == []
        assert tl["attribution"]["share"] == 1.0   # nothing to attribute

    def test_killed_follower_does_not_open_failover(self):
        """Review regression: a killed FOLLOWER is an event, not a
        leadership loss — the window must open at the real leader
        kill, not the earlier follower death."""
        events = [
            {"t": 1.0, "server": "b", "kind": "killed", "term": 1,
             "detail": {"was_leader": False}},
            {"t": 5.0, "server": "a", "kind": "killed", "term": 1,
             "detail": {"was_leader": True}},
            {"t": 5.2, "server": "c", "kind": "election_start",
             "term": 2},
            {"t": 5.4, "server": "c", "kind": "leader_won", "term": 2},
            {"t": 5.6, "server": "c", "kind": "established",
             "term": 2},
        ]
        tl = build_timeline(events)
        assert len(tl["failovers"]) == 1
        fo = tl["failovers"][0]
        assert fo["leader_from"] == "a"
        assert fo["total_ms"] == pytest.approx(600, abs=1)
        assert fo["phases_ms"]["detect"] == pytest.approx(200, abs=1)

    def test_index_pins_override_clock_order(self):
        events = [
            {"t": 1.0, "server": "b", "kind": "snapshot_install",
             "index": 3},
            {"t": 2.0, "server": "b", "kind": "snapshot_install",
             "index": 5},
            {"t": 100.0, "server": "a", "kind": "snapshot_install",
             "index": 4},
        ]
        tl = build_timeline(events)
        assert [e["index"] for e in tl["events"]] == [3, 4, 5]
        assert validate_timeline(tl) == []

    def test_skew_correction_shifts_lagging_clock(self):
        """Review regression: the old estimator anchored each index at
        the MINIMUM observer stamp, so lag was always <= 0 and the
        correction was dead code. Anchors now come from the index's
        CREATOR event (the leader's snapshot_sent) — a server whose
        same-index event precedes the creation is provably behind."""
        events = [
            {"t": 49.0, "server": "leader", "kind": "snapshot_sent",
             "index": 7},
            {"t": 50.0, "server": "a", "kind": "snapshot_install",
             "index": 7},
            # b's clock says it installed BEFORE the leader sent:
            # impossible — b is behind by >= 29s
            {"t": 20.0, "server": "b", "kind": "snapshot_install",
             "index": 7},
            {"t": 21.0, "server": "b", "kind": "election_start",
             "term": 2},
        ]
        tl = build_timeline(events)
        assert tl["clock_offsets_ms"]["b"] == pytest.approx(29000,
                                                            abs=1)
        # a installed after the send: no correction for it
        assert "a" not in tl["clock_offsets_ms"]
        assert validate_timeline(tl) == []
        # b's unpinned event moved with its offset: election_start at
        # local 21 renders AFTER the leader's send at 49
        by_kind = {e["kind"]: e["t_ms"] for e in tl["events"]}
        assert by_kind["election_start"] > by_kind["snapshot_sent"]

    def test_observer_only_indexes_produce_no_offsets(self):
        # without a creator event an early observer stamp proves
        # nothing (observers legally lag creation by transfer time)
        events = [
            {"t": 50.0, "server": "a", "kind": "snapshot_install",
             "index": 7},
            {"t": 20.0, "server": "b", "kind": "snapshot_install",
             "index": 7},
        ]
        tl = build_timeline(events)
        assert tl["clock_offsets_ms"] == {}
        assert validate_timeline(tl) == []

    def test_unrecovered_leadership_loss_stays_on_the_timeline(self):
        """Review regression: a leader lost with NO winner before the
        cell ended must not vanish — the window closes at the cell's
        end stamp with the un-elected tail unattributed, so the share
        drops instead of reading 1.0."""
        events = [
            {"t": 1.0, "server": "a", "kind": "killed", "term": 1,
             "detail": {"was_leader": True}},
            {"t": 1.2, "server": "b", "kind": "election_start",
             "term": 2},
        ]
        tl = build_timeline(events, converged_mono=3.0, cell="unit")
        assert validate_timeline(tl) == []
        assert len(tl["failovers"]) == 1
        fo = tl["failovers"][0]
        assert fo["resolved"] is False
        assert fo["leader_from"] == "a"
        assert fo["leader_to"] is None
        # window runs loss -> cell end (2s); only detect (200ms) is
        # attributable
        assert fo["total_ms"] == pytest.approx(2000, abs=1)
        assert fo["phases_ms"]["detect"] == pytest.approx(200, abs=1)
        assert fo["attributed_share"] == pytest.approx(0.1, abs=0.01)
        assert tl["attribution"]["share"] == pytest.approx(0.1,
                                                           abs=0.01)
        # a resolved window still reports resolved=True
        events += [
            {"t": 2.0, "server": "b", "kind": "leader_won", "term": 2},
            {"t": 2.2, "server": "b", "kind": "established", "term": 2},
        ]
        tl2 = build_timeline(events, converged_mono=3.0, cell="unit")
        assert tl2["failovers"][0]["resolved"] is True
        assert tl2["attribution"]["share"] == pytest.approx(1.0)

    def test_artifact_merge_aggregates_sections(self, tmp_path):
        path = str(tmp_path / "CHAOS_TIMELINE.json")
        events = [
            {"t": 0.0, "server": "a", "kind": "killed", "term": 1,
             "detail": {"was_leader": True}},
            {"t": 0.2, "server": "b", "kind": "election_start",
             "term": 2},
            {"t": 0.3, "server": "b", "kind": "leader_won", "term": 2},
            {"t": 0.5, "server": "b", "kind": "established", "term": 2},
        ]
        tl = build_timeline(events, cell="one")
        merge_into_artifact(path, "one", tl,
                            summary_extra={"seed": 999})
        doc = merge_into_artifact(path, "two",
                                  build_timeline([], cell="two"))
        assert set(doc["cells"]) == {"one", "two"}
        assert doc["failovers"] == 1
        assert 0.0 <= doc["attribution"]["share"] <= 1.0
        # review regression: an earlier section's summary_extra keys
        # survive later merges that pass none
        assert doc["seed"] == 999
        with open(path) as f:
            assert json.load(f) == doc


class TestMiniTimelineSmoke:
    def test_single_server_chaos_emits_valid_timeline(self, tmp_path):
        """Tier-1 acceptance: the mini smoke (durable single-server
        cluster + one injected leader step-down mid-burst) emits a
        valid CHAOS_TIMELINE with >= 0.90 failover attribution, and
        the burst's e2e waterfalls include the raft segments at
        >= 0.90 named-segment coverage."""
        import trace_report

        out = str(tmp_path / "CHAOS_TIMELINE.json")
        cell = trace_report.run_timeline_smoke(out_path=out)
        assert cell["placed_ok"], cell
        assert cell["quiesced"], cell
        assert cell["stepdowns_fired"] == 1, cell
        assert cell["timeline_problems"] == [], cell["timeline_problems"]
        assert cell["failovers"] >= 1, cell["timeline"]["events"]
        assert cell["attributed_share"] >= 0.9, cell["timeline"]
        # the artifact exists and carries the mini section
        with open(out) as f:
            doc = json.load(f)
        assert "mini" in doc["cells"]
        assert doc["failovers"] >= 1
        # e2e waterfalls picked up the raft segments (single durable
        # server: fsync/quorum/apply; replicate needs peers and is
        # covered by the stress-tier 3-node cells)
        assert cell["waterfall_count"] > 0
        for seg in ("raft-fsync", "raft-quorum", "raft-apply"):
            assert seg in cell["waterfall_segments"], \
                cell["waterfall_segments"]
        assert cell["p50_coverage"] >= 0.9, cell
