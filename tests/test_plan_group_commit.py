"""Group-commit bit-identity: the plan applier's vectorized wave pass
(server/plan_apply.py `_GroupFitChecker` + `apply_batch`) must produce
results identical to serialized `apply_one` over the same plans in the
same order — including node-plan conflicts, overcommit rejection,
in-place updates, staged stops, non-lean (exact-walk fallback) members,
and partial-wave failures (a rejected plan must not poison siblings).

The property test builds TWO identical universes from one randomized
scenario description, applies the plans serially in one and as a group
in the other, and compares per-plan results and final store state.
"""

from __future__ import annotations

import random

import pytest

from nomad_tpu import mock
from nomad_tpu.server.plan_apply import Planner, plan_group_stats
from nomad_tpu.server.plan_queue import PlanQueue
from nomad_tpu.state.store import StateStore
from nomad_tpu.structs import consts
from nomad_tpu.structs.alloc import Allocation
from nomad_tpu.structs.eval_plan import Plan
from nomad_tpu.structs.network import Port
from nomad_tpu.structs.resources import (
    AllocatedCpuResources,
    AllocatedMemoryResources,
    AllocatedResources,
    AllocatedSharedResources,
    AllocatedTaskResources,
)

N_NODES = 8


def _make_alloc(spec: dict) -> Allocation:
    """Instantiate one alloc from a plain-data spec (each universe gets
    its own object graph; ids are shared so results compare)."""
    shared = AllocatedSharedResources(disk_mb=spec["disk"])
    if spec.get("port"):
        shared.ports = [Port(label="p", value=spec["port"])]
    return Allocation(
        id=spec["id"],
        eval_id="eval-" + spec["id"],
        node_id=spec["node_id"],
        namespace="default",
        job_id=spec.get("job_id", "job-" + spec["id"]),
        task_group="web",
        name="job.web[0]",
        desired_status=spec.get("desired_status", consts.ALLOC_DESIRED_RUN),
        client_status=spec.get("client_status", consts.ALLOC_CLIENT_PENDING),
        allocated_resources=AllocatedResources(
            tasks={
                "web": AllocatedTaskResources(
                    cpu=AllocatedCpuResources(cpu_shares=spec["cpu"]),
                    memory=AllocatedMemoryResources(memory_mb=spec["mem"]),
                )
            },
            shared=shared,
        ),
    )


def _scenario(seed: int) -> dict:
    """One randomized scenario as plain data: node ids, pre-existing
    allocs, and a mix of plans."""
    rng = random.Random(seed)
    nodes = [f"node-{seed}-{i}" for i in range(N_NODES)]
    port_counter = [20000]
    issued_ports: list = []

    def alloc_spec(i: str, node_id: str, big: bool = False,
                   port: bool = False) -> dict:
        spec = {
            "id": f"alloc-{seed}-{i}",
            "node_id": node_id,
            # big asks force overcommit interplay on 3900-MHz nodes
            "cpu": rng.choice([500, 1200, 2000, 3000]
                              if not big else [2500, 3500, 3900]),
            "mem": rng.choice([256, 1024, 4096]),
            "disk": rng.choice([100, 1000]),
        }
        if port:
            roll = rng.random()
            if issued_ports and roll < 0.35:
                # deliberate conflict mix: reuse a port some other
                # alloc of this scenario already holds — the ports
                # plane must reject exactly where serialized
                # NetworkIndex walks reject
                spec["port"] = rng.choice(issued_ports)
            elif roll < 0.45:
                # mock nodes agent-reserve port 22: collides with the
                # node's static bitmap
                spec["port"] = 22
            else:
                port_counter[0] += 1
                spec["port"] = port_counter[0]
            issued_ports.append(spec["port"])
        return spec

    existing = [
        alloc_spec(f"pre-{i}", rng.choice(nodes),
                   port=rng.random() < 0.25)
        for i in range(rng.randint(0, 10))
    ]
    plans = []
    for p in range(rng.randint(2, 8)):
        placements = []
        stops = []
        preempts = []
        for s in range(rng.randint(1, 4)):
            roll = rng.random()
            node_id = rng.choice(nodes)
            if roll < 0.08:
                # node-plan conflict: a node that does not exist
                node_id = f"missing-{seed}-{p}-{s}"
            spec = alloc_spec(
                f"{p}-{s}", node_id,
                big=rng.random() < 0.5,
                port=rng.random() < 0.35,   # ports-plane vector check
            )
            if existing and rng.random() < 0.15:
                # in-place update: placement re-uses a live alloc id
                prev = rng.choice(existing)
                spec["id"] = prev["id"]
                spec["node_id"] = prev["node_id"]
            if rng.random() < 0.12:
                # terminal transition rides node_allocation (lost
                # marks): contributes NOTHING to the fit walk —
                # allocs_fit skips terminal allocs — and the group
                # fold must agree
                spec["client_status"] = consts.ALLOC_CLIENT_LOST
            placements.append(spec)
        if existing and rng.random() < 0.4:
            stops.append(rng.choice(existing)["id"])
        if existing and rng.random() < 0.2:
            preempts.append(rng.choice(existing)["id"])
        plans.append({"placements": placements, "stops": stops,
                      "preempts": preempts})
    return {"seed": seed, "nodes": nodes, "existing": existing,
            "plans": plans}


def _build_universe(scenario: dict):
    """(store, plans) instantiated fresh from the scenario data."""
    store = StateStore()
    for nid in scenario["nodes"]:
        store.upsert_node(mock.node(id=nid))
    pre = {}
    for spec in scenario["existing"]:
        a = _make_alloc(spec)
        a.client_status = consts.ALLOC_CLIENT_RUNNING
        pre[a.id] = a
    if pre:
        store.upsert_allocs(list(pre.values()))
    plans = []
    for pd in scenario["plans"]:
        plan = Plan(priority=50)
        for spec in pd["placements"]:
            a = _make_alloc(spec)
            plan.node_allocation.setdefault(a.node_id, []).append(a)
        for aid in pd["stops"]:
            prev = store.snapshot().alloc_by_id(aid)
            if prev is not None:
                plan.append_stopped_alloc(prev, "stopped by test")
        for aid in pd["preempts"]:
            prev = store.snapshot().alloc_by_id(aid)
            if prev is not None:
                plan.append_preempted_alloc(prev, "preemptor")
        plans.append(plan)
    return store, plans


def _result_fingerprint(result) -> tuple:
    return (
        tuple(sorted(
            (nid, tuple(a.id for a in allocs))
            for nid, allocs in result.node_allocation.items())),
        tuple(sorted(
            (nid, tuple(a.id for a in allocs))
            for nid, allocs in result.node_preemptions.items())),
        tuple(sorted(
            (nid, tuple(a.id for a in allocs))
            for nid, allocs in result.node_update.items())),
        result.refresh_index > 0,
    )


def _store_fingerprint(store) -> tuple:
    snap = store.snapshot()
    rows = tuple(sorted(
        (a.id, a.node_id, a.desired_status, a.client_status)
        for a in snap.allocs_iter()))
    u = snap.usage
    usage = tuple(sorted(
        (nid, float(u.used_cpu[row]), float(u.used_mem[row]),
         float(u.used_disk[row]), int(u.used_special[row]),
         int(u.used_devices[row]), u.port_masks.get(row, 0),
         row in u.port_dirty)
        for nid, row in u.rows.items()))
    return rows, usage


class TestGroupCommitBitIdentity:
    @pytest.mark.parametrize("seed", range(25))
    def test_group_apply_matches_serialized_apply_one(self, seed):
        scenario = _scenario(seed)

        store_a, plans_a = _build_universe(scenario)
        planner_a = Planner(store_a, PlanQueue(), pool_workers=1)
        serial = [planner_a.apply_one(p) for p in plans_a]

        store_b, plans_b = _build_universe(scenario)
        planner_b = Planner(store_b, PlanQueue(), pool_workers=1)
        group = planner_b.apply_batch(plans_b)

        assert len(serial) == len(group)
        for i, (ra, rb) in enumerate(zip(serial, group)):
            assert _result_fingerprint(ra) == _result_fingerprint(rb), \
                f"seed {seed} plan {i} diverged"
        assert _store_fingerprint(store_a) == _store_fingerprint(store_b), \
            f"seed {seed} final state diverged"

    def test_rejected_plan_does_not_poison_siblings(self):
        """Partial-wave failure: an overcommitting plan's rejection must
        leave its siblings' placements committed exactly as the serial
        applier would."""
        store, _ = _build_universe(
            {"seed": 0, "nodes": ["node-s-0"], "existing": [],
             "plans": []})
        planner = Planner(store, PlanQueue(), pool_workers=1)
        ok1 = _make_alloc({"id": "a-1", "node_id": "node-s-0",
                           "cpu": 2000, "mem": 256, "disk": 100})
        hog = _make_alloc({"id": "a-2", "node_id": "node-s-0",
                           "cpu": 3000, "mem": 256, "disk": 100})
        ok2 = _make_alloc({"id": "a-3", "node_id": "node-s-0",
                           "cpu": 1000, "mem": 256, "disk": 100})
        plans = [
            Plan(priority=50, node_allocation={"node-s-0": [ok1]}),
            Plan(priority=50, node_allocation={"node-s-0": [hog]}),
            Plan(priority=50, node_allocation={"node-s-0": [ok2]}),
        ]
        results = planner.apply_batch(plans)
        assert results[0].node_allocation    # fits (2000 <= 3900)
        assert not results[1].node_allocation  # 2000+3000 > 3900
        assert results[1].refresh_index > 0
        assert results[2].node_allocation    # 2000+1000 <= 3900
        snap = store.snapshot()
        assert snap.alloc_by_id("a-1") is not None
        assert snap.alloc_by_id("a-2") is None
        assert snap.alloc_by_id("a-3") is not None

    def test_overcommit_rejected_by_vector_check(self):
        plan_group_stats.reset()
        store, _ = _build_universe(
            {"seed": 1, "nodes": ["node-v-0"], "existing": [],
             "plans": []})
        planner = Planner(store, PlanQueue(), pool_workers=1)
        hog = _make_alloc({"id": "v-1", "node_id": "node-v-0",
                           "cpu": 3900, "mem": 256, "disk": 100})
        hog2 = _make_alloc({"id": "v-2", "node_id": "node-v-0",
                            "cpu": 100, "mem": 256, "disk": 100})
        results = planner.apply_batch([
            Plan(priority=50, node_allocation={"node-v-0": [hog]}),
            Plan(priority=50, node_allocation={"node-v-0": [hog2]}),
        ])
        assert results[0].node_allocation
        assert not results[1].node_allocation
        g = plan_group_stats.snapshot()
        assert g["fallback_nodes"] == 0      # both proven by the planes
        assert g["rejected_node_plans"] == 1

    def test_port_plan_proven_by_vector_check(self):
        """ISSUE 10: a static-port plan is proven by the ports plane —
        no exact walk — and the port-coverage counters say so."""
        plan_group_stats.reset()
        store, _ = _build_universe(
            {"seed": 2, "nodes": ["node-f-0"], "existing": [],
             "plans": []})
        planner = Planner(store, PlanQueue(), pool_workers=1)
        ported = _make_alloc({"id": "f-1", "node_id": "node-f-0",
                              "cpu": 500, "mem": 256, "disk": 100,
                              "port": 23456})
        results = planner.apply_batch(
            [Plan(priority=50, node_allocation={"node-f-0": [ported]})])
        assert results[0].node_allocation
        g = plan_group_stats.snapshot()
        assert g["fallback_plans"] == 0
        assert g["vector_plans"] == 1
        assert g["port_plans"] == 1
        assert g["port_vector_plans"] == 1
        assert g["port_fallback_plans"] == 0

    def test_port_conflict_rejected_by_vector_check(self):
        """Same port twice — live alloc vs new placement — rejects
        through the bitmap AND, without an exact walk."""
        plan_group_stats.reset()
        store, _ = _build_universe(
            {"seed": 7, "nodes": ["node-p-0"],
             "existing": [{"id": "pre-p", "node_id": "node-p-0",
                           "cpu": 200, "mem": 64, "disk": 10,
                           "port": 24000}],
             "plans": []})
        planner = Planner(store, PlanQueue(), pool_workers=1)
        clash = _make_alloc({"id": "p-1", "node_id": "node-p-0",
                             "cpu": 200, "mem": 64, "disk": 10,
                             "port": 24000})
        free = _make_alloc({"id": "p-2", "node_id": "node-p-0",
                            "cpu": 200, "mem": 64, "disk": 10,
                            "port": 24001})
        results = planner.apply_batch([
            Plan(priority=50, node_allocation={"node-p-0": [clash]}),
            Plan(priority=50, node_allocation={"node-p-0": [free]}),
        ])
        assert not results[0].node_allocation
        assert results[0].refresh_index > 0
        assert results[1].node_allocation
        g = plan_group_stats.snapshot()
        assert g["fallback_nodes"] == 0
        assert g["rejected_node_plans"] == 1

    def test_static_reserved_port_conflict_rejected(self):
        """mock nodes agent-reserve port 22: a placement claiming it
        must reject against the static bitmap (NetworkIndex.set_node
        marks agent-reserved ports used)."""
        plan_group_stats.reset()
        store, _ = _build_universe(
            {"seed": 8, "nodes": ["node-r-0"], "existing": [],
             "plans": []})
        planner = Planner(store, PlanQueue(), pool_workers=1)
        ssh = _make_alloc({"id": "r-1", "node_id": "node-r-0",
                           "cpu": 200, "mem": 64, "disk": 10,
                           "port": 22})
        results = planner.apply_batch(
            [Plan(priority=50, node_allocation={"node-r-0": [ssh]})])
        assert not results[0].node_allocation
        g = plan_group_stats.snapshot()
        assert g["fallback_nodes"] == 0
        assert g["rejected_node_plans"] == 1

    def test_device_plan_still_falls_back(self):
        """Devices stay exact-walk territory (DeviceAccounter)."""
        from nomad_tpu.structs.resources import AllocatedDeviceResource

        plan_group_stats.reset()
        store, _ = _build_universe(
            {"seed": 9, "nodes": ["node-d-0"], "existing": [],
             "plans": []})
        planner = Planner(store, PlanQueue(), pool_workers=1)
        dev = _make_alloc({"id": "d-1", "node_id": "node-d-0",
                           "cpu": 200, "mem": 64, "disk": 10})
        dev.allocated_resources.tasks["web"].devices.append(
            AllocatedDeviceResource(vendor="nvidia", type="gpu",
                                    name="t4", device_ids=["gpu0"]))
        results = planner.apply_batch(
            [Plan(priority=50, node_allocation={"node-d-0": [dev]})])
        assert results[0].node_allocation
        g = plan_group_stats.snapshot()
        assert g["fallback_plans"] == 1
        assert g["vector_plans"] == 0

    def test_group_commit_is_one_index_bump(self):
        """The whole wave lands as ONE store commit (one raft entry /
        one FSM apply in the live server)."""
        store, _ = _build_universe(
            {"seed": 3, "nodes": ["node-i-0", "node-i-1"],
             "existing": [], "plans": []})
        planner = Planner(store, PlanQueue(), pool_workers=1)
        before = store.latest_index()
        plans = [
            Plan(priority=50, node_allocation={"node-i-0": [_make_alloc(
                {"id": f"i-{k}", "node_id": "node-i-0", "cpu": 100,
                 "mem": 64, "disk": 10})]})
            for k in range(4)
        ]
        results = planner.apply_batch(plans)
        assert store.latest_index() == before + 1
        assert all(r.alloc_index == before + 1 for r in results)


class TestWaveCohortDrain:
    """Wave-boundary plan batching (ISSUE 10): the plan queue's
    dequeue_batch holds its drain window open while a fired wave's
    cohort is still landing, so a wave commits as ONE raft entry."""

    def _tracker(self):
        from nomad_tpu.utils.wavecohort import WaveCohortTracker

        return WaveCohortTracker()

    def test_cohort_drains_and_learns(self):
        t = self._tracker()
        assert t.pending_wait_s() == 0.0
        t.note_wave(3)
        assert t.pending_wait_s() > 0.0
        for _ in range(3):
            t.note_plan()
        assert t.pending_wait_s() == 0.0
        snap = t.snapshot()
        assert snap["drained_cohorts"] == 1
        assert snap["cohort_plans"] == 3
        assert snap["drain_ewma_ms"] >= 0.0

    def test_cohort_shortfall_expires(self):
        t = self._tracker()
        t.WINDOW_DEFAULT_S = 0.01
        t.note_wave(2)
        t.note_plan()
        import time

        deadline = time.monotonic() + 2.0
        while t.pending_wait_s() > 0.0 and time.monotonic() < deadline:
            time.sleep(0.002)
        assert t.pending_wait_s() == 0.0
        assert t.snapshot()["expired_cohorts"] == 1

    def test_dequeue_batch_waits_for_cohort(self):
        """Enqueue plan 1, arm a 2-plan cohort, enqueue plan 2 shortly
        after from another thread: dequeue_batch must return BOTH."""
        import threading
        import time

        from nomad_tpu.server import plan_queue as pq_mod
        from nomad_tpu.utils.wavecohort import WaveCohortTracker

        tracker = WaveCohortTracker()
        orig = pq_mod.wave_cohorts
        pq_mod.wave_cohorts = tracker
        try:
            q = pq_mod.PlanQueue()
            q.set_enabled(True)
            tracker.note_wave(2)
            q.enqueue(Plan(priority=50))

            def late():
                time.sleep(0.01)
                q.enqueue(Plan(priority=50))

            th = threading.Thread(target=late, daemon=True)
            th.start()
            batch = q.dequeue_batch(128, timeout=0.2)
            th.join()
            assert len(batch) == 2, "applier popped a partial cohort"
        finally:
            pq_mod.wave_cohorts = orig

    def test_dequeue_batch_unaffected_without_cohort(self):
        from nomad_tpu.server.plan_queue import PlanQueue

        q = PlanQueue()
        q.set_enabled(True)
        q.enqueue(Plan(priority=50))
        import time

        t0 = time.monotonic()
        batch = q.dequeue_batch(128, timeout=0.2)
        assert len(batch) == 1
        assert time.monotonic() - t0 < 0.05


class TestDuplicateSlotGuard:
    """ISSUE 18 failover regression: after a leader partition, the
    broker restore redelivers a still-pending eval whose previous plan
    ALREADY committed (the commit replicated; the worker's EVAL_UPDATE
    to complete did not). The twin holds a legitimately current token
    and evaluates from a snapshot predating the first commit, so it
    re-places the same slots — possibly on different nodes. The
    applier's duplicate-slot guard (`_duplicate_slot_nodes`) must
    reject the twin and send it back partial (refresh_index) so the
    retry reconciles against the committed slots; legitimate
    same-name flows (stop-and-replace in one plan, in-place updates,
    replacing terminal allocs, canaries, system jobs fanning out)
    must pass untouched."""

    def _store_with(self, node_ids):
        store = StateStore()
        for nid in node_ids:
            store.upsert_node(mock.node(id=nid))
        return store

    def _placement(self, i, node_id, job_id="mock-ser"):
        return _make_alloc({"id": f"dup-{i}", "node_id": node_id,
                            "cpu": 500, "mem": 256, "disk": 100,
                            "job_id": job_id})

    def test_redelivered_twin_rejected_even_cross_node(self):
        store = self._store_with(["dn-0", "dn-1"])
        planner = Planner(store, PlanQueue(), pool_workers=1)
        r1 = planner.apply_one(Plan(
            node_allocation={"dn-0": [self._placement(1, "dn-0")]}))
        assert r1.node_allocation and r1.refresh_index == 0
        # the twin re-places the same (job, slot name) on a DIFFERENT
        # node — a per-node check would never see the collision
        r2 = planner.apply_one(Plan(
            node_allocation={"dn-1": [self._placement(2, "dn-1")]}))
        assert not r2.node_allocation
        assert r2.refresh_index > 0
        assert planner.plans_duplicate_slot == 1
        live = [a for a in store.snapshot().allocs_by_job(
                    "default", "mock-ser") if not a.terminal_status()]
        assert [a.id for a in live] == ["dup-1"]

    def test_twin_in_same_batch_rejected_via_overlay(self):
        store = self._store_with(["dn-0", "dn-1"])
        planner = Planner(store, PlanQueue(), pool_workers=1)
        r1, r2 = planner.apply_batch([
            Plan(node_allocation={"dn-0": [self._placement(1, "dn-0")]}),
            Plan(node_allocation={"dn-1": [self._placement(2, "dn-1")]}),
        ])
        assert r1.node_allocation
        assert not r2.node_allocation and r2.refresh_index > 0

    def test_stop_and_replace_in_one_plan_passes(self):
        store = self._store_with(["dn-0", "dn-1"])
        planner = Planner(store, PlanQueue(), pool_workers=1)
        planner.apply_one(Plan(
            node_allocation={"dn-0": [self._placement(1, "dn-0")]}))
        old = store.snapshot().alloc_by_id("dup-1")
        plan = Plan(node_allocation={"dn-1": [self._placement(2, "dn-1")]})
        plan.append_stopped_alloc(old, "migrated")
        r = planner.apply_one(plan)
        assert r.node_allocation and r.refresh_index == 0
        assert planner.plans_duplicate_slot == 0

    def test_in_place_update_same_id_passes(self):
        store = self._store_with(["dn-0"])
        planner = Planner(store, PlanQueue(), pool_workers=1)
        planner.apply_one(Plan(
            node_allocation={"dn-0": [self._placement(1, "dn-0")]}))
        r = planner.apply_one(Plan(
            node_allocation={"dn-0": [self._placement(1, "dn-0")]}))
        assert r.node_allocation and r.refresh_index == 0

    def test_replacing_terminal_alloc_passes(self):
        store = self._store_with(["dn-0"])
        planner = Planner(store, PlanQueue(), pool_workers=1)
        planner.apply_one(Plan(
            node_allocation={"dn-0": [self._placement(1, "dn-0")]}))
        dead = _make_alloc({"id": "dup-1", "node_id": "dn-0",
                            "cpu": 500, "mem": 256, "disk": 100,
                            "job_id": "mock-ser",
                            "client_status": consts.ALLOC_CLIENT_FAILED})
        store.upsert_allocs([dead])
        r = planner.apply_one(Plan(
            node_allocation={"dn-0": [self._placement(2, "dn-0")]}))
        assert r.node_allocation and r.refresh_index == 0

    def test_system_job_fans_out_but_twin_on_same_node_rejected(self):
        store = self._store_with(["dn-0", "dn-1"])
        planner = Planner(store, PlanQueue(), pool_workers=1)
        sysjob = mock.job(id="mock-ser", type=consts.JOB_TYPE_SYSTEM)
        # one group[0] per node is the system scheduler's shape — the
        # job-wide collision scope must NOT reject the fan-out
        r1 = planner.apply_one(Plan(
            job=sysjob,
            node_allocation={"dn-0": [self._placement(1, "dn-0")],
                             "dn-1": [self._placement(2, "dn-1")]}))
        assert len(r1.node_allocation) == 2 and r1.refresh_index == 0
        # but a stale twin re-placing an occupied NODE is still caught
        r2 = planner.apply_one(Plan(
            job=sysjob,
            node_allocation={"dn-0": [self._placement(3, "dn-0")]}))
        assert not r2.node_allocation and r2.refresh_index > 0
