"""C2M replay bench harness: generation, persistence, plane export.

Reference behavior: scheduler/benchmarks/benchmarks_test.go:16-24 — the
replay bench loads a persisted cluster state (raft snapshot) and runs
the scheduler against it. Here the persisted form is the state store's
own snapshot codec, and the bench flattens the restored state to the
kernel's planes.
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "bench"))

import c2m  # noqa: E402


@pytest.fixture(scope="module")
def replay_path(tmp_path_factory):
    p = tmp_path_factory.mktemp("c2m") / "replay.snap"
    c2m.generate(str(p), n_nodes=300, n_allocs=1500, seed=7, verbose=False)
    return str(p)


class TestGenerate:
    def test_persists_and_restores_through_state_store(self, replay_path):
        store = c2m.load(replay_path, generate_if_missing=False)
        snap = store.snapshot()
        nodes = snap.nodes()
        allocs = [a for a in snap.allocs_iter()]
        assert len(nodes) == 300
        assert len(allocs) == 1500
        assert len(snap.jobs()) > 10

    def test_cluster_is_heterogeneous(self, replay_path):
        store = c2m.load(replay_path, generate_if_missing=False)
        snap = store.snapshot()
        nodes = snap.nodes()
        classes = {n.node_class for n in nodes}
        assert {"standard", "large"} <= classes
        dcs = {n.datacenter for n in nodes}
        assert len(dcs) >= 5
        racks = {n.attributes.get("platform.aws.placement.rack")
                 for n in nodes}
        assert len(racks) >= 10

    def test_workload_is_heterogeneous(self, replay_path):
        from nomad_tpu.structs import consts

        store = c2m.load(replay_path, generate_if_missing=False)
        snap = store.snapshot()
        jobs = snap.jobs()
        kinds = {j.type for j in jobs}
        assert consts.JOB_TYPE_SERVICE in kinds
        assert any(tg.spreads for j in jobs for tg in j.task_groups)
        assert any(
            c.operand == consts.CONSTRAINT_DISTINCT_HOSTS
            for j in jobs for tg in j.task_groups for c in tg.constraints)

    def test_allocations_fit_node_capacity(self, replay_path):
        """Generated placements must be feasible: per-node allocated
        cpu/mem cannot exceed the node's unreserved capacity."""
        store = c2m.load(replay_path, generate_if_missing=False)
        snap = store.snapshot()
        for node in snap.nodes():
            cap_cpu = (node.node_resources.cpu.cpu_shares
                       - node.reserved_resources.cpu_shares)
            cap_mem = (node.node_resources.memory.memory_mb
                       - node.reserved_resources.memory_mb)
            used_cpu = used_mem = 0
            for a in snap.allocs_by_node(node.id):
                cr = a.comparable_resources()
                used_cpu += cr.cpu_shares
                used_mem += cr.memory_mb
            assert used_cpu <= cap_cpu, node.id
            assert used_mem <= cap_mem, node.id

    def test_usage_planes_match_allocs(self, replay_path):
        store = c2m.load(replay_path, generate_if_missing=False)
        snap = store.snapshot()
        u = snap.usage
        want = {}
        for a in snap.allocs_iter():
            if a.terminal_status():
                continue
            cr = a.comparable_resources()
            want[a.node_id] = want.get(a.node_id, 0) + cr.cpu_shares
        for nid, cpu in want.items():
            row = u.rows[nid]
            assert u.used_cpu[row] == pytest.approx(cpu)


class TestReplayPlanes:
    def test_planes_flatten_and_feed_the_kernel(self, replay_path):
        import bench

        cluster, _snap, used_cpu, used_mem, used_disk, asks, stats = \
            bench._replay_planes(replay_path)
        assert stats["replay_nodes"] == 300
        assert stats["replay_allocs"] == 1500
        assert used_cpu[:cluster.n_real].sum() > 0
        assert asks.shape[1] == 2 and len(asks) > 0
        # capacity planes are heterogeneous (several distinct classes)
        caps = set(np.unique(cluster.cap_cpu[:cluster.n_real]).tolist())
        assert len(caps) >= 3

    def test_planes_file_roundtrip_via_baseline(self, replay_path):
        import json
        import subprocess

        import bench

        cluster, _snap, used_cpu, used_mem, used_disk, asks, _ = \
            bench._replay_planes(replay_path)
        path = bench._write_planes_file(
            cluster, used_cpu, used_mem, used_disk, asks, 50, 5)
        try:
            proc = subprocess.run(
                [bench._baseline_bin(), "--planes", path],
                check=True, capture_output=True, text=True)
            out = json.loads(proc.stdout)
        finally:
            os.unlink(path)
        assert out["evals_per_sec"] > 0
        assert out["placed"] > 0
