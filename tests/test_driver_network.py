"""Driver-managed group networks (DriverNetworkManager).

Reference behavior: plugins/drivers/driver.go:92 (CreateNetwork/
DestroyNetwork + MustInitiateNetwork) and drivers/docker/network.go —
docker builds the allocation's shared namespace itself as a "pause"
container; task containers join IT (``--network container:<pause>``),
so a group's tasks share localhost the way the client's bridge netns
gives that to exec tasks.

The docker CLI is faked (as in test_docker_driver) but the pause
semantics are REAL: the stub backs each pause container with an actual
network namespace and runs joined containers inside it, so the
two-tasks-reach-each-other-over-localhost property is genuinely
exercised end to end through AllocRunner -> DockerDriver.
"""

import os
import stat
import sys
import time
import uuid

import pytest

from nomad_tpu import mock, structs
from nomad_tpu.client.alloc_runner import AllocRunner
from nomad_tpu.client.network_manager import bridge_supported
from nomad_tpu.drivers.docker import DockerDriver

pytestmark = pytest.mark.skipif(
    not bridge_supported(), reason="host cannot create netns")

FAKE_DOCKER_NS = r'''#!/usr/bin/env python3
"""Fake docker CLI whose pause containers are real netns."""
import os, subprocess, sys

STATE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "..", "state")
ARGS = sys.argv[1:]
if ARGS[:1] == ["--config"]:
    ARGS = ARGS[2:]
CMD = ARGS[0] if ARGS else ""
with open(os.path.join(STATE, "invocations.log"), "a") as f:
    f.write(" ".join(sys.argv[1:]) + "\n")


def slug(img):
    return img.replace("/", "_").replace(":", "_")


def nsname(container):
    return "fdkns-" + container.replace("nomad-pause-", "")[:10]


if CMD == "version":
    print("24.0.7"); sys.exit(0)
if CMD == "image":
    sys.exit(0 if os.path.exists(
        os.path.join(STATE, "pulled-" + slug(ARGS[2]))) else 1)
if CMD == "pull":
    open(os.path.join(STATE, "pulled-" + slug(ARGS[1])), "w").close()
    sys.exit(0)
if CMD in ("rm", "stop"):
    name = ARGS[-1]
    if name.startswith("nomad-pause-"):
        subprocess.run(["ip", "netns", "del", nsname(name)],
                       capture_output=True)
    sys.exit(0)
if CMD == "rmi":
    sys.exit(0)
if CMD == "inspect":
    name = ARGS[-1]
    ns = nsname(name)
    if os.path.exists("/var/run/netns/" + ns):
        print("172.26.99.2" if "IPAddress" in " ".join(ARGS) else "ok")
        sys.exit(0)
    sys.exit(1)
if CMD == "run":
    rest, detach, name, network = ARGS[1:], False, "", ""
    image, command, i = None, [], 0
    VALFLAGS = {"--name", "--memory", "--cpu-shares", "-e",
                "--network", "-p"}
    while i < len(rest):
        a = rest[i]
        if a in ("--rm", "--init"):
            i += 1; continue
        if a == "-d":
            detach = True; i += 1; continue
        if a in VALFLAGS:
            if a == "--name":
                name = rest[i + 1]
            if a == "--network":
                network = rest[i + 1]
            i += 2; continue
        image = a; command = rest[i + 1:]; break
    if detach and name.startswith("nomad-pause-"):
        ns = nsname(name)
        subprocess.run(["ip", "netns", "add", ns], check=True)
        subprocess.run(["ip", "netns", "exec", ns,
                        "ip", "link", "set", "lo", "up"], check=True)
        print("deadbeef" + ns); sys.exit(0)
    if network.startswith("container:"):
        ns = nsname(network.split(":", 1)[1])
        os.execvp("ip", ["ip", "netns", "exec", ns] + command)
    if command:
        os.execvp(command[0], command)
    sys.exit(0)
sys.exit(0)
'''


@pytest.fixture()
def fake_docker_ns(tmp_path, monkeypatch):
    bin_dir = tmp_path / "bin"
    state = tmp_path / "state"
    bin_dir.mkdir()
    state.mkdir()
    stub = bin_dir / "docker"
    stub.write_text(FAKE_DOCKER_NS)
    stub.chmod(stub.stat().st_mode | stat.S_IEXEC)
    (state / "invocations.log").touch()
    monkeypatch.setenv("PATH", f"{bin_dir}:{os.environ['PATH']}")
    return state / "invocations.log"


def _mesh_job(tmp_path):
    """Two docker tasks in ONE bridge-mode group: 'srv' binds loopback
    inside the driver-created namespace, 'cli' reaches it there."""
    result = tmp_path / "result.out"
    job = mock.job()
    job.constraints = []
    tg = job.task_groups[0]
    tg.count = 1
    tg.networks = [structs.NetworkResource(mode="bridge")]
    srv = tg.tasks[0]
    srv.name = "srv"
    srv.driver = "docker"
    srv.config = {
        "image": "busybox:1.36",
        "command": sys.executable,
        "args": ["-S", "-c", (
            "import socket\n"
            "s = socket.socket()\n"
            "s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)\n"
            "s.bind((\"127.0.0.1\", 9107))\n"
            "s.listen(2)\n"
            "while True:\n"
            "    c, _ = s.accept()\n"
            "    c.sendall(b\"pause-netns-hello\")\n"
            "    c.close()\n"
        )],
    }
    cli = srv.copy()
    cli.name = "cli"
    cli.config = {
        "image": "busybox:1.36",
        "command": sys.executable,
        "args": ["-S", "-c", (
            "import socket, time\n"
            "for _ in range(300):\n"          # generous under CI load
            "    try:\n"
            "        c = socket.create_connection((\"127.0.0.1\", 9107),"
            " timeout=2)\n"
            "        break\n"
            "    except OSError:\n"
            "        time.sleep(0.2)\n"
            "data = c.recv(100)\n"
            f"open({str(result)!r}, \"wb\").write(data)\n"
        )],
    }
    tg.tasks = [srv, cli]
    return job, result


class TestDriverNetwork:
    def test_group_tasks_share_driver_created_namespace(
            self, fake_docker_ns, tmp_path):
        job, result = _mesh_job(tmp_path)
        alloc = mock.alloc(job=job)
        alloc.id = str(uuid.uuid4())
        driver = DockerDriver(options={"docker.cleanup.image": "false"})
        runner = AllocRunner(
            alloc=alloc, drivers={"docker": driver},
            data_dir=str(tmp_path / "data"),
            on_alloc_update=lambda a: None)
        try:
            runner.run()
            assert runner.driver_network is not None, \
                "driver network manager not engaged for bridge group"
            spec = runner.driver_network[1]
            sandbox = spec.labels["docker_sandbox_container"]
            assert sandbox == f"nomad-pause-{alloc.id[:8]}"

            deadline = time.time() + 90        # generous under CI load
            while time.time() < deadline and not result.exists():
                time.sleep(0.2)
            assert result.exists(), "cli never reached srv over localhost"
            assert result.read_bytes() == b"pause-netns-hello"

            # both task containers joined the pause namespace
            log = fake_docker_ns.read_text()
            joins = [ln for ln in log.splitlines()
                     if f"--network container:{sandbox}" in ln]
            assert len(joins) == 2

            # the srv port is NOT reachable from the host loopback:
            # it lives inside the driver-created namespace
            import socket
            with pytest.raises(OSError):
                socket.create_connection(("127.0.0.1", 9107), timeout=1)
        finally:
            runner.stop("test done")
            runner.destroy()
        # pause namespace torn down with the alloc
        import subprocess
        out = subprocess.run(["ip", "netns", "list"],
                             capture_output=True, text=True)
        assert f"fdkns-{alloc.id[:8]}" not in out.stdout

    def test_restore_readopts_pause_network_and_destroy_reaps_it(
            self, fake_docker_ns, tmp_path):
        """Agent restart: the pause container outlives the agent; the
        restored runner re-adopts it (restarted tasks rejoin, destroy
        tears it down) instead of leaking it forever."""
        import subprocess

        driver = DockerDriver(options={"docker.cleanup.image": "false"})
        alloc_id = str(uuid.uuid4())
        spec = driver.create_network(alloc_id, [(25090, 9090)])
        assert spec.ip == "172.26.99.2"
        ns = f"fdkns-{alloc_id[:8]}"
        assert ns in subprocess.run(["ip", "netns", "list"],
                                    capture_output=True,
                                    text=True).stdout

        job, _ = _mesh_job(tmp_path)
        alloc = mock.alloc(job=job)
        alloc.id = alloc_id
        restored = AllocRunner(
            alloc=alloc, drivers={"docker": driver},
            data_dir=str(tmp_path / "data2"),
            on_alloc_update=lambda a: None)
        restored.restore()
        assert restored.driver_network is not None
        got = restored.driver_network[1]
        assert got.labels["docker_sandbox_container"] == \
            f"nomad-pause-{alloc_id[:8]}"
        assert got.ip == "172.26.99.2"
        restored.stop("test")
        restored.destroy()
        assert ns not in subprocess.run(["ip", "netns", "list"],
                                        capture_output=True,
                                        text=True).stdout

    def test_stale_pause_container_does_not_wedge_create(
            self, fake_docker_ns, tmp_path):
        """create_network is idempotent: a leftover pause sandbox from
        a crashed attempt is replaced, not a permanent name conflict."""
        driver = DockerDriver(options={"docker.cleanup.image": "false"})
        alloc_id = str(uuid.uuid4())
        s1 = driver.create_network(alloc_id, [])
        s2 = driver.create_network(alloc_id, [])   # stale survivor
        assert s2.labels == s1.labels
        driver.destroy_network(alloc_id, s2)
