"""UsageIndex invariants + eval-tensor fast-path parity.

The store's incrementally-scattered utilization planes (state/usage.py)
must always equal a from-scratch scan of live allocations, and the
scheduler's fast eval-tensor build (stack._accumulate_usage gather
path) must produce byte-identical planes to the slow per-alloc scan.
"""

import numpy as np

from nomad_tpu import mock
from nomad_tpu.scheduler.context import EvalContext
from nomad_tpu.scheduler.stack import XLAGenericStack
from nomad_tpu.state.store import StateStore
from nomad_tpu.structs import consts
from nomad_tpu.structs.eval_plan import Plan
from nomad_tpu.tensors.schema import ClusterTensors


def _scan_usage(store):
    """From-scratch expected planes keyed by node id."""
    out = {}
    for a in store.snapshot().allocs_iter():
        if a.terminal_status():
            continue
        cr = a.comparable_resources()
        cpu, mem = out.get(a.node_id, (0.0, 0.0))
        out[a.node_id] = (cpu + cr.cpu_shares, mem + cr.memory_mb)
    return out


class TestUsageIndex:
    def test_tracks_alloc_lifecycle(self):
        store = StateStore()
        nodes = [mock.node() for _ in range(3)]
        for n in nodes:
            store.upsert_node(n)
        allocs = [mock.alloc(node_id=nodes[i % 3].id) for i in range(9)]
        store.upsert_allocs(allocs)

        # stop one (desired transition to stop makes it terminal)
        store.stop_alloc(allocs[0].id, [])
        # client completes another
        done = allocs[1].copy_skip_job()
        done.client_status = consts.ALLOC_CLIENT_COMPLETE
        store.update_allocs_from_client([done])
        # GC a third
        store.delete_allocs([allocs[2].id])

        expected = _scan_usage(store)
        u = store.usage.planes_copy()
        for nid, (cpu, mem) in expected.items():
            row = u.rows[nid]
            assert u.used_cpu[row] == np.float32(cpu), nid
            assert u.used_mem[row] == np.float32(mem), nid
        # rows of nodes with no live allocs are zero
        for n in nodes:
            if n.id not in expected:
                row = u.rows[n.id]
                assert u.used_cpu[row] == 0

    def test_node_removal_zeroes_and_recycles_rows(self):
        store = StateStore()
        n1, n2 = mock.node(), mock.node()
        store.upsert_node(n1)
        store.upsert_node(n2)
        store.upsert_allocs([mock.alloc(node_id=n1.id)])
        row1 = store.usage.rows[n1.id]
        store.delete_node(n1.id)
        assert store.usage.used_cpu[row1] == 0
        n3 = mock.node()
        store.upsert_node(n3)
        assert store.usage.rows[n3.id] == row1  # recycled

    def test_dropped_node_alloc_teardown_cannot_go_negative(self):
        """A node deleted while its alloc lives must not get a
        poisoned (negative) row when the alloc later terminates."""
        store = StateStore()
        node = mock.node()
        store.upsert_node(node)
        a = mock.alloc(node_id=node.id)
        store.upsert_allocs([a])
        store.delete_node(node.id)
        store.delete_allocs([a.id])         # -1 delta, row is gone
        # re-register the same node id: fresh zeroed row
        node2 = mock.node()
        node2.id = node.id
        store.upsert_node(node2)
        u = store.usage.planes_copy()
        assert u.used_cpu[u.rows[node.id]] == 0

    def test_restore_rebuilds_planes(self):
        store = StateStore()
        node = mock.node()
        store.upsert_node(node)
        store.upsert_allocs([mock.alloc(node_id=node.id) for _ in range(4)])
        data = store.to_snapshot_bytes()
        fresh = StateStore()
        fresh.restore_from_bytes(data)
        u0 = store.usage.planes_copy()
        u1 = fresh.usage.planes_copy()
        r0, r1 = u0.rows[node.id], u1.rows[node.id]
        assert u0.used_cpu[r0] == u1.used_cpu[r1]
        assert u0.used_mem[r0] == u1.used_mem[r1]


class TestEvalTensorFastPathParity:
    def test_fast_and_slow_paths_agree(self):
        store = StateStore()
        nodes = [mock.node() for _ in range(6)]
        for n in nodes:
            store.upsert_node(n)
        job = mock.job()
        store.upsert_job(job)
        # background load from other jobs
        other = mock.job()
        store.upsert_job(other)
        store.upsert_allocs(
            [mock.alloc(node_id=nodes[i % 6].id, job_id=other.id,
                        namespace=other.namespace, job=other)
             for i in range(10)]
        )
        # live allocs of THIS job (feed job planes)
        own = [
            mock.alloc(node_id=nodes[i].id, job_id=job.id,
                       namespace=job.namespace, job=job,
                       task_group=job.task_groups[0].name)
            for i in range(3)
        ]
        store.upsert_allocs(own)

        snap = store.snapshot()
        plan = Plan()
        # stage one stop and one in-place update in the plan
        plan.append_stopped_alloc(own[0], "test stop")
        update = own[1].copy_skip_job()
        plan.append_alloc(update, None)

        tg = job.task_groups[0]

        def build(with_usage: bool):
            s = store.snapshot()
            if not with_usage:
                s.usage = None
            cluster = ClusterTensors.build(s.nodes())
            ctx = EvalContext(s, plan)
            st = XLAGenericStack(False, ctx, cluster)
            st.set_job(job)
            return st._build_eval_tensors(tg, np.zeros(cluster.n_pad, bool))

        fast = build(True)
        slow = build(False)
        for name in ("used_cpu", "used_mem", "used_disk", "used_cores",
                     "used_mbits", "job_tg_count", "job_any_count",
                     "base_mask", "avail_mbits", "free_dyn_delta"):
            f, s = getattr(fast, name), getattr(slow, name)
            assert np.array_equal(f, s), (name, f, s)


class TestPortPlane:
    """The per-node reserved-port bitmap plane (ISSUE 10): maintained
    from port_meta on alloc transitions, poisoned whenever the flat
    bitmap stops being provable."""

    def _ported_alloc(self, node_id, port, aid=None):
        from nomad_tpu.structs.network import Port
        from nomad_tpu.structs.resources import AllocatedSharedResources

        a = mock.alloc(node_id=node_id,
                       client_status=consts.ALLOC_CLIENT_RUNNING)
        if aid:
            a.id = aid
        a.allocated_resources.shared = AllocatedSharedResources(
            disk_mb=150, ports=[Port(label="p", value=port)])
        return a

    def test_add_and_remove_port_bits(self):
        store = StateStore()
        node = mock.node()
        store.upsert_node(node)
        a = self._ported_alloc(node.id, 8080)
        store.upsert_allocs([a])
        u = store.snapshot().usage
        row = u.rows[node.id]
        assert u.port_masks.get(row, 0) == 1 << 8080
        assert row not in u.port_dirty
        stop = a.copy_skip_job()
        stop.desired_status = consts.ALLOC_DESIRED_STOP
        store.upsert_allocs([stop])
        u = store.snapshot().usage
        assert u.port_masks.get(row, 0) == 0

    def test_overlapping_add_poisons_row(self):
        """Two live allocs sharing a port (the multi-address state a
        flat bitmap cannot express) poison the row — consumers fall
        back to the exact walk."""
        store = StateStore()
        node = mock.node()
        store.upsert_node(node)
        store.upsert_allocs([self._ported_alloc(node.id, 9000, "pa-1"),
                             self._ported_alloc(node.id, 9000, "pa-2")])
        u = store.snapshot().usage
        assert u.rows[node.id] in u.port_dirty

    def test_out_of_range_port_poisons_row(self):
        store = StateStore()
        node = mock.node()
        store.upsert_node(node)
        store.upsert_allocs([self._ported_alloc(node.id, 70000)])
        u = store.snapshot().usage
        assert u.rows[node.id] in u.port_dirty

    def test_devices_plane_counts(self):
        from nomad_tpu.structs.resources import AllocatedDeviceResource

        store = StateStore()
        node = mock.node()
        store.upsert_node(node)
        a = mock.alloc(node_id=node.id,
                       client_status=consts.ALLOC_CLIENT_RUNNING)
        a.allocated_resources.tasks["web"].devices.append(
            AllocatedDeviceResource(vendor="nvidia", type="gpu",
                                    name="t4", device_ids=["g0"]))
        store.upsert_allocs([a])
        u = store.snapshot().usage
        row = u.rows[node.id]
        assert int(u.used_devices[row]) == 1
        assert int(u.used_special[row]) == 1
