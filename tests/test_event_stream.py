"""The rebuilt event broker (ISSUE 11): shared-ring fan-out semantics.

Reference behavior: nomad/stream/event_buffer_test.go +
event_broker_test.go — one ring of immutable batches, per-subscriber
cursors, topic/key/namespace filtering at the consumer, and explicit
slow-consumer semantics (a subscriber that falls off the ring learns
it, with a resume index, instead of silently losing events).

The acceptance property lives here too: publish cost must be
independent of subscriber count (the seed broker did O(subscribers x
events) queue puts inside the FSM-apply path).
"""

import json
import socket
import threading
import time

import pytest

from nomad_tpu import mock, telemetry
from nomad_tpu.server import stream
from nomad_tpu.telemetry.histogram import STREAM_DELIVER, histograms


def _ev(topic=stream.TOPIC_JOB, etype="JobRegistered", key="j1",
        index=1, ns=""):
    return stream.Event(topic=topic, type=etype, key=key, index=index,
                        namespace=ns)


class TestRingSemantics:
    def test_shared_ring_fans_out_to_every_cursor(self):
        broker = stream.EventBroker()
        subs = [broker.subscribe({stream.TOPIC_JOB: ["*"]})
                for _ in range(5)]
        broker.publish([_ev(key="a", index=1), _ev(key="b", index=2)])
        for sub in subs:
            got = sub.next_events(timeout=1.0)
            assert [e.key for e in got] == ["a", "b"]

    def test_key_filter_at_consumer(self):
        broker = stream.EventBroker()
        sub = broker.subscribe({stream.TOPIC_JOB: ["wanted"]})
        broker.publish([_ev(key="other", index=1)])
        broker.publish([_ev(key="wanted", index=2)])
        got = sub.next_events(timeout=1.0)
        assert [e.key for e in got] == ["wanted"]
        # the cursor advanced PAST the filtered batch: nothing replays
        assert sub.next_events(timeout=0.05) == []

    def test_namespace_filter_at_consumer(self):
        broker = stream.EventBroker()
        sub = broker.subscribe({stream.TOPIC_ALL: ["*"]},
                               namespaces={"default"})
        broker.publish([_ev(index=1, ns="secret"),
                        _ev(key="mine", index=2, ns="default"),
                        # namespace-less (Node-style) events always pass
                        _ev(topic=stream.TOPIC_NODE, etype="NodeUpdate",
                            key="n1", index=3)])
        got = sub.next_events(timeout=1.0)
        assert [(e.key, e.namespace) for e in got] == \
            [("mine", "default"), ("n1", "")]

    def test_tail_subscription_sees_only_new_events(self):
        broker = stream.EventBroker()
        broker.publish([_ev(key="old", index=1)])
        sub = broker.subscribe({stream.TOPIC_ALL: ["*"]})
        broker.publish([_ev(key="new", index=2)])
        got = sub.next_events(timeout=1.0)
        assert [e.key for e in got] == ["new"]

    def test_resume_from_index_replays_retained_ring(self):
        broker = stream.EventBroker()
        for i in range(1, 6):
            broker.publish([_ev(key=f"j{i}", index=i)])
        sub = broker.subscribe({stream.TOPIC_ALL: ["*"]}, from_index=2)
        got = sub.next_events(timeout=1.0, max_events=100)
        assert [e.key for e in got] == ["j3", "j4", "j5"]

    def test_max_events_capped_even_inside_one_giant_batch(self):
        """A group-committed apply can publish one batch with hundreds
        of events (the heartbeat fan-in batcher makes this the normal
        storm shape): next_events must honor max_events by parking the
        cursor INSIDE the batch and resuming there, not overshoot."""
        broker = stream.EventBroker(buffer_size=1024)
        sub = broker.subscribe({stream.TOPIC_ALL: ["*"]})
        broker.publish([_ev(key=f"a{i}", index=1) for i in range(150)])
        first = sub.next_events(timeout=1.0, max_events=64)
        assert len(first) == 64
        rest = sub.next_events(timeout=1.0, max_events=1000)
        assert len(rest) == 86
        assert [e.key for e in first + rest] == \
            [f"a{i}" for i in range(150)]
        # nothing replays after the partial-batch resume
        assert sub.next_events(timeout=0.05) == []

    def test_close_wakes_parked_reader_immediately(self):
        broker = stream.EventBroker()
        sub = broker.subscribe({stream.TOPIC_ALL: ["*"]})
        done = threading.Event()

        def consume():
            sub.next_events(timeout=30.0)
            done.set()

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        time.sleep(0.1)
        sub.close()
        # the reader returns on the close notify, not the 30s timeout
        assert done.wait(timeout=2.0)

    def test_blocking_wait_wakes_on_publish(self):
        broker = stream.EventBroker()
        sub = broker.subscribe({stream.TOPIC_ALL: ["*"]})
        got = []

        def consume():
            got.extend(sub.next_events(timeout=5.0))

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        time.sleep(0.05)
        broker.publish([_ev(index=1)])
        t.join(timeout=5.0)
        assert [e.index for e in got] == [1]


class TestSlowConsumerSemantics:
    def test_fallen_off_ring_gets_lost_marker_with_resume_index(self):
        broker = stream.EventBroker(buffer_size=10)
        sub = broker.subscribe({stream.TOPIC_ALL: ["*"]})
        for i in range(1, 31):
            broker.publish([_ev(key=f"j{i}", index=i)])
        got = sub.next_events(timeout=1.0, max_events=100)
        assert got[0].topic == stream.TOPIC_LOST
        assert got[0].payload["LostEvents"] == 20
        # resume index = the oldest event still retained
        assert got[0].payload["ResumeIndex"] == 21
        # the retained tail follows the marker, gap-free from there
        assert [e.index for e in got[1:]] == list(range(21, 31))
        assert sub.lost_events == 20
        assert broker.snapshot()["lost_events"] == 20

    def test_resume_past_trimmed_history_flags_unknown_gap(self):
        broker = stream.EventBroker(buffer_size=4)
        for i in range(1, 11):
            broker.publish([_ev(key=f"j{i}", index=i)])
        sub = broker.subscribe({stream.TOPIC_ALL: ["*"]}, from_index=2)
        got = sub.next_events(timeout=1.0, max_events=100)
        assert got[0].topic == stream.TOPIC_LOST
        # the broker cannot know how many trimmed events matched: -1
        assert got[0].payload["LostEvents"] == -1
        assert got[0].payload["ResumeIndex"] == 7
        assert [e.index for e in got[1:]] == [7, 8, 9, 10]

    def test_resume_within_ring_has_no_marker(self):
        broker = stream.EventBroker(buffer_size=100)
        for i in range(1, 6):
            broker.publish([_ev(key=f"j{i}", index=i)])
        sub = broker.subscribe({stream.TOPIC_ALL: ["*"]}, from_index=3)
        got = sub.next_events(timeout=1.0, max_events=100)
        assert all(e.topic != stream.TOPIC_LOST for e in got)
        assert [e.index for e in got] == [4, 5]


class TestPublishCost:
    def test_publish_cost_independent_of_subscriber_count(self):
        """THE acceptance property: per-publish wall with 10k idle
        subscribers within noise of 1 subscriber. The seed broker's
        O(subscribers x events) publish fails this by ~3 orders of
        magnitude; the ring's publish does zero per-subscriber work,
        so a generous 5x + absolute-slack bound is still conclusive
        while staying robust to CI-neighbor noise."""
        def per_publish_s(n_subs: int, n_pub: int = 400) -> float:
            broker = stream.EventBroker(buffer_size=512)
            for _ in range(n_subs):
                broker.subscribe({stream.TOPIC_JOB: ["*"]})
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                for i in range(n_pub):
                    broker.publish([_ev(index=i + 1)])
                best = min(best,
                           (time.perf_counter() - t0) / n_pub)
            return best

        solo = per_publish_s(1)
        fleet = per_publish_s(10_000)
        assert fleet <= solo * 5 + 50e-6, (solo, fleet)

    def test_publish_with_parked_waiters_delivers_everywhere(self):
        """Fan-out correctness under the O(1) publish: concurrent
        parked consumers all see every matching event, in publish
        order, exactly once."""
        broker = stream.EventBroker()
        n_subs, n_events = 8, 50
        subs = [broker.subscribe({stream.TOPIC_ALL: ["*"]})
                for _ in range(n_subs)]
        got = [[] for _ in range(n_subs)]

        def consume(k):
            while len(got[k]) < n_events:
                evs = subs[k].next_events(timeout=5.0, max_events=16)
                if not evs:
                    return
                got[k].extend(e.index for e in evs)

        threads = [threading.Thread(target=consume, args=(k,),
                                    daemon=True)
                   for k in range(n_subs)]
        for t in threads:
            t.start()
        for i in range(n_events):
            broker.publish([_ev(index=i + 1)])
        for t in threads:
            t.join(timeout=10.0)
        for k in range(n_subs):
            assert got[k] == list(range(1, n_events + 1)), k


class TestDeliveryTelemetry:
    def test_deliver_lag_histogram_records_from_publish_stamp(self):
        histograms.reset()
        broker = stream.EventBroker()
        sub = broker.subscribe({stream.TOPIC_ALL: ["*"]})
        stamp = time.monotonic() - 0.5   # apply happened 500ms ago
        broker.publish([_ev(index=1)], stamp=stamp)
        sub.next_events(timeout=1.0)
        h = histograms.peek(STREAM_DELIVER)
        assert h is not None and h.count == 1
        # the lag includes the pre-publish 500ms (FSM stamp anchors it)
        assert h.snapshot()["p50_ms"] >= 400.0
        histograms.reset()

    def test_stream_spans_emitted_when_tracing(self):
        was = telemetry.enabled()
        telemetry.enable()
        telemetry.reset()
        try:
            broker = stream.EventBroker()
            sub = broker.subscribe({stream.TOPIC_ALL: ["*"]})
            broker.publish([_ev(index=1)])
            sub.next_events(timeout=1.0)
            from nomad_tpu.telemetry.trace import tracer

            totals = tracer.stage_totals()
            assert "stream.publish" in totals
            assert "stream.deliver" in totals
        finally:
            telemetry.reset()
            if not was:
                telemetry.disable()

    def test_snapshot_and_reset_stats_window(self):
        broker = stream.EventBroker()
        sub = broker.subscribe({stream.TOPIC_ALL: ["*"]})
        broker.publish([_ev(index=1), _ev(key="j2", index=1)])
        sub.next_events(timeout=1.0)
        s = broker.snapshot()
        assert s["published_events"] == 2
        assert s["delivered_events"] == 2
        assert s["subscribers"] == 1
        broker.reset_stats()
        s = broker.snapshot()
        assert s["published_events"] == 0
        assert s["delivered_events"] == 0
        # the ring itself survives the stats window
        assert s["retained_events"] == 2
        broker.note_delivered_bytes(123)
        assert broker.snapshot()["delivered_bytes"] == 123

    def test_max_lag_tracks_laggard(self):
        broker = stream.EventBroker()
        fast = broker.subscribe({stream.TOPIC_ALL: ["*"]})
        broker.subscribe({stream.TOPIC_ALL: ["*"]})     # never drains
        for i in range(5):
            broker.publish([_ev(index=i + 1)])
        fast.next_events(timeout=1.0, max_events=100)
        assert broker.snapshot()["max_lag_events"] == 5


def _open_stream(addr: str, path: str = "/v1/event/stream"):
    """Raw chunked NDJSON reader (no-ACL agent); returns
    (socket, status line, line iterator)."""
    host, port = addr.rsplit(":", 1)
    host = host.replace("http://", "")
    s = socket.create_connection((host, int(port)), timeout=30)
    s.sendall((
        f"GET {path} HTTP/1.1\r\nHost: {host}\r\n\r\n"
    ).encode())
    f = s.makefile("rb")
    status = f.readline().decode()
    while f.readline().strip():
        pass

    def lines():
        while True:
            size = f.readline().strip()
            if not size:
                return
            try:
                n = int(size, 16)
            except ValueError:
                return
            if n == 0:
                return
            data = f.read(n)
            f.read(2)
            for ln in data.splitlines():
                if ln.strip():
                    yield ln

    return s, status, lines()


@pytest.fixture()
def agent():
    from nomad_tpu.api.agent import Agent, AgentConfig

    a = Agent(AgentConfig.dev())
    a.start()
    try:
        yield a
    finally:
        a.shutdown()


class TestNDJSONResume:
    def _read_batches(self, lines, want_keys, deadline_s=10.0):
        """Collect event batches until every key in ``want_keys`` was
        seen (keepalive {} lines are skipped)."""
        got, last_index = [], 0
        deadline = time.time() + deadline_s
        for ln in lines:
            batch = json.loads(ln)
            if not batch:
                if time.time() > deadline:
                    break
                continue
            last_index = batch["Index"]
            got.extend(batch.get("Events") or [])
            if want_keys <= {e.get("Key") for e in got}:
                break
            if time.time() > deadline:
                break
        return got, last_index

    def test_reconnect_with_index_sees_no_gap(self, agent):
        server = agent.server
        s, status, lines = _open_stream(agent.http.addr)
        assert " 200 " in status
        j1 = mock.job()
        j1.id = "job-before-drop"
        server.job_register(j1)
        got, last_index = self._read_batches(lines, {"job-before-drop"})
        assert any(e.get("Key") == "job-before-drop" for e in got)
        s.close()                                  # subscriber drops
        j2 = mock.job()
        j2.id = "job-while-away"
        server.job_register(j2)
        # reconnect resuming from the last Index it saw: the ring
        # replays the missed events — no gap, no duplicate
        s, status, lines = _open_stream(
            agent.http.addr, f"/v1/event/stream?index={last_index}")
        assert " 200 " in status
        try:
            got, _ = self._read_batches(lines, {"job-while-away"})
            keys = [e.get("Key") for e in got
                    if e.get("Topic") == "Job"]
            assert "job-while-away" in keys
            assert "job-before-drop" not in keys   # not replayed twice
            assert all(e.get("Topic") != "LostEvents" for e in got)
        finally:
            s.close()

    def test_reconnect_past_trimmed_ring_gets_lost_marker(self, agent):
        server = agent.server
        s, status, lines = _open_stream(agent.http.addr)
        assert " 200 " in status
        j1 = mock.job()
        j1.id = "job-first"
        server.job_register(j1)
        got, last_index = self._read_batches(lines, {"job-first"})
        s.close()
        # shrink the ring and blow past it while disconnected
        server.event_broker.buffer_size = 8
        for i in range(40):
            j = mock.job()
            j.id = f"job-flood-{i}"
            server.job_register(j)
        s, status, lines = _open_stream(
            agent.http.addr, f"/v1/event/stream?index={last_index}")
        assert " 200 " in status
        try:
            got, _ = self._read_batches(lines, {"job-flood-39"})
            # the gap is EXPLICIT: a LostEvents marker with the resume
            # index, then the retained tail
            lost = [e for e in got if e.get("Topic") == "LostEvents"]
            assert lost, [e.get("Key") for e in got][:5]
            assert lost[0]["Payload"]["ResumeIndex"] > last_index
        finally:
            s.close()

class TestFailoverReconnect:
    """ISSUE 12 satellite: /v1/event/stream reconnect semantics across
    a LEADER FAILOVER. Every replica's FSM publishes every committed
    apply into its own ring, so a subscriber that loses its leader
    resumes on the new one with ``?index=<last seen>`` and gets either
    a gap-free replay from the new leader's ring or an explicit
    LostEvents marker — never a silent gap. (The HTTP-level resume
    plumbing is covered by TestNDJSONResume; this exercises the same
    subscribe(from_index=...) path the endpoint calls, against the
    surviving server.)"""

    def _make_cluster(self):
        from nomad_tpu.server.server import ServerConfig
        from nomad_tpu.server.testing import make_cluster, wait_for_leader

        servers, registry = make_cluster(3, ServerConfig(
            num_workers=0, heartbeat_ttl=60.0))
        return servers, registry, wait_for_leader(servers, timeout=10.0)

    def _drain(self, sub, want, timeout=10.0):
        got = []
        deadline = time.time() + timeout
        while time.time() < deadline:
            got.extend(sub.next_events(timeout=0.2, max_events=256))
            if want(got):
                break
        return got

    def test_resume_on_new_leader_ring_is_gap_free(self):
        from nomad_tpu.server.testing import wait_for_leader

        servers, registry, leader = self._make_cluster()
        try:
            sub = leader.event_broker.subscribe({stream.TOPIC_ALL: ["*"]})
            before = [mock.node() for _ in range(3)]
            for n in before:
                leader.node_register(n)
            got = self._drain(
                sub, lambda g: {n.id for n in before} <=
                {e.key for e in g})
            last_index = max(e.index for e in got)
            sub.close()
            # the leader dies outright
            leader.shutdown()
            rest = [s for s in servers if s is not leader]
            new_leader = wait_for_leader(rest, timeout=10.0)
            after = [mock.node() for _ in range(2)]
            for n in after:
                new_leader.node_register(n)
            # resume on the NEW leader's ring from the last index the
            # old stream served: replay is gap-free, no marker, no
            # duplicates of what was already seen
            sub2 = new_leader.event_broker.subscribe(
                {stream.TOPIC_ALL: ["*"]}, from_index=last_index)
            got2 = self._drain(
                sub2, lambda g: {n.id for n in after} <=
                {e.key for e in g})
            assert all(e.topic != stream.TOPIC_LOST for e in got2), \
                [e.topic for e in got2]
            assert all(e.index > last_index for e in got2)
            assert {n.id for n in before} & {e.key for e in got2} \
                == set(), "pre-failover events replayed twice"
            sub2.close()
        finally:
            registry.heal()
            for s in servers:
                try:
                    s.shutdown()
                except Exception:               # noqa: BLE001
                    pass

    def test_resume_past_new_leaders_trimmed_ring_gets_marker(self):
        from nomad_tpu.server.testing import wait_for_leader

        servers, registry, leader = self._make_cluster()
        try:
            sub = leader.event_broker.subscribe({stream.TOPIC_ALL: ["*"]})
            first = mock.node()
            leader.node_register(first)
            got = self._drain(sub, lambda g: any(
                e.key == first.id for e in g))
            last_index = max(e.index for e in got)
            sub.close()
            leader.shutdown()
            rest = [s for s in servers if s is not leader]
            new_leader = wait_for_leader(rest, timeout=10.0)
            # shrink the survivor's ring and blow past it while away
            new_leader.event_broker.buffer_size = 4
            for _ in range(24):
                new_leader.node_register(mock.node())
            sub2 = new_leader.event_broker.subscribe(
                {stream.TOPIC_ALL: ["*"]}, from_index=last_index)
            got2 = self._drain(sub2, lambda g: len(g) >= 1)
            # the gap is EXPLICIT: LostEvents first, with a resume index
            assert got2[0].topic == stream.TOPIC_LOST
            assert got2[0].payload["ResumeIndex"] > last_index
            sub2.close()
        finally:
            registry.heal()
            for s in servers:
                try:
                    s.shutdown()
                except Exception:               # noqa: BLE001
                    pass


class TestNDJSONKeepalive:
    @pytest.mark.slow
    def test_idle_stream_sends_keepalive_newlines(self, agent):
        s, status, lines = _open_stream(agent.http.addr)
        assert " 200 " in status
        try:
            t0 = time.time()
            ln = next(lines)                       # blocks until data
            assert json.loads(ln) == {}            # keepalive, not data
            assert time.time() - t0 < 12.0
        finally:
            s.close()
