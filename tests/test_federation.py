"""Multi-region federation tests.

Modeled on reference rpc.go:537-707 region forwarding,
region_endpoint_test.go, and leader.go:1347 ACL replication.
"""

import time

import pytest

from nomad_tpu import mock
from nomad_tpu.api.agent import Agent, AgentConfig
from nomad_tpu.api.client import APIClient, APIError, QueryOptions


def two_regions():
    east = Agent(AgentConfig(name="east-1", region="east", num_schedulers=0))
    west = Agent(AgentConfig(name="west-1", region="west", num_schedulers=0))
    east.start()
    west.start()
    # WAN join both ways
    east.server.join_region("west", west.http.addr)
    west.server.join_region("east", east.http.addr)
    return east, west


class TestFederation:
    def test_regions_list(self):
        east, west = two_regions()
        try:
            api = APIClient(east.http.addr)
            assert api.get("/v1/regions") == ["east", "west"]
        finally:
            east.shutdown()
            west.shutdown()

    def test_forwarding_reads_other_region(self):
        east, west = two_regions()
        try:
            job = mock.job()
            west.server.job_register(job)
            api = APIClient(east.http.addr)
            # local region: job not found
            local = api.jobs.list()
            assert all(j["ID"] != job.id for j in local)
            # ?region=west forwards to the west server
            remote = api.jobs.list(q=QueryOptions(region="west"))
            assert any(j["ID"] == job.id for j in remote)
        finally:
            east.shutdown()
            west.shutdown()

    def test_forwarding_writes_other_region(self):
        east, west = two_regions()
        try:
            api = APIClient(east.http.addr, region="west")
            api.namespaces.register("team-a", "cross-region write")
            assert west.server.state.namespace_by_name("team-a") is not None
            assert east.server.state.namespace_by_name("team-a") is None
        finally:
            east.shutdown()
            west.shutdown()

    def test_unknown_region_rejected(self):
        east, west = two_regions()
        try:
            api = APIClient(east.http.addr, region="mars")
            with pytest.raises(APIError) as e:
                api.jobs.list()
            assert "No path to region" in str(e.value)
        finally:
            east.shutdown()
            west.shutdown()

    def test_join_over_http(self):
        east = Agent(AgentConfig(name="e", region="east", num_schedulers=0))
        west = Agent(AgentConfig(name="w", region="west", num_schedulers=0))
        east.start()
        west.start()
        try:
            api = APIClient(east.http.addr)
            api.put("/v1/agent/join", q=QueryOptions(params={
                "address": west.http.addr, "join_region": "west",
            }))
            assert api.get("/v1/regions") == ["east", "west"]
        finally:
            east.shutdown()
            west.shutdown()


class TestACLReplication:
    def test_policies_and_global_tokens_replicate(self):
        from nomad_tpu.acl.policy import ACLPolicy, ACLToken
        from nomad_tpu.server import fsm as fsm_msgs

        auth = Agent(AgentConfig(name="auth-1", region="authority",
                                 num_schedulers=0))
        auth.start()
        replica = Agent(AgentConfig(name="rep-1", region="replica",
                                    num_schedulers=0))
        replica.start()
        try:
            replica.server.config.authoritative_region = "authority"
            replica.server.join_region("authority", auth.http.addr)

            policy = ACLPolicy(name="readers", rules='namespace "*" '
                               '{ policy = "read" }')
            auth.server.raft_apply(fsm_msgs.ACL_POLICY_UPSERT,
                                   {"policies": [policy]})
            gtok = ACLToken.create(name="g", type="management", global_=True)
            ltok = ACLToken.create(name="l", type="management", global_=False)
            auth.server.raft_apply(fsm_msgs.ACL_TOKEN_UPSERT,
                                   {"tokens": [gtok, ltok]})

            n = replica.server.replicate_acl_once()
            assert n >= 2
            got = replica.server.state.acl_policy_by_name("readers")
            assert got is not None and "read" in got.rules
            assert replica.server.state.acl_token_by_accessor(
                gtok.accessor_id) is not None
            # local tokens never replicate
            assert replica.server.state.acl_token_by_accessor(
                ltok.accessor_id) is None

            # steady state: a second pass applies nothing
            assert replica.server.replicate_acl_once() == 0

            # revocation in the authority propagates (diff-and-delete)
            auth.server.raft_apply(
                fsm_msgs.ACL_TOKEN_DELETE,
                {"accessor_ids": [gtok.accessor_id]},
            )
            auth.server.raft_apply(
                fsm_msgs.ACL_POLICY_DELETE, {"names": ["readers"]}
            )
            assert replica.server.replicate_acl_once() == 2
            assert replica.server.state.acl_token_by_accessor(
                gtok.accessor_id) is None
            assert replica.server.state.acl_policy_by_name("readers") is None
        finally:
            auth.shutdown()
            replica.shutdown()

    def test_regions_survive_snapshot_restore(self):
        from nomad_tpu.server.server import Server, ServerConfig

        server = Server(ServerConfig(num_workers=0, region="east"))
        server.start()
        try:
            server.join_region("west", "http://west:4646")
            data = server.state.to_snapshot_bytes()
            fresh = Server(ServerConfig(num_workers=0, region="east"))
            fresh.state.restore_from_bytes(data)
            assert fresh.region_addr("west") == "http://west:4646"
        finally:
            server.shutdown()


class TestRetryJoin:
    """WAN auto-join (serf retry_join analog, agent.go retryJoin): an
    agent configured with region@url entries keeps retrying until the
    peer answers — including peers that start AFTER it."""

    def test_joins_peer_that_starts_later(self):
        import socket
        import time

        from nomad_tpu.api.agent import Agent, AgentConfig

        # reserve the west agent's port before it exists
        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        west_port = probe.getsockname()[1]
        probe.close()

        east = Agent(AgentConfig(
            name="rj-east", region="east",
            retry_join=[f"west@http://127.0.0.1:{west_port}"],
            retry_join_interval=0.2,
        ))
        east.start()
        west = None
        try:
            # east is up; west does not exist yet -> no join recorded
            time.sleep(0.6)
            assert east.server.region_addr("west") is None

            west = Agent(AgentConfig(
                name="rj-west", region="west", http_port=west_port))
            west.start()
            deadline = time.time() + 15
            while time.time() < deadline:
                if east.server.region_addr("west"):
                    break
                time.sleep(0.1)
            assert east.server.region_addr("west") == \
                f"http://127.0.0.1:{west_port}"
        finally:
            east.shutdown()
            if west is not None:
                west.shutdown()

    def test_config_file_server_join_stanza(self, tmp_path):
        from nomad_tpu.api.config_file import load_config_files

        p = tmp_path / "agent.hcl"
        p.write_text("""
server {
  enabled = true
  server_join {
    retry_join     = ["west@http://h2:4646", "eu@https://h3:4646"]
    retry_max      = 12
    retry_interval = "30s"
  }
}
""")
        cfg = load_config_files([str(p)])
        assert cfg.retry_join == ["west@http://h2:4646",
                                  "eu@https://h3:4646"]
        assert cfg.retry_join_max_attempts == 12
        assert cfg.retry_join_interval == 30.0
