"""Fused wave mega-kernel tests (ISSUE 19).

The fused program (ops/pallas_kernel.fused_wave_place) runs the whole
wave — feasibility, scoring, the per-step capacity-carry scan, top-k —
as ONE pallas dispatch whose body calls the SAME
place_taskgroups_joint the composite program jits, so parity with the
composite must be BITWISE, not approximate, across the supported
feature lattice (ports, preemption penalties, preferred pins,
distinct_hosts, shuffle, padded shapes). Tests run the kernel in
interpret mode (tests force CPU) — the exact program the TPU path
dispatches. The sharded mirror runs on the conftest 8-virtual-device
mesh through parallel/sharded.fused_sharded_entry.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nomad_tpu.ops.kernel import (
    FUSED_METRIC_FIELDS,
    MAX_PENALTY_NODES,
    TOPK,
    KernelIn,
    LEAN_FEATURES,
    build_kernel_in,
    fused_wave_supported,
    pad_steps,
    place_taskgroups_joint_jit,
    unpack_fused_wave,
)
from nomad_tpu.ops.pallas_kernel import fused_wave_place_jit
from nomad_tpu.parallel import coalesce
from nomad_tpu.parallel.synthetic import synthetic_cluster, synthetic_eval

K = 4
B = 4

#: the fused envelope's feature lattice, each variant pinned to a node
#: count in a DIFFERENT pad bucket so padded shapes ride along (n_real
#: strictly below n_pad everywhere)
_VARIANTS = (
    ("lean", 60),
    ("shuffle", 200),
    ("penalty_preferred", 383),
    ("distinct", 60),
    ("ports", 200),
    ("kitchen_sink", 383),
)


def _variant_features(variant):
    f = LEAN_FEATURES._replace(with_topk=True)
    if variant in ("shuffle", "penalty_preferred", "kitchen_sink"):
        f = f._replace(with_shuffle=True)
    if variant in ("penalty_preferred", "kitchen_sink"):
        f = f._replace(with_step_penalties=True, with_preferred=True)
    if variant in ("distinct", "kitchen_sink"):
        f = f._replace(with_distinct=True)
    if variant in ("ports", "kitchen_sink"):
        f = f._replace(with_ports=True)
    assert fused_wave_supported(f)
    return f


def _wave_members(seed, variant, n_nodes):
    """B randomized member kins + the variant's features."""
    rng = np.random.default_rng(seed * 1000 + n_nodes)
    cluster = synthetic_cluster(
        n_nodes, cpu=3900.0, mem=7936.0, disk=98304.0,
        seed=int(rng.integers(0, 99)))
    n_pad = cluster.n_pad
    kp = pad_steps(K)
    kins = []
    for _ in range(B):
        ev = synthetic_eval(cluster, desired_count=K)
        kwargs = {"node_perm": rng.permutation(n_pad).astype(np.int32)}
        if variant in ("penalty_preferred", "kitchen_sink"):
            pen = np.full((kp, MAX_PENALTY_NODES), -1, np.int32)
            pen[0, 0] = rng.integers(0, n_nodes)
            pen[1, 0] = rng.integers(0, n_nodes)
            pref = np.full(kp, -1, np.int32)
            pref[int(rng.integers(0, K))] = rng.integers(0, n_nodes)
            kwargs.update(step_penalty=pen, step_preferred=pref)
        kin = build_kernel_in(cluster, ev, K, **kwargs)
        uc = (3900.0 * 0.6 * rng.random(n_pad)).astype(np.float32)
        um = (7936.0 * 0.6 * rng.random(n_pad)).astype(np.float32)
        kin = kin._replace(
            used_cpu=uc, used_mem=um,
            ask_cpu=np.float32(rng.choice([250, 500, 900])),
            ask_mem=np.float32(rng.choice([128, 256, 700])))
        if variant in ("ports", "kitchen_sink"):
            kin = kin._replace(
                port_conflict=(rng.random(n_pad) < 0.3),
                ask_has_reserved_ports=np.asarray(True),
                ask_dyn_ports=np.asarray(2, np.int32))
        if variant in ("distinct", "kitchen_sink"):
            kin = kin._replace(
                job_tg_count=rng.integers(0, 2, n_pad).astype(np.int32),
                job_any_count=rng.integers(0, 3, n_pad).astype(np.int32),
                distinct_hosts_job=np.asarray(
                    variant == "kitchen_sink"),
                distinct_hosts_tg=np.asarray(True))
        kins.append(kin)
    return kins, _variant_features(variant)


def _stack_wave(kins):
    stacked = KernelIn(*[
        np.stack([np.asarray(getattr(k, f)) for k in kins])
        for f in KernelIn._fields])
    t_pad = pad_steps(len(kins) * K)
    step_member = np.full(t_pad, -1, np.int32)
    step_local = np.zeros(t_pad, np.int32)
    for i in range(len(kins)):
        step_member[i * K:(i + 1) * K] = i
        step_local[i * K:(i + 1) * K] = np.arange(K)
    return stacked, step_member, step_local, t_pad


def _assert_bitwise(fo, ref, t_pad, b, ctx=""):
    host = unpack_fused_wave(np.asarray(fo.packed), t_pad, b)
    np.testing.assert_array_equal(
        host["chosen"], np.asarray(ref.chosen), err_msg=f"chosen {ctx}")
    np.testing.assert_array_equal(
        host["found"], np.asarray(ref.found), err_msg=f"found {ctx}")
    # scores BITWISE, not allclose: same program, same math
    np.testing.assert_array_equal(
        host["scores"], np.asarray(ref.scores), err_msg=f"scores {ctx}")
    for name in FUSED_METRIC_FIELDS:
        np.testing.assert_array_equal(
            host[name], np.asarray(getattr(ref, name)),
            err_msg=f"{name} {ctx}")
    np.testing.assert_array_equal(
        np.asarray(fo.topk_idx), np.asarray(ref.topk_idx),
        err_msg=f"topk_idx {ctx}")
    np.testing.assert_array_equal(
        np.asarray(fo.topk_scores), np.asarray(ref.topk_scores),
        err_msg=f"topk_scores {ctx}")
    for nm in ("a_cpu", "a_mem", "a_disk"):
        np.testing.assert_array_equal(
            np.asarray(getattr(fo, nm)), np.asarray(getattr(ref, nm)),
            err_msg=f"{nm} {ctx}")
    return host


def _run_parity_seed(seed):
    variant, n_nodes = _VARIANTS[seed % len(_VARIANTS)]
    kins, feats = _wave_members(seed, variant, n_nodes)
    stacked, sm, sl, t_pad = _stack_wave(kins)
    ref = place_taskgroups_joint_jit(
        stacked, jnp.asarray(sm), jnp.asarray(sl), t_pad, feats)
    fo = fused_wave_place_jit(
        stacked, jnp.asarray(sm), jnp.asarray(sl), t_pad, feats)
    host = _assert_bitwise(fo, ref, t_pad, B, ctx=f"seed={seed} "
                           f"variant={variant}")
    return host


class TestFusedParity:
    """Property suite: fused == composite, bit for bit, across the
    lattice. Variant and pad bucket cycle with the seed."""

    @pytest.mark.parametrize("seed", range(25))
    def test_bit_identity_across_lattice(self, seed):
        _run_parity_seed(seed)

    @pytest.mark.slow
    @pytest.mark.parametrize("seed", range(25, 200))
    def test_bit_identity_across_lattice_slow(self, seed):
        _run_parity_seed(seed)

    def test_some_seed_actually_places(self):
        host = _run_parity_seed(0)
        assert host["found"].any()


class TestFusedShardedParity:
    """The sharded mirror: fused_sharded_entry's shard_map program on
    the conftest 8-virtual-device mesh vs the single-device composite
    — same bitwise bar, per variant."""

    @pytest.fixture()
    def mesh(self):
        from nomad_tpu.parallel.sharded import wave_mesh as make

        assert len(jax.devices()) >= 8, \
            "conftest must force 8 CPU devices"
        return make(8)

    @pytest.mark.parametrize("seed", range(len(_VARIANTS)))
    def test_sharded_bit_identity(self, seed, mesh):
        from nomad_tpu.parallel.sharded import fused_sharded_entry

        variant, n_nodes = _VARIANTS[seed]
        kins, feats = _wave_members(seed + 77, variant, n_nodes)
        stacked, sm, sl, t_pad = _stack_wave(kins)
        n_pad = stacked.cap_cpu.shape[-1]
        assert n_pad % mesh.size == 0
        assert n_pad // mesh.size >= TOPK, "local top-k merge floor"
        ref = place_taskgroups_joint_jit(
            stacked, jnp.asarray(sm), jnp.asarray(sl), t_pad, feats)
        fn, kin_sh, repl = fused_sharded_entry(mesh)
        kin_dev = KernelIn(*[jax.device_put(x, s)
                             for x, s in zip(stacked, kin_sh)])
        fo = fn(kin_dev, jax.device_put(sm, repl),
                jax.device_put(sl, repl), t_pad, feats)
        _assert_bitwise(fo, ref, t_pad, B,
                        ctx=f"sharded variant={variant}")

    def test_launch_wave_sharded_zero_fallbacks(self, mesh):
        """launch_wave over the mesh must take the fused sharded path
        (fused launches counted, zero fused fallbacks, zero unsharded
        fallbacks) and match the single-device composite exactly."""
        from nomad_tpu import telemetry

        kins, feats0 = _wave_members(5, "shuffle", 200)
        steps = [K] * len(kins)
        feats = [feats0] * len(kins)

        prior = coalesce.fused_wave_enabled()
        telemetry.enable()
        telemetry.reset()
        try:
            coalesce.configure_fused_wave(False)
            single = coalesce.launch_wave(kins, steps, feats)
            coalesce.configure_fused_wave(True)
            coalesce.fused_wave_stats.reset()
            coalesce.sharded_wave_stats.reset()
            sharded = coalesce.launch_wave(kins, steps, feats,
                                           mesh=mesh)
            fused = coalesce.fused_wave_stats.snapshot()
            sw = coalesce.sharded_wave_stats.snapshot()
        finally:
            coalesce.configure_fused_wave(prior)
            telemetry.disable()
            telemetry.reset()
        assert fused["launches"] == 1 and fused["fallbacks"] == 0
        assert sw["fallbacks"] == 0
        for s, m in zip(single, sharded):
            np.testing.assert_array_equal(np.asarray(s.chosen),
                                          np.asarray(m.chosen))
            np.testing.assert_array_equal(np.asarray(s.found),
                                          np.asarray(m.found))
            np.testing.assert_array_equal(np.asarray(s.scores),
                                          np.asarray(m.scores))
            np.testing.assert_array_equal(np.asarray(s.topk_idx),
                                          np.asarray(m.topk_idx))
        assert any(np.asarray(s.found).any() for s in single)


class TestFusedLaunchWave:
    """Routing: the launcher runs fused waves at ONE dispatch each,
    falls back (counted) outside the envelope, and never diverges."""

    def test_single_device_fused_matches_composite(self):
        from nomad_tpu import telemetry
        from nomad_tpu.telemetry.kernel_profile import profiler

        kins, feats0 = _wave_members(9, "kitchen_sink", 383)
        steps = [K] * len(kins)
        feats = [feats0] * len(kins)

        prior = coalesce.fused_wave_enabled()
        telemetry.enable()
        telemetry.reset()
        try:
            coalesce.configure_fused_wave(False)
            composite = coalesce.launch_wave(kins, steps, feats)
            coalesce.configure_fused_wave(True)
            fused = coalesce.launch_wave(kins, steps, feats)
            disp = dict(profiler.summary()["Dispatches"])
        finally:
            coalesce.configure_fused_wave(prior)
            telemetry.disable()
            telemetry.reset()
        # composite wave: program + eager fetch; fused wave: program
        # only (the packed readback rides the dispatch)
        assert disp.get("joint", 0) == 1 and disp.get("wave_fetch") == 1
        assert disp.get("fused_wave") == 1
        for c, f in zip(composite, fused):
            np.testing.assert_array_equal(np.asarray(c.chosen),
                                          np.asarray(f.chosen))
            np.testing.assert_array_equal(np.asarray(c.found),
                                          np.asarray(f.found))
            np.testing.assert_array_equal(np.asarray(c.scores),
                                          np.asarray(f.scores))
            np.testing.assert_array_equal(np.asarray(c.topk_scores),
                                          np.asarray(f.topk_scores))

    def test_steady_fused_burst_zero_new_misses(self):
        """Mini steady-burst smoke: after ONE warm wave, repeated
        fused waves of the same bucket shape compile nothing and cost
        exactly one dispatch each."""
        from nomad_tpu import telemetry
        from nomad_tpu.telemetry.kernel_profile import profiler

        kins, feats0 = _wave_members(11, "shuffle", 200)
        steps = [K] * len(kins)
        feats = [feats0] * len(kins)

        prior = coalesce.fused_wave_enabled()
        telemetry.enable()
        try:
            coalesce.configure_fused_wave(True)
            coalesce.launch_wave(kins, steps, feats)      # warm
            telemetry.reset()
            for _ in range(3):
                coalesce.launch_wave(kins, steps, feats)
            prof = profiler.summary()
            fused = coalesce.fused_wave_stats.snapshot()
        finally:
            coalesce.configure_fused_wave(prior)
            telemetry.disable()
            telemetry.reset()
        assert prof["JitCacheMisses"] == 0, prof["PerKey"]
        assert prof["Dispatches"].get("fused_wave") == 3
        assert "wave_fetch" not in prof["Dispatches"]
        assert fused["launches"] == 3 and fused["fallbacks"] == 0

    def test_unsupported_union_falls_back_counted(self):
        """A wave whose union leaves the envelope (spreads) must run
        the composite program and count ONE fallback."""
        from nomad_tpu import telemetry

        kins, feats0 = _wave_members(13, "lean", 60)
        steps = [K] * len(kins)
        feats = [feats0._replace(n_spreads=1)] * len(kins)
        assert not fused_wave_supported(coalesce.union_features(feats))

        prior = coalesce.fused_wave_enabled()
        telemetry.enable()
        telemetry.reset()
        try:
            coalesce.configure_fused_wave(True)
            coalesce.fused_wave_stats.reset()
            outs = coalesce.launch_wave(kins, steps, feats)
            fused = coalesce.fused_wave_stats.snapshot()
        finally:
            coalesce.configure_fused_wave(prior)
            telemetry.disable()
            telemetry.reset()
        assert fused["launches"] == 0 and fused["fallbacks"] == 1
        assert len(outs) == len(kins)

    def test_disabled_knob_runs_composite_uncounted(self):
        kins, feats0 = _wave_members(15, "lean", 60)
        steps = [K] * len(kins)
        feats = [feats0] * len(kins)
        prior = coalesce.fused_wave_enabled()
        try:
            coalesce.configure_fused_wave(False)
            coalesce.fused_wave_stats.reset()
            coalesce.launch_wave(kins, steps, feats)
            fused = coalesce.fused_wave_stats.snapshot()
        finally:
            coalesce.configure_fused_wave(prior)
        assert fused == {"launches": 0, "fallbacks": 0}


class TestFusedWarmup:
    """ops/warmup learns the fused signatures: fused profiler keys
    fold into mesh/fusion-agnostic joint manifest entries, and warming
    a joint entry compiles the fused variant too (steady fused waves
    keep zero jit misses)."""

    def test_fused_launch_keys_fold_into_manifest(self):
        from nomad_tpu import telemetry
        from nomad_tpu.ops import warmup as kernel_warmup
        from nomad_tpu.telemetry.kernel_profile import profiler

        kins, feats0 = _wave_members(17, "shuffle", 200)
        steps = [K] * len(kins)
        feats = [feats0] * len(kins)
        prior = coalesce.fused_wave_enabled()
        telemetry.enable()
        telemetry.reset()
        try:
            coalesce.configure_fused_wave(True)
            coalesce.launch_wave(kins, steps, feats)
            entries = kernel_warmup.manifest_from_profiler(profiler)
        finally:
            coalesce.configure_fused_wave(prior)
            telemetry.disable()
            telemetry.reset()
        joints = [e for e in entries if e["kernel"] == "joint"]
        assert joints, entries
        assert joints[0]["nodes"] == 256
        assert not [e for e in entries
                    if "fused" in e.get("kernel", "")], entries

    def test_warmup_compiles_fused_signature(self):
        """A joint manifest entry warmed WITHOUT a mesh makes the live
        fused launch of that bucket a cache hit. Uses a bucket no
        other fused test touches (B=2 -> distinct wave pad), so the
        warmup itself must do the compiling."""
        from nomad_tpu import telemetry
        from nomad_tpu.ops import warmup as kernel_warmup
        from nomad_tpu.telemetry.kernel_profile import profiler

        kins, feats0 = _wave_members(19, "lean", 500)
        kins = kins[:2]
        steps = [K] * len(kins)
        feats = [feats0] * len(kins)
        n_pad = int(np.asarray(kins[0].cap_cpu).shape[0])
        b_pad = coalesce.pad_wave(len(kins))
        feat_union = coalesce.union_features(feats)
        entry = {
            "kernel": "joint", "wave": b_pad,
            "steps": pad_steps(b_pad * K), "nodes": n_pad,
            "shared": False, "neutral_shared": False,
            "job_shared": False,
            "features": dict(feat_union._asdict()),
        }
        compiled, failed = kernel_warmup.warmup_entries([entry])
        assert compiled == 1 and failed == 0

        prior = coalesce.fused_wave_enabled()
        telemetry.enable()
        telemetry.reset()
        try:
            coalesce.configure_fused_wave(True)
            coalesce.launch_wave(kins, steps, feats)
            misses = profiler.misses_for("fused_wave")
            fused = coalesce.fused_wave_stats.snapshot()
        finally:
            coalesce.configure_fused_wave(prior)
            telemetry.disable()
            telemetry.reset()
        assert fused["launches"] >= 1
        assert misses == 0, profiler.summary()["PerKey"]


class TestFusedDonation:
    """make_fused_wave_apply routes donation through owned-buffer
    copies: caller-held numpy planes survive a repeated drive and no
    'donated buffers were not usable' warning fires (conftest promotes
    it to an error)."""

    def test_repeated_drive_keeps_caller_planes(self):
        from nomad_tpu.ops.pallas_kernel import make_fused_wave_apply

        kins, feats = _wave_members(21, "lean", 60)
        stacked, sm, sl, t_pad = _stack_wave(kins)
        n_pad = stacked.cap_cpu.shape[-1]
        # shared (unbatched) used planes: the donated carries
        used_cpu = (100.0 * np.arange(n_pad)).astype(np.float32)
        used_mem = np.full(n_pad, 64.0, np.float32)
        uc_copy, um_copy = used_cpu.copy(), used_mem.copy()

        apply = make_fused_wave_apply(t_pad, feats, interpret=True)
        uc, um = jnp.asarray(used_cpu), jnp.asarray(used_mem)
        outs = []
        for _ in range(2):
            fo, uc, um = apply(stacked, uc, um,
                               jnp.asarray(sm), jnp.asarray(sl))
            outs.append(fo)
        # donated carries advanced (or at least stayed valid arrays)
        assert np.asarray(uc).shape == (n_pad,)
        # the caller's numpy planes are untouched by donation
        np.testing.assert_array_equal(used_cpu, uc_copy)
        np.testing.assert_array_equal(used_mem, um_copy)
        host = unpack_fused_wave(np.asarray(outs[0].packed), t_pad, B)
        assert host["chosen"].shape == (t_pad,)
