"""Consul/Vault integration tests.

Modeled on reference nomad/vault_test.go (derivation, renewal,
revocation) and client/allocrunner/taskrunner/template/template_test.go
(render functions, change modes).
"""

import os
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.client.client import Client, ClientConfig, InProcessRPC
from nomad_tpu.client.template import (
    TemplateContext,
    TemplateWatcher,
    render,
    uses_live_data,
)
from nomad_tpu.server.secrets import (
    DevConsulProvider,
    DevVaultProvider,
    VaultManager,
)
from nomad_tpu.server.server import Server, ServerConfig
from nomad_tpu.structs import consts
from nomad_tpu.structs.job import Template, Vault


class TestDevVaultProvider:
    def test_token_lifecycle(self):
        v = DevVaultProvider()
        info = v.create_token(["web-policy"], ttl_s=60)
        assert info.token.startswith("s.")
        assert v.token_valid(info.token)
        assert v.lookup(info.accessor).policies == ["web-policy"]
        old_expiry = info.expires_at
        time.sleep(0.01)
        assert v.renew(info.accessor) >= old_expiry
        v.revoke(info.accessor)
        assert not v.token_valid(info.token)
        with pytest.raises(KeyError):
            v.renew(info.accessor)

    def test_secret_kv(self):
        v = DevVaultProvider()
        v.write_secret("secret/db", {"password": "hunter2"})
        assert v.read_secret("secret/db")["password"] == "hunter2"
        assert v.read_secret("secret/missing") is None

    def test_secrets_index_bumps_on_write(self):
        v = DevVaultProvider()
        i0 = v.secrets_index()
        v.write_secret("a", {"x": "1"})
        assert v.secrets_index() > i0

    def test_policy_enforcement(self):
        v = DevVaultProvider()
        v.write_secret("secret/db", {"password": "pw"})
        v.write_secret("secret/admin", {"root": "rw"})
        v.set_policy("db-read", ["secret/db"])
        tok = v.create_token(["db-read"], ttl_s=60).token
        assert v.read_secret("secret/db", token=tok)["password"] == "pw"
        with pytest.raises(PermissionError):
            v.read_secret("secret/admin", token=tok)
        with pytest.raises(PermissionError):
            v.read_secret("secret/db", token="bogus")

    def test_dev_mode_no_policies_allows_all(self):
        v = DevVaultProvider()
        v.write_secret("secret/x", {"k": "v"})
        # no policy docs configured -> dev root behavior
        assert v.read_secret("secret/x", token="")["k"] == "v"


class TestVaultManager:
    def test_derive_and_revoke_per_alloc(self):
        m = VaultManager()
        tokens = m.derive_tokens("alloc-1", {"web": ["p1"], "db": ["p2"]})
        assert set(tokens) == {"web", "db"}
        assert len(m.accessors_for_alloc("alloc-1")) == 2
        assert m.revoke_for_alloc("alloc-1") == 2
        assert m.accessors_for_alloc("alloc-1") == {}
        for info in tokens.values():
            assert not m.provider.token_valid(info.token)

    def test_renew_loop_extends_leases(self):
        m = VaultManager(renew_interval_s=0.05)
        info = m.derive_tokens("a", {"t": []})["t"]
        first_expiry = m.provider.lookup(info.accessor).expires_at
        m.start()
        try:
            time.sleep(0.2)
            assert m.provider.lookup(info.accessor).expires_at > first_expiry
        finally:
            m.stop()

    def test_revoke_all_on_restore(self):
        m = VaultManager()
        m.derive_tokens("a1", {"t": []})
        m.derive_tokens("a2", {"t": []})
        assert m.revoke_all() == 2


class TestConsulKV:
    def test_kv_index_monotonic(self):
        c = DevConsulProvider()
        i1 = c.kv_put("app/config", "v1")
        i2 = c.kv_put("app/config", "v2")
        assert i2 > i1
        assert c.kv_get("app/config") == "v2"
        assert c.kv_index() == i2

    def test_si_token_stable_per_task(self):
        c = DevConsulProvider()
        t1 = c.derive_si_token("a", "web", "svc")
        assert c.derive_si_token("a", "web", "svc") == t1
        assert c.derive_si_token("a", "db", "svc") != t1


class TestTemplateRender:
    def test_all_functions(self):
        ctx = TemplateContext(
            env={"PORT": "8080"},
            meta={"team": "infra"},
            node_attrs={"arch": "x86"},
            kv_get={"app/name": "web"}.get,
            secret_get={"secret/db": {"password": "pw"}}.get,
        )
        out = render(
            'name={{ key "app/name" }} port={{ env "PORT" }} '
            'team={{ meta "team" }} arch={{ node_attr "arch" }} '
            'pw={{ secret "secret/db" "password" }} '
            'miss={{ keyOrDefault "nope" "fallback" }}',
            ctx,
        )
        assert out == ("name=web port=8080 team=infra arch=x86 "
                       "pw=pw miss=fallback")

    def test_missing_renders_empty(self):
        assert render('x={{ key "none" }}', TemplateContext()) == "x="

    def test_uses_live_data(self):
        assert uses_live_data('{{ key "a" }}')
        assert uses_live_data('{{ secret "a" "b" }}')
        assert not uses_live_data('{{ env "A" }}')

    def test_watcher_fires_on_index_change(self):
        c = DevConsulProvider()
        c.kv_put("k", "v1")
        fired = []
        w = TemplateWatcher(
            poll_index=c.kv_index,
            rerender=lambda: True,
            on_change=lambda: fired.append(1),
            interval_s=0.05,
        )
        w.start()
        try:
            time.sleep(0.1)
            assert not fired
            c.kv_put("k", "v2")
            deadline = time.time() + 2
            while not fired and time.time() < deadline:
                time.sleep(0.02)
            assert fired
        finally:
            w.stop()


@pytest.fixture()
def cluster(tmp_path):
    server = Server(ServerConfig(num_workers=1))
    server.start()
    client = Client(
        InProcessRPC(server),
        ClientConfig(data_dir=str(tmp_path), update_batch_interval=0.05),
    )
    client.start()
    yield server, client
    client.shutdown()
    server.shutdown()


def _wait(fn, timeout=15.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(0.05)
    return False


class TestEndToEnd:
    def test_vault_token_delivered_to_task(self, cluster, tmp_path):
        server, client = cluster
        job = mock.job()
        job.task_groups[0].count = 1
        task = job.task_groups[0].tasks[0]
        task.driver = "mock_driver"
        task.config = {"run_for": "2s"}
        task.vault = Vault(policies=["web-read"])
        server.job_register(job)

        assert _wait(lambda: any(
            tr.task_state.state == "running"
            for ar in client.allocs.values()
            for tr in ar.task_runners.values()
        )), "task never started"
        ar = next(iter(client.allocs.values()))
        token_file = os.path.join(
            ar.alloc_dir, task.name, "secrets", "vault_token")
        with open(token_file) as f:
            token = f.read()
        assert token.startswith("s.")
        assert server.vault.provider.token_valid(token)
        assert len(server.vault.accessors_for_alloc(ar.alloc.id)) == 1

    def test_tokens_revoked_when_alloc_completes(self, cluster):
        server, client = cluster
        job = mock.job()
        job.type = consts.JOB_TYPE_BATCH
        job.task_groups[0].count = 1
        task = job.task_groups[0].tasks[0]
        task.driver = "mock_driver"
        task.config = {"run_for": "0.1s"}
        task.vault = Vault(policies=[])
        server.job_register(job)

        # batch task finishes -> client reports terminal -> server revokes
        assert _wait(lambda: all(
            a.client_status == consts.ALLOC_CLIENT_COMPLETE
            for a in server.state.snapshot().allocs_iter()
            if a.job_id == job.id
        ) and any(server.state.snapshot().allocs_iter()))
        alloc = next(a for a in server.state.snapshot().allocs_iter()
                     if a.job_id == job.id)
        assert _wait(
            lambda: server.vault.accessors_for_alloc(alloc.id) == {})

    def test_template_rendered_and_change_mode_restart(self, cluster):
        server, client = cluster
        server.consul.kv_put("app/greeting", "hello")
        job = mock.job()
        job.task_groups[0].count = 1
        task = job.task_groups[0].tasks[0]
        task.driver = "mock_driver"
        task.config = {"run_for": "60s"}
        task.templates = [Template(
            embedded_tmpl='greeting={{ key "app/greeting" }}',
            dest_path="local/config.txt",
            change_mode="restart",
        )]
        server.job_register(job)

        assert _wait(lambda: any(
            tr.task_state.state == "running"
            for ar in client.allocs.values()
            for tr in ar.task_runners.values()
        ))
        ar = next(iter(client.allocs.values()))
        dest = os.path.join(ar.alloc_dir, task.name, "local", "config.txt")
        with open(dest) as f:
            assert f.read() == "greeting=hello"

        tr = next(iter(ar.task_runners.values()))
        restarts_before = len([
            e for e in tr.task_state.events if e.type == "Restarting"])
        server.consul.kv_put("app/greeting", "bonjour")
        assert _wait(lambda: open(dest).read() == "greeting=bonjour")
        assert _wait(lambda: len([
            e for e in tr.task_state.events if e.type == "Restarting"
        ]) > restarts_before), "change_mode=restart never fired"

    def test_change_mode_of_changed_template_only(self, cluster):
        """A noop template re-rendering must not fire an unrelated
        template's restart mode."""
        server, client = cluster
        server.consul.kv_put("noop/key", "v1")
        job = mock.job()
        job.task_groups[0].count = 1
        task = job.task_groups[0].tasks[0]
        task.driver = "mock_driver"
        task.config = {"run_for": "60s"}
        task.templates = [
            Template(embedded_tmpl='k={{ key "noop/key" }}',
                     dest_path="local/live.txt", change_mode="noop"),
            Template(embedded_tmpl="static content",
                     dest_path="local/static.txt", change_mode="restart"),
        ]
        server.job_register(job)
        assert _wait(lambda: any(
            tr.task_state.state == "running"
            for ar in client.allocs.values()
            for tr in ar.task_runners.values()
        ))
        ar = next(iter(client.allocs.values()))
        tr = next(iter(ar.task_runners.values()))
        dest = os.path.join(ar.alloc_dir, task.name, "local", "live.txt")
        server.consul.kv_put("noop/key", "v2")
        assert _wait(lambda: open(dest).read() == "k=v2")
        time.sleep(0.3)   # give a wrong restart a chance to fire
        assert not any(e.type == "Restarting" for e in tr.task_state.events)

    def test_secret_rotation_triggers_rerender(self, cluster):
        """Vault secret writes bump the live-data index too."""
        server, client = cluster
        server.vault.provider.write_secret("db/creds", {"pass": "one"})
        job = mock.job()
        job.task_groups[0].count = 1
        task = job.task_groups[0].tasks[0]
        task.driver = "mock_driver"
        task.config = {"run_for": "60s"}
        task.vault = Vault(policies=[])
        task.templates = [Template(
            embedded_tmpl='pass={{ secret "db/creds" "pass" }}',
            dest_path="local/creds.txt", change_mode="noop",
        )]
        server.job_register(job)
        assert _wait(lambda: any(
            tr.task_state.state == "running"
            for ar in client.allocs.values()
            for tr in ar.task_runners.values()
        ))
        ar = next(iter(client.allocs.values()))
        dest = os.path.join(ar.alloc_dir, task.name, "local", "creds.txt")
        assert open(dest).read() == "pass=one"
        server.vault.provider.write_secret("db/creds", {"pass": "two"})
        assert _wait(lambda: open(dest).read() == "pass=two"), \
            "secret rotation never re-rendered"

    def test_template_with_secret_requires_vault_block(self, cluster):
        server, client = cluster
        job = mock.job()
        job.task_groups[0].count = 1
        task = job.task_groups[0].tasks[0]
        task.driver = "mock_driver"
        task.config = {"run_for": "60s"}
        task.templates = [Template(
            embedded_tmpl='{{ secret "a" "b" }}', dest_path="local/x")]
        server.job_register(job)
        assert _wait(lambda: any(
            tr.task_state.state == "dead" and tr.task_state.failed
            for ar in client.allocs.values()
            for tr in ar.task_runners.values()
        )), "prestart should fail without a vault block"

    def test_vault_token_rotation_redelivers(self, cluster):
        server, client = cluster
        job = mock.job()
        job.task_groups[0].count = 1
        task = job.task_groups[0].tasks[0]
        task.driver = "mock_driver"
        task.config = {"run_for": "60s"}
        task.vault = Vault(policies=[], change_mode="noop")
        server.job_register(job)
        assert _wait(lambda: any(
            tr.task_state.state == "running"
            for ar in client.allocs.values()
            for tr in ar.task_runners.values()
        ))
        ar = next(iter(client.allocs.values()))
        tr = next(iter(ar.task_runners.values()))
        tr.vault_poll_interval_s = 0.05
        old = tr._vault_token
        # revoke out from under the task (external operator action)
        server.vault.revoke_for_alloc(ar.alloc.id)
        assert _wait(lambda: tr._vault_token != old
                     and server.vault.provider.token_valid(tr._vault_token)), \
            "token never re-derived"
        token_file = os.path.join(
            ar.alloc_dir, task.name, "secrets", "vault_token")
        assert open(token_file).read() == tr._vault_token


class TestDeriveValidation:
    def test_terminal_alloc_rejected(self):
        server = Server(ServerConfig(num_workers=0))
        server.start()
        try:
            job = mock.job()
            server.job_register(job)
            from nomad_tpu.structs.alloc import Allocation
            alloc = Allocation(
                job_id=job.id, namespace=job.namespace,
                task_group=job.task_groups[0].name,
                client_status=consts.ALLOC_CLIENT_COMPLETE,
                desired_status=consts.ALLOC_DESIRED_STOP,
            )
            alloc.job = job
            server.state.upsert_allocs([alloc])
            with pytest.raises(ValueError):
                server.derive_vault_tokens(alloc.id, [
                    job.task_groups[0].tasks[0].name])
        finally:
            server.shutdown()


class TestJobspecVault:
    def test_vault_block_parses(self):
        from nomad_tpu.jobspec.parse import parse_hcl as parse_job
        hcl = '''
        job "web" {
          group "app" {
            task "server" {
              driver = "mock_driver"
              vault {
                policies      = ["db-read", "kv-read"]
                change_mode   = "signal"
                change_signal = "SIGUSR1"
              }
            }
          }
        }
        '''
        job = parse_job(hcl)
        v = job.task_groups[0].tasks[0].vault
        assert v is not None
        assert v.policies == ["db-read", "kv-read"]
        assert v.change_mode == "signal"
        assert v.change_signal == "SIGUSR1"
