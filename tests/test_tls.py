"""TLS/mTLS tests.

Modeled on reference helper/tlsutil/config_test.go and
command/agent HTTPS tests: CA-verified HTTPS API, mTLS enforcement
with verify_https_client, and the tls ca/cert create CLI.
"""

import os
import ssl
import subprocess
import sys
import urllib.error
import urllib.request

import pytest

# the TLS material helpers are a thin wrapper over `cryptography`,
# which is an optional dependency: without it these tests cannot even
# build a CA, so they read as skips rather than failures
pytest.importorskip("cryptography")

from nomad_tpu.api.agent import Agent, AgentConfig
from nomad_tpu.api.client import APIClient
from nomad_tpu.utils.tlsutil import (
    TLSConfig,
    generate_ca,
    generate_cert,
)


@pytest.fixture(scope="module")
def material(tmp_path_factory):
    d = tmp_path_factory.mktemp("tls")
    ca = generate_ca()
    server_cert = generate_cert(ca[0], ca[1], "server.global.nomad",
                                san_dns=["server.global.nomad"])
    client_cert = generate_cert(ca[0], ca[1], "cli.global.nomad",
                                server=False)
    paths = {}
    for name, data in (("ca.pem", ca[0]), ("ca-key.pem", ca[1]),
                       ("server.pem", server_cert[0]),
                       ("server-key.pem", server_cert[1]),
                       ("client.pem", client_cert[0]),
                       ("client-key.pem", client_cert[1])):
        p = d / name
        p.write_bytes(data)
        paths[name] = str(p)
    return paths


def _agent(material, verify_client=False):
    tls = TLSConfig(
        enabled=True,
        ca_file=material["ca.pem"],
        cert_file=material["server.pem"],
        key_file=material["server-key.pem"],
        verify_https_client=verify_client,
    )
    a = Agent(AgentConfig(name="tls-agent", num_schedulers=0, tls=tls))
    a.start()
    return a


class TestHTTPS:
    def test_https_with_ca_verification(self, material):
        a = _agent(material)
        try:
            assert a.http_addr.startswith("https://")
            api = APIClient(a.http_addr, ca_cert=material["ca.pem"])
            assert api.agent.self()["Config"]["Name"] == "tls-agent"
        finally:
            a.shutdown()

    def test_unverified_client_rejected(self, material):
        a = _agent(material)
        try:
            # no CA configured -> default trust store -> handshake fails
            api = APIClient(a.http_addr, ca_cert=material["server.pem"])
            with pytest.raises((urllib.error.URLError, ssl.SSLError)):
                api.agent.self()
        finally:
            a.shutdown()

    def test_plain_http_refused(self, material):
        a = _agent(material)
        try:
            url = a.http_addr.replace("https://", "http://")
            with pytest.raises(Exception):
                urllib.request.urlopen(url + "/v1/agent/self", timeout=5)
        finally:
            a.shutdown()


class TestMTLS:
    def test_client_cert_required(self, material):
        a = _agent(material, verify_client=True)
        try:
            # with cert: accepted
            api = APIClient(
                a.http_addr, ca_cert=material["ca.pem"],
                client_cert=material["client.pem"],
                client_key=material["client-key.pem"],
            )
            assert api.agent.self()["Config"]["Name"] == "tls-agent"
            # without cert: handshake rejected
            bare = APIClient(a.http_addr, ca_cert=material["ca.pem"])
            with pytest.raises((urllib.error.URLError, ssl.SSLError,
                                ConnectionResetError)):
                bare.agent.self()
        finally:
            a.shutdown()


class TestFederatedTLS:
    def test_region_forwarding_over_tls(self, material):
        """Cross-region proxying must trust the cluster CA (the
        forwarder dials the remote region over HTTPS)."""
        east = _agent(material)
        west = _agent(material)
        try:
            east.server.join_region("west", west.http.addr)
            west.server.join_region("east", east.http.addr)
            api = APIClient(east.http_addr, ca_cert=material["ca.pem"])
            # ?region=west forwards east->west over HTTPS
            jobs = api.get("/v1/jobs?region=west")
            assert jobs == []
        finally:
            east.shutdown()
            west.shutdown()


class TestTLSCLI:
    def test_ca_and_cert_create(self, tmp_path):
        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               "PYTHONPATH": "/root/repo"}
        r = subprocess.run(
            [sys.executable, "-m", "nomad_tpu", "tls", "ca", "create"],
            cwd=tmp_path, capture_output=True, text=True, timeout=120,
            env=env)
        assert r.returncode == 0, r.stderr
        assert (tmp_path / "nomad-agent-ca.pem").exists()
        r = subprocess.run(
            [sys.executable, "-m", "nomad_tpu", "tls", "cert", "create",
             "-server"],
            cwd=tmp_path, capture_output=True, text=True, timeout=120,
            env=env)
        assert r.returncode == 0, r.stderr
        assert (tmp_path / "global-server-nomad.pem").exists()
        # issued cert chains to the CA
        from cryptography import x509
        ca = x509.load_pem_x509_certificate(
            (tmp_path / "nomad-agent-ca.pem").read_bytes())
        leaf = x509.load_pem_x509_certificate(
            (tmp_path / "global-server-nomad.pem").read_bytes())
        assert leaf.issuer == ca.subject
        leaf.verify_directly_issued_by(ca)
