"""One-time tokens, autopilot, and periodic-launch-ledger tests.

Modeled on reference nomad/acl_endpoint_test.go (OneTimeToken),
nomad/autopilot_test.go (CleanupDeadServer), and periodic_test.go
restore semantics.
"""

import time

import pytest

from nomad_tpu import mock, structs
from nomad_tpu.server.server import Server, ServerConfig
from nomad_tpu.server.testing import make_cluster, wait_for_leader, wait_until
from nomad_tpu.structs import consts


class TestOneTimeTokens:
    def _server_with_token(self):
        from nomad_tpu.acl.policy import ACLToken

        server = Server(ServerConfig(num_workers=0))
        server.start()
        token = ACLToken.create(name="ops", type="management")
        server.raft_apply("ACLTokenUpsertRequestType", {"tokens": [token]})
        return server, token

    def test_create_and_exchange(self):
        server, token = self._server_with_token()
        try:
            ott = server.create_one_time_token(token.accessor_id)
            assert ott["expires_at"] > time.time()
            got = server.exchange_one_time_token(ott["one_time_secret_id"])
            assert got.accessor_id == token.accessor_id
            # single use
            with pytest.raises(ValueError):
                server.exchange_one_time_token(ott["one_time_secret_id"])
        finally:
            server.shutdown()

    def test_expired_rejected_and_gcd(self):
        server, token = self._server_with_token()
        try:
            ott = server.create_one_time_token(token.accessor_id, ttl_s=-1)
            with pytest.raises(ValueError):
                server.exchange_one_time_token(ott["one_time_secret_id"])
            assert server.expire_one_time_tokens() == 1
            assert server.state.one_time_token_by_secret(
                ott["one_time_secret_id"]) is None
        finally:
            server.shutdown()

    def test_over_http(self):
        from nomad_tpu.api.agent import Agent, AgentConfig
        from nomad_tpu.api.client import APIClient, APIError
        from nomad_tpu.acl.policy import ACLToken

        agent = Agent(AgentConfig(num_schedulers=0))
        agent.start()
        try:
            token = ACLToken.create(name="ops", type="management")
            agent.server.raft_apply("ACLTokenUpsertRequestType",
                                    {"tokens": [token]})
            api = APIClient(agent.http.addr, token=token.secret_id)
            resp = api.acl.create_one_time_token()
            secret = resp["OneTimeToken"]["OneTimeSecretID"]
            anon = APIClient(agent.http.addr)
            got = anon.acl.exchange_one_time_token(secret)
            assert got["Token"]["AccessorID"] == token.accessor_id
            with pytest.raises(APIError):
                anon.acl.exchange_one_time_token(secret)
        finally:
            agent.shutdown()


class TestPeriodicLedger:
    def test_dispatch_records_launch(self):
        server = Server(ServerConfig(num_workers=0))
        server.start()
        try:
            job = mock.job()
            job.periodic = structs.PeriodicConfig(enabled=True,
                                                  spec="@every 3600s")
            server.job_register(job)
            child = server.periodic_dispatcher.force_run(job)
            assert child
            assert server.state.periodic_launch_by_id(
                "default", job.id) > 0
        finally:
            server.shutdown()

    def test_restore_catches_up_missed_launch(self):
        server = Server(ServerConfig(num_workers=0))
        server.start()
        try:
            job = mock.job()
            job.periodic = structs.PeriodicConfig(enabled=True,
                                                  spec="@every 0.5s")
            server.job_register(job)
            # ledger says the last launch was long ago -> the next
            # scheduled launch has been missed
            server.state.upsert_periodic_launch(
                "default", job.id, time.time() - 3600
            )
            before = len([
                j for j in server.state.snapshot().jobs()
                if getattr(j, "parent_id", "") == job.id
            ])
            server.periodic_dispatcher.restore(server.state.snapshot())
            after = len([
                j for j in server.state.snapshot().jobs()
                if getattr(j, "parent_id", "") == job.id
            ])
            assert after == before + 1
        finally:
            server.shutdown()


class TestAutopilot:
    def test_health_view(self):
        servers, registry = make_cluster(3)
        try:
            leader = wait_for_leader(servers)
            wait_until(
                lambda: all(
                    h["last_contact_s"] < 5.0
                    for h in leader.raft.server_health()
                ),
                msg="peers contacted",
            )
            h = leader.autopilot.health()
            assert h["Healthy"] is True
            assert len(h["Servers"]) == 3
            assert sum(1 for s in h["Servers"] if s["Leader"]) == 1
        finally:
            for s in servers:
                s.shutdown()

    def test_dead_server_cleanup(self):
        servers, registry = make_cluster(3)
        try:
            leader = wait_for_leader(servers)
            # tighten thresholds so the test is fast
            leader.state.set_autopilot_config({
                "cleanup_dead_servers": True,
                "last_contact_threshold_s": 0.5,
                "server_stabilization_time_s": 0.2,
            })
            dead = next(s for s in servers if s is not leader)
            dead_id = dead.raft.id
            dead.shutdown()
            registry.partition(leader.raft.id, dead_id)
            wait_until(
                lambda: leader.autopilot.evaluate_once() or
                dead_id not in leader.raft.peers,
                timeout=10.0, msg="dead server removed",
            )
            assert dead_id not in leader.raft.peers
            # the removal is a replicated config change: the surviving
            # follower drops the peer too, so a failover cannot
            # resurrect it
            follower = next(s for s in servers
                            if s is not leader and s.raft.id != dead_id)
            wait_until(lambda: dead_id not in follower.raft.peers,
                       msg="follower applied removal")
            # cluster still works with the remaining pair
            job = mock.job()
            leader.job_register(job)
            assert leader.state.snapshot().job_by_id(
                "default", job.id) is not None
        finally:
            for s in servers:
                try:
                    s.shutdown()
                except Exception:
                    pass

    def test_quorum_guard(self):
        servers, registry = make_cluster(3)
        try:
            leader = wait_for_leader(servers)
            leader.state.set_autopilot_config({
                "cleanup_dead_servers": True,
                "last_contact_threshold_s": 0.3,
                "server_stabilization_time_s": 0.1,
            })
            others = [s for s in servers if s is not leader]
            for s in others:
                registry.partition(leader.raft.id, s.raft.id)
            time.sleep(0.6)
            # both peers dead: removing either would leave the leader
            # alone -> quorum guard refuses
            removed = leader.autopilot.evaluate_once()
            assert removed == []
            assert len(leader.raft.peers) == 2
        finally:
            registry.heal()
            for s in servers:
                s.shutdown()
