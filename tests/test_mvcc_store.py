"""ISSUE 16: the MVCC snapshot-isolated StateStore property suite.

Four properties, each the acceptance surface of one design claim:

- **Frozen snapshots.** A pinned snapshot serializes bit-identically
  before and after any amount of later write traffic: the root it
  holds is immutable, and path-copying never touches retained nodes.
- **Shadow-oracle parity.** The SEED lock-based store
  (tests/_shadow_store.py, frozen verbatim) replays the same
  randomized op stream and must land on the same final state — every
  table, every index, every usage-visible row. The MVCC rebuild is a
  representation change, not a semantics change, and this is the test
  that keeps it one (seed-swept; the 200-seed sweep runs in the slow
  tier).
- **Usage consistency.** ``usage_rebuild_diff`` is empty at EVERY
  generation — the incrementally-maintained planes always match a
  from-scratch rebuild over the same snapshot.
- **Retention.** Dropping the last reference to a snapshot releases
  its generation root (weakref registry, no generation leak), and a
  single-row write shares every untouched row object with the
  previous root (structural sharing, not copying).

Plus PMap unit/property tests: the dict-model equivalence, collision
handling, bulk commit with tombstones, and the pickle round-trip the
raft snapshot path relies on.
"""

import copy
import gc
import pickle
import random

import pytest

import _shadow_store as shadow_mod

from nomad_tpu import mock, structs
from nomad_tpu.state.pmap import EMPTY, TOMBSTONE, PMap
from nomad_tpu.state.store import StateStore, snapshot_at
from nomad_tpu.state.usage import usage_rebuild_diff
from nomad_tpu.structs import consts
from nomad_tpu.structs.services import ServiceRegistration


# ---------------------------------------------------------------------------
# PMap


class _FixedHash:
    """A key with a chosen hash: forces radix-path collisions."""

    def __init__(self, name, h):
        self.name, self.h = name, h

    def __hash__(self):
        return self.h

    def __eq__(self, other):
        return isinstance(other, _FixedHash) and self.name == other.name

    def __reduce__(self):
        return (_FixedHash, (self.name, self.h))


class TestPMap:
    def test_dict_model_equivalence(self):
        """Random assoc/dissoc streams against a plain-dict model."""
        for seed in range(10):
            rng = random.Random(seed)
            m, model = EMPTY, {}
            for _ in range(400):
                k = f"k{rng.randrange(80)}"
                if rng.random() < 0.3 and model:
                    m = m.dissoc(k)
                    model.pop(k, None)
                else:
                    v = rng.randrange(1000)
                    m = m.assoc(k, v)
                    model[k] = v
            assert m.to_dict() == model
            assert len(m) == len(model)
            assert sorted(m.keys(), key=str) == sorted(model, key=str)
            for k, v in model.items():
                assert m[k] == v
            assert m.get("never-written") is None

    def test_hash_collisions(self):
        """Keys sharing one hash live in one leaf and stay distinct."""
        keys = [_FixedHash(f"c{i}", 0xDEAD) for i in range(40)]
        m = EMPTY
        for i, k in enumerate(keys):
            m = m.assoc(k, i)
        assert len(m) == 40
        for i, k in enumerate(keys):
            assert m[k] == i
        m = m.dissoc(keys[7])
        assert len(m) == 39 and keys[7] not in m and m[keys[8]] == 8

    def test_update_with_tombstones(self):
        m = PMap.from_dict({f"k{i}": i for i in range(100)})
        m2 = m.update_with({"k5": 500, "k6": TOMBSTONE, "new": 1})
        assert m2["k5"] == 500 and "k6" not in m2 and m2["new"] == 1
        # the base never moved
        assert m["k5"] == 5 and m["k6"] == 6 and "new" not in m
        assert len(m2) == 100  # -1 tombstone +1 new

    def test_structural_sharing_on_assoc(self):
        m = PMap.from_dict({f"k{i:04d}": i for i in range(5000)})
        m2 = m.assoc("k0001", -1)
        # every untouched value object is the SAME object
        shared = sum(1 for k, v in m2.items() if m.get(k) is v)
        assert shared == 4999

    def test_pickle_round_trip(self):
        src = {f"k{i}": (i, f"v{i}") for i in range(500)}
        src[_FixedHash("a", 3)] = "x"
        m = PMap.from_dict(src)
        m2 = pickle.loads(pickle.dumps(m))
        assert m2.to_dict() == src and len(m2) == len(src)


# ---------------------------------------------------------------------------
# randomized op streams (shared by the oracle / usage / frozen tests)


def _gen_ops(seed, n_ops=120):
    """A deterministic op stream over the write API. Args are built
    once; ``_apply`` deep-copies them per store so the seed store's
    in-place index stamping never leaks into the MVCC store's rows."""
    rng = random.Random(seed)
    ops = []
    node_ids, job_keys, alloc_ids, eval_ids = [], [], [], []
    # nodes that ever received an alloc: never deleted (mirrors node
    # GC, which only reaps nodes with no non-terminal allocs — and
    # keeps `usage_rebuild_diff` meaningful: the live planes drop a
    # deleted node's row while a rebuild resurrects it from orphan
    # allocs, a divergence real op order never produces)
    alloc_nodes = set()
    statuses = [consts.NODE_STATUS_READY, consts.NODE_STATUS_DOWN,
                consts.NODE_STATUS_INIT]
    for _ in range(n_ops):
        menu = ["upsert_node", "upsert_job"]
        if node_ids:
            menu += ["node_status", "node_drain", "node_elig", "services"]
        if [n for n in node_ids if n not in alloc_nodes]:
            menu += ["delete_node"]
        if job_keys:
            menu += ["upsert_eval", "stability", "scaling"]
            if len(job_keys) > 2:
                menu += ["delete_job"]
        if job_keys and node_ids:
            menu += ["upsert_alloc", "upsert_alloc"]
        if alloc_ids:
            menu += ["client_update", "desired_transition", "stop_alloc"]
        if eval_ids:
            menu += ["delete_eval"]
        kind = rng.choice(menu)

        if kind == "upsert_node":
            n = mock.node()
            node_ids.append(n.id)
            ops.append(("upsert_node", (n,)))
        elif kind == "node_status":
            ops.append(("update_node_status",
                        (rng.choice(node_ids), rng.choice(statuses))))
        elif kind == "node_drain":
            ops.append(("update_node_drain",
                        (rng.choice(node_ids), rng.random() < 0.5)))
        elif kind == "node_elig":
            elig = rng.choice([consts.NODE_SCHEDULING_ELIGIBLE,
                               consts.NODE_SCHEDULING_INELIGIBLE])
            ops.append(("update_node_eligibility",
                        (rng.choice(node_ids), elig)))
        elif kind == "delete_node":
            nid = rng.choice([n for n in node_ids if n not in alloc_nodes])
            node_ids.remove(nid)
            ops.append(("delete_node", (nid,)))
        elif kind == "services":
            reg = ServiceRegistration(
                id=f"svc-{len(ops)}", service_name="web",
                node_id=rng.choice(node_ids), address="10.0.0.1",
                port=rng.randrange(2000, 3000))
            ops.append(("upsert_service_registrations", ([reg],)))
        elif kind == "upsert_job":
            j = mock.job()
            job_keys.append((j.namespace, j.id))
            ops.append(("upsert_job", (j,)))
        elif kind == "delete_job":
            ns, jid = job_keys.pop(rng.randrange(len(job_keys)))
            ops.append(("delete_job", (ns, jid)))
        elif kind == "stability":
            ns, jid = rng.choice(job_keys)
            ops.append(("set_job_stability",
                        (ns, jid, 0, rng.random() < 0.5)))
        elif kind == "scaling":
            ns, jid = rng.choice(job_keys)
            ops.append(("record_scaling_event",
                        (ns, jid, "web", {"message": f"e{len(ops)}"})))
        elif kind == "upsert_eval":
            ns, jid = rng.choice(job_keys)
            e = mock.eval(job_id=jid, namespace=ns)
            eval_ids.append(e.id)
            ops.append(("upsert_evals", ([e],)))
        elif kind == "delete_eval":
            eid = eval_ids.pop(rng.randrange(len(eval_ids)))
            ops.append(("delete_evals", ([eid],)))
        elif kind == "upsert_alloc":
            ns, jid = rng.choice(job_keys)
            nid = rng.choice(node_ids)
            alloc_nodes.add(nid)
            a = mock.alloc(node_id=nid, job_id=jid, namespace=ns)
            alloc_ids.append(a.id)
            ops.append(("upsert_allocs", ([a],)))
        elif kind == "client_update":
            status = rng.choice([consts.ALLOC_CLIENT_RUNNING,
                                 consts.ALLOC_CLIENT_COMPLETE,
                                 consts.ALLOC_CLIENT_FAILED])
            upd = structs.Allocation(
                id=rng.choice(alloc_ids), client_status=status,
                client_description="prop test", task_states={})
            ops.append(("update_allocs_from_client", ([upd],)))
        elif kind == "desired_transition":
            ops.append(("update_allocs_desired_transition",
                        ({rng.choice(alloc_ids): {"migrate": True}}, [])))
        elif kind == "stop_alloc":
            ops.append(("stop_alloc", (rng.choice(alloc_ids), [])))
    return ops


def _apply(store, ops):
    for method, args in ops:
        getattr(store, method)(*copy.deepcopy(args))


def _payload(store):
    p = pickle.loads(store.to_snapshot_bytes())
    # SchedulerConfiguration has identity equality; compare its fields
    p["scheduler_config"] = vars(p["scheduler_config"])
    return p


# ---------------------------------------------------------------------------
# frozen snapshots


def _snap_bytes(snap):
    """Serialize everything a snapshot can see, via its public reads."""
    return pickle.dumps({
        "index": snap.latest_index(),
        "nodes": sorted(snap.nodes(), key=lambda n: n.id),
        "jobs": sorted(snap.jobs(), key=lambda j: j.id),
        "evals": sorted(snap.evals_iter(), key=lambda e: e.id),
        "allocs": sorted(snap.allocs_iter(), key=lambda a: a.id),
        "deployments": sorted(snap.deployments_iter(),
                              key=lambda d: d.id),
        "csi": sorted(snap.csi_volumes_iter(), key=lambda v: v.id),
    })


class TestFrozenSnapshots:
    @pytest.mark.parametrize("seed", range(5))
    def test_pinned_snapshot_is_bit_identical_after_writes(self, seed):
        store = StateStore()
        ops = _gen_ops(seed, n_ops=80)
        _apply(store, ops[:40])
        pinned = store.snapshot()
        before = _snap_bytes(pinned)
        _apply(store, ops[40:])
        assert store.latest_index() > pinned.latest_index()
        assert _snap_bytes(pinned) == before

    def test_snapshot_row_is_same_object_across_reads(self):
        store = StateStore()
        n = mock.node()
        store.upsert_node(n)
        snap = store.snapshot()
        store.update_node_status(n.id, consts.NODE_STATUS_DOWN)
        assert snap.node_by_id(n.id).status == consts.NODE_STATUS_READY
        assert store.snapshot().node_by_id(n.id).status == \
            consts.NODE_STATUS_DOWN
        # same generation -> same root -> identical row object
        assert snap.node_by_id(n.id) is snap.node_by_id(n.id)


# ---------------------------------------------------------------------------
# shadow oracle


def _assert_parity(seed, n_ops):
    ops = _gen_ops(seed, n_ops=n_ops)
    mvcc, oracle = StateStore(), shadow_mod.StateStore()
    _apply(mvcc, ops)
    _apply(oracle, ops)
    assert mvcc.latest_index() == oracle.latest_index()
    pm, po = _payload(mvcc), _payload(oracle)
    assert sorted(pm) == sorted(po)
    for key in pm:
        assert pm[key] == po[key], f"table {key!r} diverged (seed {seed})"


class TestShadowOracle:
    @pytest.mark.parametrize("seed", range(25))
    def test_op_stream_parity(self, seed):
        """The CI sweep: 25 seeds, every table equal to the seed
        store's final state after an identical randomized op stream."""
        _assert_parity(seed, n_ops=120)

    @pytest.mark.slow
    def test_op_stream_parity_200_seed_sweep(self):
        for seed in range(25, 200):
            _assert_parity(seed, n_ops=80)


# ---------------------------------------------------------------------------
# usage consistency


class TestUsageConsistency:
    @pytest.mark.parametrize("seed", [0, 7, 13])
    def test_rebuild_diff_empty_every_generation(self, seed):
        store = StateStore()
        for method, args in _gen_ops(seed, n_ops=60):
            getattr(store, method)(*copy.deepcopy(args))
            diffs = usage_rebuild_diff(store)
            assert diffs == [], (
                f"usage drift after {method} (seed {seed}): {diffs[:3]}")

    def test_rebuild_diff_under_write_load(self):
        """The torn-pair case the seed store needed a retry loop for:
        the diff runs against one snapshot, so a concurrent writer can
        never make it report phantom drift."""
        import threading

        store = StateStore()
        nodes = [mock.node() for _ in range(8)]
        for n in nodes:
            store.upsert_node(n)
        job = mock.job()
        store.upsert_job(job)
        stop = threading.Event()

        def churn():
            i = 0
            while not stop.is_set():
                a = mock.alloc(node_id=nodes[i % 8].id, job_id=job.id)
                store.upsert_allocs([a])
                store.update_allocs_from_client([structs.Allocation(
                    id=a.id, client_status=consts.ALLOC_CLIENT_COMPLETE,
                    task_states={})])
                i += 1

        t = threading.Thread(target=churn, daemon=True)
        t.start()
        try:
            for _ in range(25):
                assert usage_rebuild_diff(store) == []
        finally:
            stop.set()
            t.join()


# ---------------------------------------------------------------------------
# retention


class TestRetention:
    def test_dropped_snapshot_releases_generation(self):
        store = StateStore()
        for _ in range(5):
            store.upsert_node(mock.node())
        snap = store.snapshot()
        gen = snap.generation
        assert snapshot_at(gen) is not None
        assert store.snapshot_at(gen) is not None
        # advance the store: its CURRENT root moves on, so `snap`
        # becomes the generation's only remaining pin
        store.upsert_node(mock.node())
        assert snapshot_at(gen) is not None
        del snap
        gc.collect()
        assert snapshot_at(gen) is None  # weak registry let go
        # the CURRENT root is always pinned by the store itself
        cur = store.current_generation()
        assert store.snapshot_at(cur) is not None

    def test_write_burst_does_not_leak_roots(self):
        from nomad_tpu.state.store import _ROOT_REGISTRY, store_stats

        store = StateStore()
        n = mock.node()
        store.upsert_node(n)
        gc.collect()
        base = len(_ROOT_REGISTRY)
        for i in range(200):
            store.update_node_status(
                n.id, consts.NODE_STATUS_READY if i % 2 else
                consts.NODE_STATUS_DOWN)
        gc.collect()
        # unreferenced intermediate generations are all gone; only
        # roots someone (any test in the process) still pins survive
        assert len(_ROOT_REGISTRY) <= base + 1
        assert store_stats.snapshot()["live_roots"] == len(_ROOT_REGISTRY)

    def test_single_row_write_shares_untouched_rows(self):
        store = StateStore()
        nodes = [mock.node() for _ in range(300)]
        for n in nodes:
            store.upsert_node(n)
        root0 = store.snapshot()
        store.update_node_status(nodes[0].id, consts.NODE_STATUS_DOWN)
        root1 = store.snapshot()
        shared = sum(
            1 for n in nodes[1:]
            if root1.node_by_id(n.id) is root0.node_by_id(n.id))
        assert shared == 299
        assert root1.node_by_id(nodes[0].id) is not \
            root0.node_by_id(nodes[0].id)
