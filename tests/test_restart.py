"""Kill→restart recovery tests (ISSUE 13): the durability plane wired
through the server — the tier-1 pinned mini restart smoke, raft hard-
state safety across restarts (no double vote), event-stream resume
semantics over a full server restart, and the heartbeat-expired node
re-registering into a restarted cluster. The full restart chaos cell
runs in the stress tier (tests/test_stress.py::TestRestartCell)."""

import threading
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.raft.node import RaftConfig, RaftNode
from nomad_tpu.raft.transport import InmemTransport, TransportRegistry
from nomad_tpu.server.server import Server, ServerConfig
from nomad_tpu.server.testing import (
    hard_kill,
    make_cluster,
    restart_server,
    wait_for_leader,
)
from nomad_tpu.state.usage import usage_rebuild_diff
from nomad_tpu.structs import consts
from nomad_tpu.utils import faultpoints


@pytest.fixture(autouse=True)
def _clean_plane():
    faultpoints.reset()
    yield
    faultpoints.reset()


def _wait(fn, timeout=30.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return
        time.sleep(0.02)
    raise AssertionError(f"timeout waiting for {msg}")


def _live_allocs(server, jobs):
    snap = server.state.snapshot()
    return [a for j in jobs
            for a in snap.allocs_by_job(j.namespace, j.id)
            if not a.terminal_status()]


def _one_server(data_dir, **cfg_overrides):
    cfg = dict(num_workers=1, worker_batch_size=4, heartbeat_ttl=60.0,
               data_dir=data_dir)
    cfg.update(cfg_overrides)
    servers, registry = make_cluster(1, ServerConfig(**cfg),
                                     data_dirs=[data_dir])
    return servers[0], registry


class TestMiniRestartSmoke:
    def test_commit_kill_restart_bit_identical(self, tmp_path):
        """The tier-1 pinned restart smoke (ISSUE 13 satellite): a
        single durable server commits N evals, the node object is
        hard-dropped (in-memory state discarded wholesale), and a
        fresh server restarted from the data dir must converge to
        bit-identical usage planes with every eval terminal and every
        acked placement intact."""
        d = str(tmp_path / "srv")
        server, registry = _one_server(d)
        s2 = None
        try:
            wait_for_leader([server], timeout=15.0)
            for _ in range(6):
                server.node_register(mock.node())
            jobs = []
            for _ in range(4):
                j = mock.simple_job()
                j.task_groups[0].count = 2
                server.job_register(j)      # returning = acked
                jobs.append(j)
            _wait(lambda: len(_live_allocs(server, jobs)) == 8,
                  timeout=60.0, msg="burst placed")
            idx0 = server.state.latest_index()

            hard_kill(server)
            s2 = restart_server(server, registry)
            wait_for_leader([s2], timeout=15.0)
            _wait(lambda: s2.state.latest_index() >= idx0,
                  timeout=30.0, msg="recovery caught up")
            assert s2.raft.replayed_entries > 0
            # acked placements intact, exactly once
            live = _live_allocs(s2, jobs)
            assert len(live) == 8
            for j in jobs:
                mine = [a for a in live if a.job_id == j.id]
                assert len({a.name for a in mine}) == len(mine) == 2
            # usage planes bit-identical to a from-scratch rebuild
            assert usage_rebuild_diff(s2.state) == []

            def terminal():
                snap = s2.state.snapshot()
                if any(e.status == consts.EVAL_STATUS_PENDING
                       for e in snap.evals_iter()):
                    return False
                b = s2.eval_broker.stats()
                return b["total_ready"] == 0 and b["total_unacked"] == 0

            _wait(terminal, timeout=30.0, msg="evals terminal")
        finally:
            for s in (server, s2):
                if s is not None:
                    try:
                        s.shutdown()
                    except Exception:           # noqa: BLE001
                        pass


class TestHardStateDurability:
    FAST = RaftConfig(heartbeat_interval=0.02,
                      election_timeout_min=0.06,
                      election_timeout_max=0.12)

    def _bare_node(self, d, registry, peers=("n0", "peer-a", "peer-b")):
        node = RaftNode(
            node_id="n0", peers=list(peers),
            transport=InmemTransport("n0", registry),
            fsm_apply=lambda t, r: 0,
            config=self.FAST, data_dir=d,
        )
        return node

    def test_vote_survives_restart_no_double_vote(self, tmp_path):
        """The raft SAFETY half of the tentpole: a node that granted
        its term-5 vote to candidate A, crashed, and restarted must
        refuse candidate B in term 5 (the seed's in-memory term/vote
        allowed the double vote)."""
        d = str(tmp_path / "raft")
        registry = TransportRegistry()
        node = self._bare_node(d, registry)
        req = {"term": 5, "candidate": "peer-a",
               "last_log_index": 0, "last_log_term": 0}
        resp = node._on_request_vote(dict(req))
        assert resp["granted"]
        # crash: drop the object, no graceful anything
        node.transport.close()

        again = self._bare_node(d, registry)
        assert again.current_term == 5
        assert again.voted_for == "peer-a"
        steal = {"term": 5, "candidate": "peer-b",
                 "last_log_index": 99, "last_log_term": 5}
        assert not again._on_request_vote(steal)["granted"]
        # the same candidate may re-ask (lost response retry)
        assert again._on_request_vote(dict(req))["granted"]
        again.transport.close()

    def test_fallback_snapshot_behind_base_refuses_to_boot(self, tmp_path):
        """Keep-last-2 fallback meets a compacted log: when the newest
        snapshot fails its CRC and the older fallback sits BELOW the
        WAL's compacted base, the span in between is unreconstructable
        — recovery must refuse loudly, never boot an FSM silently
        missing committed state."""
        import os

        from nomad_tpu.raft.wal import (
            DurableLogStore,
            SnapshotStore,
            WalCorruptionError,
        )
        from nomad_tpu.raft.log import LogEntry

        d = str(tmp_path / "raft")
        os.makedirs(d)
        sn = SnapshotStore(d)
        sn.save(5, 1, b"older-fallback")
        newest = sn.save(20, 1, b"newest")
        log = DurableLogStore(os.path.join(d, "wal"))
        for i in range(1, 26):
            log.append(LogEntry(index=i, term=1, data=("op", i)))
        log.compact_to(20, 1)
        log.close()
        # bit-rot the newest snapshot: load falls back to index 5 < 20
        size = os.path.getsize(newest)
        with open(newest, "r+b") as f:
            f.seek(size - 1)
            last = f.read(1)
            f.seek(size - 1)
            f.write(bytes([last[0] ^ 0xFF]))
        registry = TransportRegistry()
        with pytest.raises(WalCorruptionError):
            RaftNode(
                node_id="n0", peers=["n0"],
                transport=InmemTransport("n0", registry),
                fsm_apply=lambda t, r: 0,
                restore_fn=lambda b: None,
                config=self.FAST, data_dir=d,
            )

    def test_failed_wal_demotes_leader_and_fails_over(self, tmp_path):
        """Fail-stop demotion: a leader whose WAL dies (torn write /
        IO error) must stop leading — its heartbeats would otherwise
        suppress elections forever while every write fails — and a
        healthy peer must take over (the reference's panic-and-
        failover, in-process)."""
        registry = TransportRegistry()
        addrs = ["d0", "d1", "d2"]
        nodes = []
        for addr in addrs:
            nodes.append(RaftNode(
                node_id=addr, peers=addrs,
                transport=InmemTransport(addr, registry),
                fsm_apply=lambda t, r: 0,
                config=self.FAST,
                data_dir=str(tmp_path / addr),
            ))
        for n in nodes:
            n.start()
        try:
            _wait(lambda: sum(n.is_leader() for n in nodes) == 1,
                  timeout=10.0, msg="initial leader")
            leader = next(n for n in nodes if n.is_leader())
            leader.apply("op", {"i": 0})
            # tear the LEADER's next journaled frame: WAL fail-stops
            faultpoints.arm(
                {"wal.frame.torn": {"kind": "error", "nth": 1}})
            with pytest.raises(faultpoints.FaultError):
                leader.apply("op", {"i": 1})
            faultpoints.disarm()
            assert leader.log.wal_failed
            _wait(lambda: not leader.is_leader(), timeout=10.0,
                  msg="failed-WAL leader demoted")
            _wait(lambda: any(n.is_leader() for n in nodes
                              if n is not leader),
                  timeout=10.0, msg="healthy peer took over")
            new_leader = next(n for n in nodes
                              if n is not leader and n.is_leader())
            assert new_leader.apply("op", {"i": 2}) is not None
            # the dead-disk node never reclaims leadership
            time.sleep(0.5)
            assert not leader.is_leader()
        finally:
            for n in nodes:
                n.shutdown()

    def test_term_adoption_durable_before_response(self, tmp_path):
        """AppendEntries carrying a newer term persists it before the
        ack: a restart must come back in the adopted term, not behind
        it."""
        d = str(tmp_path / "raft")
        registry = TransportRegistry()
        node = self._bare_node(d, registry)
        resp = node._on_append_entries({
            "term": 9, "leader": "peer-a", "prev_log_index": 0,
            "prev_log_term": 0, "entries": [], "leader_commit": 0,
        })
        assert resp["success"]
        node.transport.close()
        again = self._bare_node(d, registry)
        assert again.current_term == 9
        again.transport.close()


class TestStreamResumeAcrossRestart:
    def test_resume_above_boot_index_gap_free_no_duplicates(self, tmp_path):
        """ISSUE 13 satellite: a client holding ``?index=`` across a
        full server restart. With the whole history in the WAL, replay
        republishes every event with its original index — the resume
        delivers exactly the events past the client's index, no silent
        gap, no replayed duplicate."""
        d = str(tmp_path / "srv")
        server, registry = _one_server(d)
        s2 = None
        try:
            wait_for_leader([server], timeout=15.0)
            sub = server.event_broker.subscribe()
            for _ in range(3):
                server.node_register(mock.node())
            jobs = [mock.simple_job() for _ in range(2)]
            for j in jobs:
                j.task_groups[0].count = 1
                server.job_register(j)
            _wait(lambda: len(_live_allocs(server, jobs)) == 2,
                  timeout=60.0, msg="placed")
            seen = [e for e in sub.next_events(timeout=2.0,
                                               max_events=4096)]
            assert seen
            last_index = max(e.index for e in seen)
            seen_keys = {(e.index, e.topic, e.type, e.key) for e in seen}
            sub.close()

            hard_kill(server)
            s2 = restart_server(server, registry)
            wait_for_leader([s2], timeout=15.0)
            _wait(lambda: s2.state.latest_index() >= last_index,
                  timeout=30.0, msg="replay caught up")
            # register one more node so there is post-restart traffic
            post = mock.node()
            s2.node_register(post)
            resumed = s2.event_broker.subscribe(from_index=last_index)
            got = resumed.next_events(timeout=3.0, max_events=4096)
            from nomad_tpu.server.stream import TOPIC_LOST

            # everything the client already saw stays unseen (no
            # replayed duplicates) ...
            dupes = [e for e in got
                     if (e.index, e.topic, e.type, e.key) in seen_keys]
            assert not dupes, dupes[:3]
            # ... and the new event arrives without a loss marker
            assert any(e.key == post.id for e in got
                       if e.topic != TOPIC_LOST)
            assert not any(e.topic == TOPIC_LOST for e in got)
            resumed.close()
        finally:
            for s in (server, s2):
                if s is not None:
                    try:
                        s.shutdown()
                    except Exception:           # noqa: BLE001
                        pass

    def test_resume_below_boot_index_gets_explicit_lost_marker(
            self, tmp_path):
        """A snapshot compacts history the fresh ring can never
        replay: a client resuming below the boot index must get the
        explicit unknown-size LostEvents marker with a resume point —
        never a silent gap (the fresh-ring trimmed-history floor)."""
        d = str(tmp_path / "srv")
        server, registry = _one_server(d)
        s2 = None
        try:
            wait_for_leader([server], timeout=15.0)
            for _ in range(3):
                server.node_register(mock.node())
            job = mock.simple_job()
            job.task_groups[0].count = 1
            server.job_register(job)
            _wait(lambda: len(_live_allocs(server, [job])) == 1,
                  timeout=60.0, msg="placed")
            early_index = 1                 # a long-gone client cursor
            server.raft.force_snapshot()    # history compacted to disk

            hard_kill(server)
            s2 = restart_server(server, registry)
            wait_for_leader([s2], timeout=15.0)
            assert s2.raft.recovered_snapshot_index > early_index
            resumed = s2.event_broker.subscribe(from_index=early_index)
            s2.node_register(mock.node())   # wake the stream
            got = resumed.next_events(timeout=3.0, max_events=4096)
            from nomad_tpu.server.stream import TOPIC_LOST

            assert got and got[0].topic == TOPIC_LOST
            assert got[0].payload["LostEvents"] == -1
            assert got[0].payload["ResumeIndex"] >= 0
            resumed.close()
        finally:
            for s in (server, s2):
                if s is not None:
                    try:
                        s.shutdown()
                    except Exception:           # noqa: BLE001
                        pass


class TestExpiredNodeReregisterAcrossRestart:
    def test_expiry_reregister_reconcile_drain_preserved(self, tmp_path):
        """ISSUE 13 satellite: a node heartbeat-expires while its
        server cluster rides a leader kill→restart (step_down +
        restart interplay), then re-registers under the SAME id with a
        fresh struct. The drain-derived state (ineligibility, drain
        strategy) must survive the re-registration and the job must
        end exactly-once placed — no duplicate live allocs, nothing
        resurrected on the victim."""
        dirs = [str(tmp_path / f"srv-{i}") for i in range(3)]
        servers, registry = make_cluster(3, ServerConfig(
            num_workers=1, worker_batch_size=2, heartbeat_ttl=1.5,
            nack_timeout=1.5, data_dir=""), data_dirs=dirs)
        stop = threading.Event()
        try:
            wait_for_leader(servers, timeout=15.0)

            def cur_leader():
                for s in servers:
                    if s.raft.is_leader() and s.is_leader():
                        return s
                return None

            def with_leader(fn, timeout=20.0):
                deadline = time.time() + timeout
                last = None
                while time.time() < deadline:
                    s = cur_leader()
                    if s is not None:
                        try:
                            return fn(s)
                        except Exception as e:  # noqa: BLE001
                            last = e
                    time.sleep(0.05)
                raise AssertionError(f"no leader took the call: {last!r}")

            worker_node = mock.node()
            victim = mock.node()
            with_leader(lambda s: s.node_register(worker_node))
            with_leader(lambda s: s.node_register(victim))

            def keep_worker_alive():
                while not stop.is_set():
                    s = cur_leader()
                    if s is not None:
                        try:
                            s.node_heartbeat(worker_node.id, "ready")
                        except Exception:       # noqa: BLE001
                            pass
                    time.sleep(0.2)

            hb = threading.Thread(target=keep_worker_alive, daemon=True)
            hb.start()

            job = mock.simple_job()
            job.task_groups[0].count = 2
            with_leader(lambda s: s.job_register(job))
            _wait(lambda: len(_live_allocs(
                cur_leader() or servers[0], [job])) == 2,
                timeout=60.0, msg="job placed")

            # drain the victim: allocs migrate off; completion leaves
            # it ineligible (drainer semantics)
            with_leader(lambda s: s.node_update_drain(
                victim.id, True, None))
            _wait(lambda: all(
                a.node_id != victim.id for a in _live_allocs(
                    cur_leader() or servers[0], [job])),
                timeout=60.0, msg="victim drained")

            # kill the leader (deposed mid-flight) and restart it; the
            # VICTIM never heartbeats, so its TTL expires on whichever
            # leader owns the timers during the transition
            leader = cur_leader()
            idx = servers.index(leader)
            hard_kill(leader)
            fresh = restart_server(leader, registry)
            servers[idx] = fresh
            _wait(lambda: cur_leader() is not None, timeout=30.0,
                  msg="re-elected")
            _wait(lambda: (cur_leader() or servers[0]).state.snapshot()
                  .node_by_id(victim.id).status == consts.NODE_STATUS_DOWN,
                  timeout=30.0, msg="victim expired down")

            # the client restarts and re-registers: SAME id, fresh
            # struct (no drain fields — clients never set those)
            reborn = mock.node(id=victim.id)
            with_leader(lambda s, n=reborn: s.node_register(n))

            def settled():
                s = cur_leader()
                if s is None:
                    return False
                snap = s.state.snapshot()
                row = snap.node_by_id(victim.id)
                if row is None or row.status != consts.NODE_STATUS_READY:
                    return False
                live = _live_allocs(s, [job])
                names = [a.name for a in live]
                return (len(live) == 2 and len(set(names)) == 2
                        and all(a.node_id != victim.id for a in live))

            _wait(settled, timeout=60.0,
                  msg="reconciled exactly-once off the drained victim")
            row = (cur_leader() or servers[0]).state.snapshot() \
                .node_by_id(victim.id)
            # operator intent survived BOTH the server restart and the
            # client re-registration
            assert row.scheduling_eligibility == \
                consts.NODE_SCHEDULING_INELIGIBLE
        finally:
            stop.set()
            for s in servers:
                try:
                    s.shutdown()
                except Exception:               # noqa: BLE001
                    pass
