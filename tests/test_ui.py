"""Web UI serving tests.

The reference serves its Ember app at /ui (command/agent/http.go:318
UIEnabled handler); ours serves a single-file SPA. These tests cover
the HTTP wiring — redirect, catch-all document serving, and the
?resources=true stub extension the topology view uses.
"""

import time
import urllib.request

import pytest

from nomad_tpu import mock
from nomad_tpu.api.agent import Agent, AgentConfig
from nomad_tpu.api.client import APIClient
from nomad_tpu.api.codec import encode


@pytest.fixture(scope="module")
def agent():
    a = Agent(AgentConfig(name="ui-test-agent", num_schedulers=1))
    a.start()
    for _ in range(3):
        a.server.node_register(mock.node())
    yield a
    a.shutdown()


@pytest.fixture(scope="module")
def api(agent):
    return APIClient(agent.http_addr)


def _get(agent, path):
    req = urllib.request.Request(agent.http_addr + path)
    return urllib.request.urlopen(req, timeout=10)


class TestUIServing:
    def test_root_redirects_to_ui(self, agent):
        # urllib follows the 307; the final body is the app document
        resp = _get(agent, "/")
        assert resp.status == 200
        assert resp.url.endswith("/ui/")

    def test_ui_serves_app(self, agent):
        body = _get(agent, "/ui/").read().decode()
        assert "nomad-tpu" in body
        # the app module is extracted but served with the document
        assert '<script src="/ui/app.js">' in body
        js = _get(agent, "/ui/app.js").read().decode()
        # every app section is routable
        for view in ("#/jobs", "#/clients", "#/allocations",
                     "#/evaluations", "#/deployments", "#/topology",
                     "#/servers", "#/settings"):
            assert view in body or view in js

    def test_ui_catchall_paths_serve_same_doc(self, agent):
        a = _get(agent, "/ui/").read()
        b = _get(agent, "/ui/jobs/some-job").read()
        assert a == b
        assert _get(agent, "/ui").read() == a

    def test_content_type_is_html(self, agent):
        resp = _get(agent, "/ui/")
        assert resp.headers["Content-Type"].startswith("text/html")


class TestAllocStubResources:
    def test_resources_param_adds_allocated(self, agent, api):
        job = mock.job()
        api.jobs.register(encode(job))
        deadline = time.time() + 30
        while time.time() < deadline:
            allocs = api.get("/v1/allocations?resources=true")
            if allocs:
                break
            time.sleep(0.2)
        assert allocs, "no allocations placed"
        res = allocs[0]["AllocatedResources"]
        assert res["CPU"] > 0 and res["MemoryMB"] > 0
        # default stub stays lean
        lean = api.get("/v1/allocations")
        assert "AllocatedResources" not in lean[0]

    def test_node_stub_resources(self, api):
        nodes = api.get("/v1/nodes?resources=true")
        assert nodes and nodes[0]["NodeResources"]["CPU"] > 0
        assert nodes[0]["NodeResources"]["MemoryMB"] > 0
        assert "NodeResources" not in api.get("/v1/nodes")[0]


class TestUIExecTerminal:
    """The exec terminal's code path: the UI builds
    /v1/client/allocation/<id>/exec?task&tty&command&x_nomad_token and
    speaks the JSON-frame protocol over a websocket. This drives the
    EXACT request shape the SPA constructs (viewExec)."""

    def test_ui_document_has_exec_view_and_event_stream(self, agent):
        body = _get(agent, "/ui/app.js").read().decode()
        assert "viewExec" in body
        assert "/exec/" in body
        assert "startEventStream" in body
        assert "/v1/event/stream" in body
        assert "x_nomad_token" in body

    def test_exec_websocket_via_ui_url_shape(self):
        import base64
        import json as _json
        import urllib.parse

        from nomad_tpu.utils import ws as wslib

        # a dev agent: the exec session needs a real client + driver
        agent = Agent(AgentConfig.dev(name="ui-exec-agent"))
        agent.start()
        try:
            self._drive_exec(agent, base64, _json, urllib.parse, wslib)
        finally:
            agent.shutdown()

    def _drive_exec(self, agent, base64, _json, urlparse, wslib):
        job = mock.job()
        job.constraints = []
        tg = job.task_groups[0]
        tg.count = 1
        task = tg.tasks[0]
        task.driver = "raw_exec"
        task.config = {"command": "/bin/sh",
                       "args": ["-c", "sleep 120"]}
        agent.server.job_register(job)
        deadline = time.time() + 30
        alloc = None
        while time.time() < deadline:
            allocs = agent.server.state.snapshot().allocs_by_job(
                job.namespace, job.id)
            alloc = next((a for a in allocs
                          if a.client_status == "running"), None)
            if alloc:
                break
            time.sleep(0.2)
        assert alloc is not None, "task never ran"

        # the SPA's URL shape: query-string token + JSON command
        qs = urlparse.urlencode({
            "task": task.name, "tty": "false",
            "command": _json.dumps(["/bin/sh"]),
            "x_nomad_token": "",
        })
        url = (f"{agent.http_addr}/v1/client/allocation/"
               f"{alloc.id}/exec?{qs}")
        conn = wslib.connect(url)
        try:
            line = b"echo ui-exec-$((40+2))\n"
            conn.send(_json.dumps(
                {"stdin": {"data":
                           base64.b64encode(line).decode()}}).encode())
            got = b""
            deadline = time.time() + 20
            while b"ui-exec-42" not in got and time.time() < deadline:
                op, data = conn.recv()
                if op == wslib.OP_TEXT:
                    frame = _json.loads(data)
                    for k in ("stdout", "stderr"):
                        d = (frame.get(k) or {}).get("data")
                        if d:
                            got += base64.b64decode(d)
            assert b"ui-exec-42" in got
        finally:
            conn.close()


class TestUIHarness:
    """Mirage-analog harness: a seeded dev cluster behind the real /v1
    surface, driven through the SPA's exact request contract (no JS
    runtime ships in this environment; the click path exercises every
    call each view makes and the fields it consumes)."""

    def test_clicks_job_to_alloc_to_logs_and_files(self):
        from nomad_tpu.api.agent import Agent, AgentConfig
        from nomad_tpu.ui.harness import UIClient, seed_cluster

        agent = Agent(AgentConfig.dev())
        agent.start()
        try:
            seeded = seed_cluster(agent, n_service_jobs=1)
            ui = UIClient(agent.http.addr)

            # jobs list -> the seeded job row with the fields the
            # table renders
            jobs = ui.click_jobs()
            row = next(j for j in jobs if j["ID"] == "ui-seed-0")
            assert row["Status"] and row["Type"]

            # job detail fan-out -> an allocation id
            detail = ui.click_job("ui-seed-0")
            assert detail["job"]["ID"] == "ui-seed-0"
            assert detail["allocs"], "job detail shows no allocations"
            alloc_id = detail["allocs"][0]["ID"]

            # alloc detail -> task states the view renders
            a = ui.click_alloc(alloc_id)
            assert a["ClientStatus"] == "running"
            task = next(iter(a["TaskStates"]))

            # logs view -> the task's real output
            deadline = time.time() + 20
            logs = ""
            while time.time() < deadline and "ui-harness-line" not in logs:
                logs = ui.click_logs(alloc_id, task)
                time.sleep(0.2)
            assert "ui-harness-line" in logs

            # fs browser -> walk to the log file (alloc/logs, the
            # reference layout) and read it back
            entries = ui.click_fs(alloc_id, "/")
            shared = next(e for e in entries if e["Name"] == "alloc")
            assert shared["IsDir"]
            files = ui.click_fs(alloc_id, "/alloc/logs")
            logfile = next(e for e in files
                           if e["Name"].endswith(".stdout.0"))
            got = ui.click_file(alloc_id,
                                f"/alloc/logs/{logfile['Name']}")
            assert "ui-harness-line" in got["Data"]
        finally:
            agent.shutdown()

    def test_every_spa_api_reference_has_a_route(self, agent):
        """A renamed endpoint must fail THIS test, not silently 404 in
        the browser (the contract half of the Mirage analog)."""
        import os

        from nomad_tpu.ui.harness import unrouted_paths

        app_js = open(os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "nomad_tpu", "ui", "app.js")).read()
        missing = unrouted_paths(app_js, agent.http)
        assert missing == [], f"SPA references unrouted paths: {missing}"

    def test_app_js_served_and_referenced(self, agent):
        import urllib.request

        doc = urllib.request.urlopen(
            agent.http.addr + "/ui/").read().decode()
        assert '<script src="/ui/app.js">' in doc
        js = urllib.request.urlopen(
            agent.http.addr + "/ui/app.js").read().decode()
        assert "viewAllocFs" in js and "viewAllocLogs" in js
        assert "/v1/client/fs/ls" in js

    def test_app_js_is_structurally_valid(self):
        """One syntax error aborts the whole SPA module; with no JS
        runtime in this environment, the structural lint is the
        backstop for the bricking error class (unbalanced brackets,
        unterminated strings/templates)."""
        import os

        from nomad_tpu.ui.harness import lint_js

        src = open(os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "nomad_tpu", "ui", "app.js")).read()
        assert lint_js(src) == []
        # the linter itself catches what it claims to catch
        assert lint_js("function f() { return `${x`; }")
        assert lint_js("const a = (1, [2, 3);")
        assert lint_js("const s = 'oops\nmore';")
        assert lint_js("/* never closed")


class TestViewContract:
    """The machine-checked view contract (VERDICT r4 #6): app.js embeds
    a route -> endpoint -> field manifest; the harness (a) cross-checks
    every PascalCase field read in each view against the manifest and
    (b) walks every declared field path against the REAL seeded API.
    Together: a view cannot read a field the API does not return
    without one of these tests failing — the executable equivalent of
    running the SPA against reference Mirage (ui/mirage/config.js)."""

    @staticmethod
    def _app_js():
        import os

        return open(os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "nomad_tpu", "ui", "app.js")).read()

    def test_contract_parses_and_covers_every_routed_view(self):
        import re

        from nomad_tpu.ui.harness import extract_view_contract

        src = self._app_js()
        contract = extract_view_contract(src)
        assert "helpers" in contract
        # every view the router dispatches to has a contract entry
        # (viewExec drives a websocket, exempt by design)
        routed = set(re.findall(r"\bview\w+", src.split("const routes")[1]))
        missing = sorted(routed - set(contract) - {"viewExec"})
        assert missing == [], f"routed views missing a contract: {missing}"

    def test_every_field_read_is_declared(self):
        from nomad_tpu.ui.harness import undeclared_field_reads

        extra = undeclared_field_reads(self._app_js())
        assert extra == {}, (
            f"views read API fields the contract never walks: {extra}")

    def test_contract_walks_clean_against_a_seeded_cluster(self):
        import time

        from nomad_tpu import mock
        from nomad_tpu.api.agent import Agent, AgentConfig
        from nomad_tpu.structs import csi
        from nomad_tpu.ui.harness import (
            UIClient, extract_view_contract, seed_cluster,
            walk_view_contract,
        )

        agent = Agent(AgentConfig.dev())
        agent.start()
        try:
            seeded = seed_cluster(agent, n_service_jobs=1)
            server = agent.server
            # CSI seeds: a fingerprinting node + a registered volume
            n = mock.node()
            n.csi_node_plugins = {"plug-ui": {"provider": "ui.csi",
                                              "version": "1.0",
                                              "healthy": True}}
            n.csi_controller_plugins = {"plug-ui": {"provider": "ui.csi",
                                                    "version": "1.0",
                                                    "healthy": True}}
            server.node_register(n)
            vol = csi.CSIVolume(
                id="ui-vol", namespace="default", name="ui-vol",
                external_id="ext-ui-vol", plugin_id="plug-ui",
                requested_capabilities=[csi.CSIVolumeCapability(
                    access_mode=csi.ACCESS_MODE_SINGLE_NODE_WRITER,
                    attachment_mode=csi.ATTACHMENT_MODE_FS)],
            )
            server.csi_volume_register([vol])
            # ACL seed: a policy + token the ACL views render
            from nomad_tpu.acl.policy import ACLPolicy, ACLToken
            server.state.upsert_acl_policy(ACLPolicy(
                name="ui-policy", description="ui harness seed",
                rules='namespace "default" { policy = "read" }'))
            server.state.upsert_acl_token(ACLToken.create(
                name="ui-token", type="client",
                policies=["ui-policy"]))

            alloc0 = seeded["allocs"][0]
            # a native service registration (services views)
            from nomad_tpu.structs.services import ServiceRegistration
            server.service_register([ServiceRegistration(
                id="ui-svc-1", service_name="web", namespace="default",
                node_id=alloc0.node_id, job_id=seeded["jobs"][0].id,
                alloc_id=alloc0.id, address="127.0.0.1", port=8080,
                tags=["ui"])])
            # a deployment row (deployments views): service job with an
            # update strategy
            dj = mock.job(id="ui-deploy-job")
            dj.type = "service"
            dj.task_groups[0].count = 1
            dj.task_groups[0].tasks[0].driver = "mock_driver"
            from nomad_tpu.structs.job import UpdateStrategy
            dj.task_groups[0].update = UpdateStrategy(
                max_parallel=1, min_healthy_time_s=0.1,
                healthy_deadline_s=30, progress_deadline_s=600)
            server.job_register(dj)
            deadline = time.time() + 30
            while time.time() < deadline:
                if server.state.snapshot().latest_deployment_by_job_id(
                        "default", "ui-deploy-job") is not None:
                    break
                time.sleep(0.2)

            alloc = seeded["allocs"][0]
            job = seeded["jobs"][0]
            # a log file the fs/stat walk can stat
            deadline = time.time() + 20
            ui = UIClient(agent.http.addr)
            logfile = None
            while time.time() < deadline and logfile is None:
                try:
                    files = ui.click_fs(alloc.id, "/alloc/logs")
                    logfile = next(
                        (e["Name"] for e in files
                         if e["Name"].endswith(".stdout.0")), None)
                except Exception:                # noqa: BLE001
                    pass
                if logfile is None:
                    time.sleep(0.3)
            assert logfile, "no rotated log file appeared"

            params = {
                # the deployment-bearing job exercises the full job
                # detail fan-out (deployments included)
                "job": "ui-deploy-job",
                "node": alloc.node_id,
                "alloc": alloc.id,
                "volume": "ui-vol",
                "plugin": "plug-ui",
                "policy": "ui-policy",
                "service": "web",
                "task": next(iter(alloc.task_states or {"web": 1})),
                "file": f"/alloc/logs/{logfile}",
            }
            contract = extract_view_contract(self._app_js())
            failures = walk_view_contract(ui, contract, params)
            assert failures == [], "\n".join(failures)
        finally:
            agent.shutdown()
