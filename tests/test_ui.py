"""Web UI serving tests.

The reference serves its Ember app at /ui (command/agent/http.go:318
UIEnabled handler); ours serves a single-file SPA. These tests cover
the HTTP wiring — redirect, catch-all document serving, and the
?resources=true stub extension the topology view uses.
"""

import time
import urllib.request

import pytest

from nomad_tpu import mock
from nomad_tpu.api.agent import Agent, AgentConfig
from nomad_tpu.api.client import APIClient
from nomad_tpu.api.codec import encode


@pytest.fixture(scope="module")
def agent():
    a = Agent(AgentConfig(name="ui-test-agent", num_schedulers=1))
    a.start()
    for _ in range(3):
        a.server.node_register(mock.node())
    yield a
    a.shutdown()


@pytest.fixture(scope="module")
def api(agent):
    return APIClient(agent.http_addr)


def _get(agent, path):
    req = urllib.request.Request(agent.http_addr + path)
    return urllib.request.urlopen(req, timeout=10)


class TestUIServing:
    def test_root_redirects_to_ui(self, agent):
        # urllib follows the 307; the final body is the app document
        resp = _get(agent, "/")
        assert resp.status == 200
        assert resp.url.endswith("/ui/")

    def test_ui_serves_app(self, agent):
        body = _get(agent, "/ui/").read().decode()
        assert "nomad-tpu" in body
        assert "<script>" in body
        # every app section is routable
        for view in ("#/jobs", "#/clients", "#/allocations",
                     "#/evaluations", "#/deployments", "#/topology",
                     "#/servers", "#/settings"):
            assert view in body

    def test_ui_catchall_paths_serve_same_doc(self, agent):
        a = _get(agent, "/ui/").read()
        b = _get(agent, "/ui/jobs/some-job").read()
        assert a == b
        assert _get(agent, "/ui").read() == a

    def test_content_type_is_html(self, agent):
        resp = _get(agent, "/ui/")
        assert resp.headers["Content-Type"].startswith("text/html")


class TestAllocStubResources:
    def test_resources_param_adds_allocated(self, agent, api):
        job = mock.job()
        api.jobs.register(encode(job))
        deadline = time.time() + 30
        while time.time() < deadline:
            allocs = api.get("/v1/allocations?resources=true")
            if allocs:
                break
            time.sleep(0.2)
        assert allocs, "no allocations placed"
        res = allocs[0]["AllocatedResources"]
        assert res["CPU"] > 0 and res["MemoryMB"] > 0
        # default stub stays lean
        lean = api.get("/v1/allocations")
        assert "AllocatedResources" not in lean[0]

    def test_node_stub_resources(self, api):
        nodes = api.get("/v1/nodes?resources=true")
        assert nodes and nodes[0]["NodeResources"]["CPU"] > 0
        assert nodes[0]["NodeResources"]["MemoryMB"] > 0
        assert "NodeResources" not in api.get("/v1/nodes")[0]
