"""Sharded resident cluster state (ISSUE 14 tentpole): generations
placed over the device mesh's nodes axis must stay BIT-IDENTICAL to a
fresh ``ClusterTensors.build`` + upload through every advance path the
single-device suite proves (tests/test_device_state.py) — dirty-row
scatter, structure forks, eviction/miss rebuilds, trimmed-log
fallbacks — while every resident plane actually lives split across the
8 conftest host devices, and placement-mismatched lookups MISS instead
of leaking a sharded buffer into a single-device dispatch (or vice
versa).
"""

import numpy as np
import numpy.testing as npt
import pytest

jax = pytest.importorskip("jax")

from nomad_tpu import mock  # noqa: E402
from nomad_tpu.parallel.sharded import (  # noqa: E402
    shared_field_spec,
    wave_mesh,
)
from nomad_tpu.state.store import StateStore  # noqa: E402
from nomad_tpu.tensors.device_state import DeviceClusterState  # noqa: E402
from nomad_tpu.tensors.schema import (  # noqa: E402
    ClusterTensors,
    IncrementalClusterCache,
)


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= 8, "conftest must force 8 CPU devices"
    return wave_mesh(8)


def assert_sharded_matches_fresh(ds, snap, mesh) -> None:
    """The resident generation for ``snap`` is bit-identical to a
    fresh host build AND split over the mesh's devices."""
    u = snap.usage
    fresh = ClusterTensors.build(snap.nodes())
    want = fresh.wave_shared_planes(u)
    gen = ds._gens[(u.uid, u.structure_version)]
    assert gen.mesh is mesh
    for f, host in want.items():
        dev = gen.planes[f]
        got = np.asarray(dev)
        assert got.dtype == host.dtype, f
        npt.assert_array_equal(got, host, err_msg=f)
        # placement is REAL sharding, not replication on one device
        assert len(dev.sharding.device_set) == mesh.size, \
            (f, dev.sharding)


def _store(n_nodes: int) -> StateStore:
    s = StateStore()
    for _ in range(n_nodes):
        s.upsert_node(mock.node())
    return s


def _ensure(ds, cache, store):
    snap = store.snapshot()
    ds.ensure(cache.get(snap), snap.usage)
    return snap


class TestShardedDeltaParity:
    def test_alloc_churn_advances_by_sharded_scatter(self, mesh):
        store = _store(24)
        ds = DeviceClusterState(mesh=mesh)
        cache = IncrementalClusterCache()
        _ensure(ds, cache, store)
        nodes = store.snapshot().nodes()
        store.upsert_allocs(
            [mock.alloc(node_id=nodes[i % 8].id) for i in range(20)])
        snap = _ensure(ds, cache, store)
        assert ds.delta_advances == 1
        assert ds.full_uploads == 1          # only the initial build
        assert ds.usage_full_uploads == 0
        assert_sharded_matches_fresh(ds, snap, mesh)

    def test_structure_fork_stays_sharded(self, mesh):
        store = _store(24)
        ds = DeviceClusterState(mesh=mesh)
        cache = IncrementalClusterCache()
        _ensure(ds, cache, store)
        node = store.snapshot().nodes()[5].copy()
        node.node_resources.cpu.cpu_shares = 12345
        store.upsert_node(node)
        snap = _ensure(ds, cache, store)
        assert ds.fork_deltas == 1
        assert_sharded_matches_fresh(ds, snap, mesh)

    @pytest.mark.parametrize("n_nodes", [9, 24, 63])
    def test_uneven_node_counts_pad_to_shard_multiples(self, mesh,
                                                       n_nodes):
        """Real node counts that do NOT divide the mesh: the pad
        bucket (power of two, min 64) always does, so real rows land
        unevenly across shards — parity must hold through churn."""
        store = _store(n_nodes)
        ds = DeviceClusterState(mesh=mesh)
        cache = IncrementalClusterCache()
        _ensure(ds, cache, store)
        nodes = store.snapshot().nodes()
        store.upsert_allocs(
            [mock.alloc(node_id=nodes[i % n_nodes].id)
             for i in range(min(n_nodes * 2, 30))])
        snap = _ensure(ds, cache, store)
        assert ds.delta_advances == 1
        assert_sharded_matches_fresh(ds, snap, mesh)

    def test_random_sharded_sequences(self, mesh):
        """Property-style: random interleavings of alloc transitions
        and node adds/updates/drains/deletes, sharded-device-vs-fresh
        parity after every round (the device mirror of the
        single-device suite's random walk)."""
        rng = np.random.default_rng(41)
        store = _store(24)
        ds = DeviceClusterState(mesh=mesh)
        cache = IncrementalClusterCache()
        _ensure(ds, cache, store)
        live = []
        for _round in range(6):
            for _ in range(int(rng.integers(1, 5))):
                nodes = store.snapshot().nodes()
                pick = nodes[int(rng.integers(0, len(nodes)))]
                op = rng.integers(0, 6)
                if op == 0:
                    a = mock.alloc(node_id=pick.id)
                    live.append(a)
                    store.upsert_allocs([a])
                elif op == 1 and live:
                    a = live.pop(int(rng.integers(0, len(live))))
                    store.stop_alloc(a.id, [])
                elif op == 2:
                    store.upsert_node(mock.node())
                elif op == 3:
                    n = pick.copy()
                    n.node_resources.cpu.cpu_shares = int(
                        rng.integers(1000, 9000))
                    store.upsert_node(n)
                elif op == 4:
                    store.update_node_drain(pick.id,
                                            bool(rng.integers(0, 2)))
                elif len(nodes) > 4:
                    store.delete_node(pick.id)
            snap = _ensure(ds, cache, store)
            assert_sharded_matches_fresh(ds, snap, mesh)
        assert ds.delta_advances + ds.fork_deltas >= 2

    def test_trimmed_row_log_full_upload_stays_sharded(self, mesh):
        """The unprovable-log fallback re-uploads the usage planes —
        WITH the generation's sharded placement, not to one device."""
        from nomad_tpu.state import usage as usage_mod

        store = _store(24)
        ds = DeviceClusterState(mesh=mesh)
        cache = IncrementalClusterCache()
        _ensure(ds, cache, store)
        nodes = store.snapshot().nodes()
        for i in range(usage_mod.ROW_LOG_MAX + 8):
            store.upsert_allocs([mock.alloc(node_id=nodes[i % 8].id)])
        snap = _ensure(ds, cache, store)
        assert ds.usage_full_uploads == 1
        assert ds.delta_advances == 0
        assert_sharded_matches_fresh(ds, snap, mesh)

    def test_eviction_and_miss_rebuild_sharded(self, mesh):
        store = _store(24)
        ds = DeviceClusterState(max_generations=2, mesh=mesh)
        cache = IncrementalClusterCache()
        first = store.snapshot()
        first_cluster = cache.get(first)
        ds.ensure(first_cluster, first.usage)
        first_host = first_cluster.wave_shared_planes(first.usage)
        for _ in range(3):
            store.upsert_node(mock.node())
            _ensure(ds, cache, store)
        assert len(ds._gens) == 2
        assert ds.lookup(first_host["cap_cpu"], mesh=mesh) is None
        full_before = ds.full_uploads
        ds.ensure(first_cluster, first.usage)
        assert ds.full_uploads == full_before + 1
        gen = ds._gens[(first.usage.uid,
                        first.usage.structure_version)]
        for f, host in first_host.items():
            npt.assert_array_equal(np.asarray(gen.planes[f]), host,
                                   err_msg=f)


class TestPlacementIsolation:
    def test_single_device_lookup_misses_sharded_generation(self, mesh):
        """A direct (unsharded) dispatch must never receive a sharded
        buffer: it would reshard inside the jit and fork its cache."""
        store = _store(16)
        ds = DeviceClusterState(mesh=mesh)
        cache = IncrementalClusterCache()
        snap = store.snapshot()
        cluster = cache.get(snap)
        ds.ensure(cluster, snap.usage)
        host = cluster.wave_shared_planes(snap.usage)
        # frozen_ok=False: the launcher's contract for the snapshot
        # group (the gathered planes are read-only, and the frozen-
        # singleton path would otherwise mint an unsharded twin)
        for f, arr in host.items():
            assert ds.lookup(arr, frozen_ok=False,
                             mesh=mesh) is not None, f
            assert ds.lookup(arr, frozen_ok=False) is None, f
            assert ds.lookup(arr, frozen_ok=False,
                             mesh=wave_mesh(4)) is None, f

    def test_frozen_singleton_resident_under_both_placements(self, mesh):
        from nomad_tpu.ops.kernel import neutral_planes

        ds = DeviceClusterState(mesh=mesh)
        host = neutral_planes(64).zeros_f32
        spec = shared_field_spec("cap_cpu")
        dev_sharded = ds.lookup(host, spec=spec, mesh=mesh)
        dev_single = ds.lookup(host)
        assert dev_sharded is not None and dev_single is not None
        assert dev_sharded is not dev_single
        assert len(dev_sharded.sharding.device_set) == mesh.size
        npt.assert_array_equal(np.asarray(dev_sharded), host)
        npt.assert_array_equal(np.asarray(dev_single), host)
        # repeat lookups serve the SAME resident arrays (no re-upload)
        assert ds.lookup(host, spec=spec, mesh=mesh) is dev_sharded
        assert ds.lookup(host) is dev_single

    def test_foreign_mesh_frozen_lookup_misses(self, mesh):
        from nomad_tpu.ops.kernel import neutral_planes

        ds = DeviceClusterState(mesh=mesh)
        host = neutral_planes(64).zeros_f32
        spec = shared_field_spec("cap_cpu")
        other = wave_mesh(4)
        assert ds.lookup(host, spec=spec, mesh=other) is None
        # ... including once an entry for the SAME spec is resident
        # under the state's own mesh (the spec key alone would
        # collide across meshes and hand the foreign caller a buffer
        # placed for the wrong device set)
        assert ds.lookup(host, spec=spec, mesh=mesh) is not None
        assert ds.lookup(host, spec=spec, mesh=other) is None

    def test_configure_mesh_change_evicts_everything(self, mesh):
        store = _store(16)
        ds = DeviceClusterState()                    # single-device
        cache = IncrementalClusterCache()
        snap = store.snapshot()
        cluster = cache.get(snap)
        ds.ensure(cluster, snap.usage)
        host = cluster.wave_shared_planes(snap.usage)
        assert ds.lookup(host["cap_cpu"]) is not None
        ds.configure_mesh(mesh)
        assert ds.lookup(host["cap_cpu"]) is None
        assert ds.lookup(host["cap_cpu"], mesh=mesh) is None
        assert len(ds._gens) == 0 and len(ds._frozen) == 0
        # re-ensure builds the sharded generation
        ds.ensure(cluster, snap.usage)
        assert ds.lookup(host["cap_cpu"], mesh=mesh) is not None
        # equal mesh (a NEW object over the same devices) is a no-op
        gen_before = dict(ds._gens)
        ds.configure_mesh(wave_mesh(8))
        assert dict(ds._gens) == gen_before

    def test_indivisible_node_axis_places_single_device(self):
        """A mesh whose device count does not divide the pad bucket
        (3 devices x 64-row bucket): generations place single-device
        and the registry serves UNSHARDED callers — the launcher makes
        the same call and counts an unsharded fallback."""
        store = _store(16)
        ds = DeviceClusterState(mesh=wave_mesh(3))
        cache = IncrementalClusterCache()
        snap = store.snapshot()
        cluster = cache.get(snap)
        gen = ds.ensure(cluster, snap.usage)
        assert gen.mesh is None
        host = cluster.wave_shared_planes(snap.usage)
        assert ds.lookup(host["cap_cpu"]) is not None
