"""Core data-model tests (reference: nomad/structs/*_test.go semantics)."""

import math

import pytest

from nomad_tpu import mock, structs
from nomad_tpu.structs import consts
from nomad_tpu.structs.constraints import (
    check_constraint,
    check_version_constraint,
    node_meets_constraints,
)


class TestScoreFit:
    """Reference: structs/funcs_test.go TestScoreFitBinPack/Spread."""

    def _node(self, cpu=4096, mem=8192):
        n = mock.node()
        n.node_resources.cpu.cpu_shares = cpu
        n.node_resources.memory.memory_mb = mem
        n.reserved_resources = structs.NodeReservedResources()
        return n

    def test_binpack_half_util(self):
        node = self._node()
        util = structs.ComparableResources(cpu_shares=2048, memory_mb=4096)
        # freePct = 0.5 each: 20 - 2*10^0.5 ~= 13.675
        score = structs.score_fit_binpack(node, util)
        assert score == pytest.approx(20.0 - 2 * math.pow(10, 0.5), abs=1e-9)

    def test_binpack_full_util(self):
        node = self._node()
        util = structs.ComparableResources(cpu_shares=4096, memory_mb=8192)
        assert structs.score_fit_binpack(node, util) == pytest.approx(18.0)

    def test_binpack_zero_util(self):
        node = self._node()
        util = structs.ComparableResources()
        assert structs.score_fit_binpack(node, util) == pytest.approx(0.0)

    def test_spread_is_inverse(self):
        node = self._node()
        util = structs.ComparableResources(cpu_shares=2048, memory_mb=4096)
        b = structs.score_fit_binpack(node, util)
        s = structs.score_fit_spread(node, util)
        assert s == pytest.approx(2 * math.pow(10, 0.5) - 2, abs=1e-9)
        assert b != s

    def test_reserved_resources_shrink_capacity(self):
        node = self._node()
        node.reserved_resources = structs.NodeReservedResources(
            cpu_shares=2048, memory_mb=4096
        )
        util = structs.ComparableResources(cpu_shares=2048, memory_mb=4096)
        # all remaining capacity used -> perfect fit
        assert structs.score_fit_binpack(node, util) == pytest.approx(18.0)


class TestAllocsFit:
    """Reference: structs/funcs_test.go TestAllocsFit*."""

    def test_fits(self):
        node = mock.node()
        a = mock.alloc()
        fit, dim, used = structs.allocs_fit(node, [a], None, False)
        assert fit, dim
        assert used.cpu_shares == 500
        assert used.memory_mb == 256

    def test_exceeds_memory(self):
        node = mock.node()
        big = mock.alloc()
        big.allocated_resources.tasks["web"].memory.memory_mb = 9000
        fit, dim, _ = structs.allocs_fit(node, [big], None, False)
        assert not fit
        assert dim == "memory"

    def test_terminal_allocs_ignored(self):
        node = mock.node()
        stopped = mock.alloc()
        stopped.desired_status = consts.ALLOC_DESIRED_STOP
        allocs = [mock.alloc() for _ in range(4)] + [stopped]
        fit, dim, used = structs.allocs_fit(node, allocs, None, False)
        assert fit, dim
        assert used.cpu_shares == 2000

    def test_core_overlap(self):
        node = mock.node()
        a1, a2 = mock.alloc(), mock.alloc()
        a1.allocated_resources.tasks["web"].cpu.reserved_cores = [0]
        a2.allocated_resources.tasks["web"].cpu.reserved_cores = [0]
        fit, dim, _ = structs.allocs_fit(node, [a1, a2], None, False)
        assert not fit
        assert dim == "cores"

    def test_port_collision(self):
        node = mock.node()
        a1, a2 = mock.alloc(), mock.alloc()
        for a in (a1, a2):
            a.allocated_resources.tasks["web"].networks = [
                structs.NetworkResource(
                    device="eth0", ip="192.168.0.100",
                    reserved_ports=[structs.Port(label="main", value=8000)],
                )
            ]
        fit, dim, _ = structs.allocs_fit(node, [a1, a2], None, False)
        assert not fit
        assert "collision" in dim

    def test_device_oversubscription(self):
        node = mock.node()
        node.node_resources.devices = [
            structs.NodeDeviceResource(
                vendor="nvidia", type="gpu", name="1080ti",
                instance_ids=["d1"],
            )
        ]
        a1, a2 = mock.alloc(), mock.alloc()
        for a in (a1, a2):
            a.allocated_resources.tasks["web"].devices = [
                structs.AllocatedDeviceResource(
                    vendor="nvidia", type="gpu", name="1080ti", device_ids=["d1"]
                )
            ]
        fit, dim, _ = structs.allocs_fit(node, [a1, a2], None, True)
        assert not fit
        assert dim == "device oversubscribed"


class TestNetworkIndex:
    """Reference: structs/network_test.go semantics."""

    def test_set_node_reserved_port(self):
        idx = structs.NetworkIndex()
        node = mock.node()
        collide, _ = idx.set_node(node)
        assert not collide
        # port 22 is agent-reserved
        used = idx.port_words()
        assert used[22 // 64] & (1 << (22 % 64))

    def test_assign_network_dynamic(self):
        idx = structs.NetworkIndex()
        idx.set_node(mock.node())
        ask = structs.NetworkResource(
            mbits=50, dynamic_ports=[structs.Port(label="http")]
        )
        offer, err = idx.assign_network(ask)
        assert offer is not None, err
        port = offer.dynamic_ports[0].value
        assert 20000 <= port <= 32000

    def test_assign_network_reserved_collision(self):
        idx = structs.NetworkIndex()
        idx.set_node(mock.node())
        ask = structs.NetworkResource(
            mbits=10, reserved_ports=[structs.Port(label="ssh", value=22)]
        )
        offer, err = idx.assign_network(ask)
        assert offer is None
        assert "collision" in err

    def test_bandwidth_overcommit(self):
        idx = structs.NetworkIndex()
        idx.set_node(mock.node())
        ask = structs.NetworkResource(mbits=800)
        offer, err = idx.assign_network(ask)
        assert offer is not None
        idx.add_reserved(offer)
        offer2, err2 = idx.assign_network(structs.NetworkResource(mbits=300))
        assert offer2 is None
        assert "bandwidth" in err2

    def test_assign_ports_group(self):
        idx = structs.NetworkIndex()
        idx.set_node(mock.node())
        ask = structs.NetworkResource(
            reserved_ports=[structs.Port(label="db", value=5432)],
            dynamic_ports=[structs.Port(label="http", to=-1)],
        )
        offer, err = idx.assign_ports(ask)
        assert offer is not None, err
        labels = {p.label: p for p in offer}
        assert labels["db"].value == 5432
        assert labels["http"].to == labels["http"].value


class TestConstraints:
    def test_operands(self):
        assert check_constraint("=", "linux", "linux", True, True)
        assert not check_constraint("=", "linux", "windows", True, True)
        assert check_constraint("!=", "linux", "windows", True, True)
        assert check_constraint("!=", None, "windows", False, True)
        assert not check_constraint("!=", None, None, False, False)
        assert check_constraint("regexp", "ubuntu-20.04", r"ubuntu-\d+", True, True)
        assert not check_constraint("regexp", "centos", r"ubuntu-\d+", True, True)
        assert check_constraint("set_contains", "a,b,c", "a,c", True, True)
        assert not check_constraint("set_contains", "a,b", "a,z", True, True)
        assert check_constraint("set_contains_any", "a,b", "z,b", True, True)
        assert check_constraint("is_set", "anything", None, True, False)
        assert check_constraint("is_not_set", None, None, False, False)
        assert check_constraint(">", "b", "a", True, True)
        assert check_constraint("<=", "a", "a", True, True)

    def test_version_constraints(self):
        assert check_version_constraint("1.2.3", ">= 1.0, < 2.0")
        assert not check_version_constraint("2.1.0", ">= 1.0, < 2.0")
        assert check_version_constraint("1.2.3", "~> 1.2")
        assert not check_version_constraint("2.0.0", "~> 1.2")
        assert check_version_constraint("1.2.4", "~> 1.2.3")
        assert not check_version_constraint("1.3.0", "~> 1.2.3")
        assert check_version_constraint("1.7.0-beta1", ">= 1.6.0")
        # semver: prerelease does not satisfy plain range
        assert not check_version_constraint("1.7.0-beta1", ">= 1.6.0", semver=True)

    def test_node_meets_constraints(self):
        node = mock.node()
        ok = node_meets_constraints(
            node,
            [structs.Constraint(ltarget="${attr.kernel.name}", rtarget="linux")],
        )
        assert ok
        bad = node_meets_constraints(
            node,
            [structs.Constraint(ltarget="${attr.kernel.name}", rtarget="darwin")],
        )
        assert not bad


class TestAllocStatuses:
    def test_terminal(self):
        a = mock.alloc()
        assert not a.terminal_status()
        a.desired_status = consts.ALLOC_DESIRED_STOP
        assert a.terminal_status()
        b = mock.alloc()
        b.client_status = consts.ALLOC_CLIENT_FAILED
        assert b.terminal_status()

    def test_index_parse(self):
        a = mock.alloc()
        a.name = "my-job.web[13]"
        assert a.index() == 13

    def test_next_delay(self):
        a = mock.alloc()
        pol = structs.ReschedulePolicy(
            attempts=3, interval_s=600, delay_s=5, delay_function="exponential",
            max_delay_s=100,
        )
        assert a._next_delay(pol, 0) == 5
        assert a._next_delay(pol, 2) == 20
        assert a._next_delay(pol, 10) == 100  # capped
        fib = structs.ReschedulePolicy(
            delay_s=5, delay_function="fibonacci", max_delay_s=1000
        )
        assert a._next_delay(fib, 0) == 5
        assert a._next_delay(fib, 1) == 5
        assert a._next_delay(fib, 2) == 10
        assert a._next_delay(fib, 3) == 15
        assert a._next_delay(fib, 4) == 25


class TestNodeClass:
    def test_same_attrs_same_class(self):
        n1, n2 = mock.node(), mock.node()
        assert n1.computed_class == n2.computed_class

    def test_different_class(self):
        n1 = mock.node()
        n2 = mock.node()
        n2.attributes["kernel.name"] = "windows"
        n2.compute_class()
        assert n1.computed_class != n2.computed_class

    def test_unique_attrs_excluded(self):
        n1 = mock.node()
        n2 = mock.node()
        n2.attributes["unique.hostname"] = "different"
        n2.compute_class()
        assert n1.computed_class == n2.computed_class


class TestPlan:
    def test_append_stopped(self):
        plan = structs.Plan()
        a = mock.alloc()
        plan.append_stopped_alloc(a, "no longer needed")
        assert plan.node_update[a.node_id][0].desired_status == "stop"
        # original untouched
        assert a.desired_status == "run"

    def test_make_plan(self):
        e = mock.eval()
        j = mock.job()
        p = e.make_plan(j)
        assert p.eval_id == e.id
        assert p.job is j
