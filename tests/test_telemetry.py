"""The telemetry subsystem (ISSUE 1): span tracing, kernel profiling,
exposition, and the live-path trace decomposition.

Covers the acceptance surface directly:
- span nesting + cross-thread propagation (exclusive-time accounting)
- disabled-mode overhead (the no-op fast path)
- jit cache-miss counter correctness under re-used bucket shapes
- /v1/metrics Prometheus text + /v1/operator/traces against the real
  HTTP API, including the ACL gate
- the e2e traced burst emitting a TRACE_DECOMP stage decomposition
  that attributes >= 90% of per-eval wall time to named spans
"""

import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from nomad_tpu import telemetry
from nomad_tpu.telemetry.exporter import prometheus_text, traces_json
from nomad_tpu.telemetry.kernel_profile import profiler
from nomad_tpu.telemetry.trace import Tracer, tracer

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "bench"))


@pytest.fixture()
def clean_telemetry():
    """Enable + reset around a test; restore disabled state after."""
    telemetry.enable()
    telemetry.reset()
    yield
    telemetry.disable()
    telemetry.reset()


class TestSpans:
    def test_nesting_and_exclusive_time(self):
        t = Tracer()
        t.enable()
        with t.span("outer", trace_id="t1"):
            time.sleep(0.01)
            with t.span("inner"):
                time.sleep(0.02)
        spans = {s.name: s for s in t.spans()}
        assert spans["inner"].parent_id == spans["outer"].span_id
        assert spans["inner"].trace_id == "t1"
        assert spans["outer"].dur_s >= 0.028
        # outer's exclusive excludes inner's whole duration
        assert spans["outer"].exclusive_s <= spans["outer"].dur_s - 0.015
        agg = t.stage_totals()
        assert agg["outer"]["count"] == 1
        assert agg["outer"]["exclusive_s"] < agg["outer"]["total_s"]

    def test_cross_thread_propagation(self):
        t = Tracer()
        t.enable()
        got = {}

        with t.span("root", trace_id="trace-x") as root:
            ctx = t.context()

            def worker():
                with t.attach(ctx):
                    with t.span("child"):
                        pass
                # attach scope ends: a new root span is unparented
                with t.span("orphan"):
                    pass
                got["done"] = True

            th = threading.Thread(target=worker)
            th.start()
            th.join()

        assert got["done"]
        child = t.spans(name="child")[0]
        assert child.trace_id == "trace-x"
        assert child.parent_id == root.span_id
        orphan = t.spans(name="orphan")[0]
        assert orphan.parent_id == 0

    def test_exception_unwinds_stack(self):
        t = Tracer()
        t.enable()
        with pytest.raises(RuntimeError):
            with t.span("a"):
                with t.span("b"):
                    raise RuntimeError("boom")
        # stack fully unwound: a new span is a root again
        with t.span("c"):
            pass
        assert t.spans(name="c")[0].parent_id == 0

    def test_ring_is_bounded_but_aggregates_are_not(self):
        t = Tracer(capacity=8)
        t.enable()
        for _ in range(50):
            with t.span("x"):
                pass
        assert len(t.spans()) == 8
        assert t.stage_totals()["x"]["count"] == 50

    def test_disabled_mode_is_cheap(self):
        """The disabled path must be a near-no-op: no allocation, no
        clock. Bound it RELATIVE to the enabled path (absolute
        thresholds flake on loaded CI)."""
        t = Tracer()
        n = 20_000

        t.enable()
        t0 = time.perf_counter()
        for _ in range(n):
            with t.span("s"):
                pass
        enabled_s = time.perf_counter() - t0

        t.disable()
        t0 = time.perf_counter()
        for _ in range(n):
            with t.span("s"):
                pass
        disabled_s = time.perf_counter() - t0

        assert disabled_s < enabled_s / 3
        # and nothing was recorded
        assert t.stage_totals()["s"]["count"] == n

    def test_record_after_the_fact_parents_under_open_span(self):
        t = Tracer()
        t.enable()
        with t.span("parent") as p:
            t.record("leaf", 0.005)
        leaf = t.spans(name="leaf")[0]
        assert leaf.parent_id == p.span_id
        parent = t.spans(name="parent")[0]
        assert parent.child_s >= 0.005


class TestKernelProfiler:
    def test_cache_miss_counting_under_reused_bucket_shapes(
            self, clean_telemetry):
        """Two launches with the SAME bucket key: one compile, one
        cache hit. A third with a new key: another miss. Uses a real
        jit function so the cache-growth cross-check exercises."""
        import jax
        import jax.numpy as jnp

        fn = jax.jit(lambda x, k: x * k, static_argnums=(1,))
        key_a = ("bucket", 64)
        out1 = profiler.call("toy", fn, (jnp.ones(64),), (2,), key_a,
                             jit_fn=fn)
        out2 = profiler.call("toy", fn, (jnp.ones(64),), (2,), key_a,
                             jit_fn=fn)
        assert float(out1[0]) == 2.0 and float(out2[0]) == 2.0
        assert profiler.misses_for("toy") == 1

        key_b = ("bucket", 128)
        profiler.call("toy", fn, (jnp.ones(128),), (2,), key_b, jit_fn=fn)
        assert profiler.misses_for("toy") == 2

        s = profiler.summary()
        assert s["Launches"] == 3
        assert s["JitCacheMisses"] == 2
        # cross-check agrees with the seen-set on a well-bucketed kernel
        assert s["JitCacheGrowth"] == 2
        assert s["StageSeconds"]["execute"] >= 0.0
        assert s["StageSeconds"]["h2d"] > 0.0

    def test_live_wave_records_kernel_stages(self, clean_telemetry):
        """A real coalesced wave populates the kernel spans and the
        per-key accounting (two same-shape waves -> one compile)."""
        from nomad_tpu import mock
        from nomad_tpu.server.server import Server, ServerConfig

        server = Server(ServerConfig(num_workers=1, worker_batch_size=4,
                                     heartbeat_ttl=3600.0))
        server.start()
        try:
            for _ in range(20):
                server.node_register(mock.node())
            jobs = []
            for _ in range(8):
                job = mock.simple_job()
                job.task_groups[0].count = 2
                jobs.append(job)
                server.job_register(job)
            deadline = time.time() + 60
            while time.time() < deadline:
                snap = server.state.snapshot()
                if sum(len(snap.allocs_by_job(j.namespace, j.id))
                       for j in jobs) >= 16:
                    break
                time.sleep(0.05)
            stages = tracer.stage_totals()
            for name in ("broker.dequeue", "worker.snapshot",
                         "eval.schedule", "wave.assemble", "kernel.h2d",
                         "kernel.execute", "kernel.d2h", "plan.evaluate",
                         "plan.group_commit", "plan.commit", "fsm.apply"):
                assert name in stages, f"missing span {name}"
            prof = profiler.summary()
            assert prof["Launches"] >= 1
            # repeated same-bucket waves must not recompile
            assert prof["JitCacheMisses"] <= len(prof["PerKey"])
        finally:
            server.shutdown()


class TestExposition:
    def test_prometheus_text_includes_telemetry_series(
            self, clean_telemetry):
        with tracer.span("unit.test.span"):
            pass
        text = prometheus_text()
        assert "# TYPE nomad_tpu_trace_span_seconds_total counter" in text
        assert 'nomad_tpu_trace_span_seconds_total{span="unit.test.span"}' \
            in text
        assert "nomad_tpu_telemetry_enabled 1" in text
        # transfer byte counters + device-residency series (ISSUE 3)
        assert 'nomad_tpu_kernel_transfer_bytes_total{direction="h2d"}' \
            in text
        assert 'nomad_tpu_kernel_transfer_bytes_total{direction="d2h"}' \
            in text
        assert "nomad_tpu_device_state_dirty_row_upload_ratio" in text
        # plan group-commit series (ISSUE 6)
        assert 'nomad_tpu_plan_group_plans_total{kind="vector"}' in text
        assert 'nomad_tpu_plan_group_plans_total{kind="fallback"}' in text
        assert "nomad_tpu_plan_group_commits_total" in text
        assert "nomad_tpu_plan_group_rejects_total" in text
        assert "nomad_tpu_plan_group_bytes_total" in text

    def test_prometheus_latency_histograms(self, clean_telemetry):
        """ISSUE 8: streaming latency histograms export as the real
        Prometheus histogram type — cumulative _bucket/_sum/_count."""
        from nomad_tpu.telemetry.histogram import histograms

        for v in (0.002, 0.004, 0.050):
            histograms.get("e2e").record(v)
        histograms.get("wave_park").record(0.001)
        text = prometheus_text()
        assert "# TYPE nomad_tpu_latency_seconds histogram" in text
        assert 'nomad_tpu_latency_seconds_bucket{op="e2e",le="' in text
        assert 'nomad_tpu_latency_seconds_bucket{op="e2e",le="+Inf"} 3' \
            in text
        assert 'nomad_tpu_latency_seconds_count{op="e2e"} 3' in text
        assert 'nomad_tpu_latency_seconds_sum{op="e2e"} 0.056' in text
        assert 'nomad_tpu_latency_seconds_count{op="wave_park"} 1' \
            in text
        # flight-recorder health series ride along
        assert "nomad_tpu_slow_evals_captured_total" in text
        assert "nomad_tpu_slow_eval_threshold_seconds" in text
        # cumulative bucket counts are non-decreasing per op
        cums = [int(line.rsplit(" ", 1)[1]) for line in text.splitlines()
                if line.startswith(
                    'nomad_tpu_latency_seconds_bucket{op="e2e"')]
        assert cums == sorted(cums)

    def test_traces_json_shape(self, clean_telemetry):
        with tracer.span("a", trace_id="t"):
            pass
        body = traces_json()
        assert body["Enabled"] is True
        assert body["Stages"]["a"]["Count"] == 1
        assert body["Spans"][-1]["Name"] == "a"
        assert "Kernel" in body


def _get(addr: str, path: str, token: str = ""):
    req = urllib.request.Request(addr + path)
    if token:
        req.add_header("X-Nomad-Token", token)
    with urllib.request.urlopen(req, timeout=10) as resp:
        return resp.status, resp.headers, resp.read()


class TestHTTPEndpoints:
    @pytest.fixture()
    def agent(self):
        from nomad_tpu.api.agent import Agent, AgentConfig

        a = Agent(AgentConfig(serf_enabled=False))
        a.start()
        try:
            yield a
        finally:
            a.shutdown()

    def test_metrics_prometheus_is_raw_text(self, agent, clean_telemetry):
        with tracer.span("http.test"):
            pass
        status, headers, body = _get(
            agent.http.addr, "/v1/metrics?format=prometheus")
        assert status == 200
        assert headers["Content-Type"].startswith("text/plain")
        text = body.decode()
        # raw exposition, not a JSON-quoted string
        assert text.startswith("#") or text.startswith("nomad")
        assert "nomad_tpu_telemetry_enabled" in text
        assert 'span="http.test"' in text

    def test_metrics_default_is_json_summary(self, agent):
        status, headers, body = _get(agent.http.addr, "/v1/metrics")
        assert status == 200
        data = json.loads(body)
        assert "Counters" in data and "Samples" in data

    def test_operator_traces_roundtrip(self, agent, clean_telemetry):
        with tracer.span("op.span", trace_id="t9"):
            pass
        status, _, body = _get(agent.http.addr, "/v1/operator/traces")
        assert status == 200
        data = json.loads(body)
        assert data["Enabled"] is True
        assert any(s["Name"] == "op.span" for s in data["Spans"])

    def test_operator_traces_trace_id_filter(self, agent,
                                             clean_telemetry):
        """?trace_id= narrows the span dump to one eval's tree
        (Tracer.spans already filters; this is the HTTP plumbing)."""
        with tracer.span("filter.a", trace_id="trace-a"):
            pass
        with tracer.span("filter.b", trace_id="trace-b"):
            pass
        status, _, body = _get(
            agent.http.addr, "/v1/operator/traces?trace_id=trace-a")
        assert status == 200
        data = json.loads(body)
        assert data["TraceID"] == "trace-a"
        assert data["Spans"]
        assert all(s["TraceID"] == "trace-a" for s in data["Spans"])
        assert not any(s["Name"] == "filter.b" for s in data["Spans"])

    def test_operator_slow_evals_roundtrip(self, agent,
                                           clean_telemetry):
        """GET /v1/operator/slow-evals serves the flight recorder's
        captured trees + threshold + histogram summaries."""
        from nomad_tpu.telemetry.histogram import histograms
        from nomad_tpu.telemetry.trace import flight_recorder

        e2e = histograms.get("e2e")
        for i in range(flight_recorder.MIN_SAMPLES):
            e2e.record(0.01)
            flight_recorder.observe(f"fast-{i}", 0.01)
        with tracer.span("eval.schedule", trace_id="slow-1"):
            pass
        e2e.record(5.0)
        assert flight_recorder.observe("slow-1", 5.0)
        status, _, body = _get(agent.http.addr,
                               "/v1/operator/slow-evals")
        assert status == 200
        data = json.loads(body)
        assert data["Captured"] >= 1
        assert data["ThresholdMs"] > 0
        assert data["Trees"]
        tree = data["Trees"][-1]
        assert tree["TraceID"] == "slow-1"
        assert any(s["Name"] == "eval.schedule"
                   for s in tree["Spans"])
        assert data["Histogram"]["e2e"]["count"] == \
            flight_recorder.MIN_SAMPLES + 1


class TestServingPlane:
    """ISSUE 11: the serving-plane observability surface — the
    stream-health endpoint, the nomad_tpu_stream_*/watch/heartbeat/
    wave-cohort Prometheus series, and the fleet_* bench-key contract."""

    @pytest.fixture()
    def agent(self):
        from nomad_tpu.api.agent import Agent, AgentConfig

        a = Agent(AgentConfig(serf_enabled=False))
        a.start()
        try:
            yield a
        finally:
            a.shutdown()

    def test_stream_health_endpoint(self, agent, clean_telemetry):
        from nomad_tpu import mock

        server = agent.server
        sub = server.event_broker.subscribe({"*": ["*"]})
        server.job_register(mock.job())
        evs = sub.next_events(timeout=5.0)
        assert evs
        status, _, body = _get(agent.http.addr,
                               "/v1/operator/stream-health")
        assert status == 200
        data = json.loads(body)
        assert data["Stream"]["published_events"] >= 1
        assert data["Stream"]["delivered_events"] >= 1
        assert data["Stream"]["subscribers"] >= 1
        assert "held_watchers" in data["Watch"]
        assert "wakeups" in data["Watch"]
        assert "heartbeats" in data["Heartbeat"]
        assert "batches" in data["Heartbeat"]
        # the delivery-lag histogram recorded the hand-off above
        assert data["DeliverLatency"].get("count", 0) >= 1
        sub.close()

    def test_serving_prometheus_series(self, agent, clean_telemetry):
        """The serving-plane series ride the standard scrape: stream
        ring gauges (per-server, passed by the HTTP layer), watch
        wakeups, heartbeat fan-in, and the ISSUE 11 satellite's
        wave-cohort gauges."""
        from nomad_tpu import mock

        server = agent.server
        sub = server.event_broker.subscribe({"*": ["*"]})
        node = mock.node()
        server.node_register(node)
        server.node_heartbeat(node.id, "ready")
        # a held-then-woken blocking query feeds the watch counters
        idx = server.state.table_index(["jobs"])
        waiter = threading.Thread(
            target=lambda: server.state.block_until(["jobs"], idx, 5.0),
            daemon=True)
        waiter.start()
        time.sleep(0.1)
        server.job_register(mock.job())
        waiter.join(timeout=5.0)
        sub.next_events(timeout=5.0)
        status, _, body = _get(
            agent.http.addr, "/v1/metrics?format=prometheus")
        assert status == 200
        text = body.decode()
        for series in (
            "nomad_tpu_stream_subscribers",
            'nomad_tpu_stream_events_total{kind="published"}',
            'nomad_tpu_stream_events_total{kind="delivered"}',
            'nomad_tpu_stream_events_total{kind="lost"}',
            "nomad_tpu_stream_max_lag_events",
            "nomad_tpu_stream_retained_events",
            "nomad_tpu_stream_delivered_bytes_total",
            "nomad_tpu_watch_held_watchers",
            'nomad_tpu_watch_wakeups_total{kind="real"}',
            'nomad_tpu_watch_wakeups_total{kind="spurious"}',
            "nomad_tpu_heartbeats_total",
            'nomad_tpu_client_update_fanin_total{kind="batches"}',
            "nomad_tpu_wave_cohort_waves_total",
            "nomad_tpu_wave_cohort_plans_total",
            'nomad_tpu_wave_cohort_outcomes_total{kind="drained"}',
            'nomad_tpu_wave_cohort_outcomes_total{kind="hard_cap"}',
            "nomad_tpu_wave_cohort_drain_ewma_seconds",
            'nomad_tpu_latency_seconds_bucket{op="stream_deliver"',
        ):
            assert series in text, series
        # the watch thread above must have produced a real wakeup
        import re as _re

        m = _re.search(
            r'nomad_tpu_watch_wakeups_total\{kind="real"\} (\d+)', text)
        assert m and int(m.group(1)) >= 1, m
        sub.close()

    def test_fleet_bench_keys_emitted(self):
        """The fleet cell's trend lines are contract: bench.py must
        emit the fleet_* keys the serving-plane work gates on (the
        graftcheck R5 rule holds them against TELEMETRY.md both
        directions; this pins the REQUIRED core set)."""
        import ast

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with open(os.path.join(repo, "bench.py")) as f:
            tree = ast.parse(f.read())
        emitted = {
            kw.arg
            for node in ast.walk(tree) if isinstance(node, ast.Call)
            for kw in node.keywords
            if kw.arg and kw.arg.startswith("fleet_")
        }
        assert {
            "fleet_clients",
            "fleet_heartbeats_per_sec",
            "fleet_watch_wakeups_per_sec",
            "fleet_stream_deliver_p99_ms",
            "fleet_e2e_p99_ms",
            "fleet_e2e_p99_held",
        } <= emitted, emitted

    def test_client_update_fan_in_coalesces_concurrent_callers(self):
        """Heartbeat fan-in batching: concurrent Node.UpdateAlloc
        callers must merge into fewer ALLOC_CLIENT_UPDATE raft entries
        (one per drain) with every caller seeing a committed index."""
        from nomad_tpu import mock
        from nomad_tpu.server.server import (
            Server,
            ServerConfig,
            client_update_stats,
        )

        server = Server(ServerConfig(num_workers=0,
                                     heartbeat_ttl=3600.0,
                                     client_update_fill_window_ms=5.0))
        server.start()
        try:
            node = mock.node()
            server.node_register(node)
            allocs = []
            for _ in range(16):
                a = mock.alloc(node_id=node.id)
                server.state.upsert_allocs([a])
                allocs.append(a)
            client_update_stats.reset_stats()
            applies0 = server.state.latest_index()
            results = [None] * len(allocs)

            def report(k):
                results[k] = server.update_allocs_from_client(
                    [allocs[k]])

            threads = [threading.Thread(target=report, args=(k,))
                       for k in range(len(allocs))]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            snap = client_update_stats.snapshot()
            assert snap["callers"] == len(allocs)
            assert snap["allocs"] == len(allocs)
            # coalescing happened: strictly fewer raft entries than
            # callers (16 concurrent updates against a >=5ms window
            # cannot all land in distinct batches)
            assert snap["batches"] < len(allocs), snap
            assert all(isinstance(r, int) and r > applies0
                       for r in results)
            # every alloc's update actually committed
            state_snap = server.state.snapshot()
            assert all(state_snap.alloc_by_id(a.id) is not None
                       for a in allocs)
        finally:
            server.shutdown()


class TestTracesACL:
    """/v1/operator/traces is gated like the event stream: a token
    without operator:read is rejected outright."""

    @pytest.fixture()
    def acl_agent(self):
        from nomad_tpu.acl.policy import ACLPolicy, ACLToken
        from nomad_tpu.api.agent import Agent, AgentConfig
        from nomad_tpu.server import fsm as fsm_msgs

        cfg = AgentConfig(acl_enabled=True, serf_enabled=False)
        agent = Agent(cfg)
        agent.start()
        server = agent.server
        # bootstrap a management token + a no-capability token
        mgmt = ACLToken.create(name="mgmt", type="management")
        server.raft_apply(fsm_msgs.ACL_TOKEN_UPSERT, {"tokens": [mgmt]})
        policy = ACLPolicy(name="job-read",
                           rules='namespace "default" { policy = "read" }')
        server.raft_apply(fsm_msgs.ACL_POLICY_UPSERT,
                          {"policies": [policy]})
        weak = ACLToken.create(name="weak", type="client",
                               policies=["job-read"])
        server.raft_apply(fsm_msgs.ACL_TOKEN_UPSERT, {"tokens": [weak]})
        try:
            yield agent, mgmt.secret_id, weak.secret_id
        finally:
            agent.shutdown()

    def test_anonymous_and_weak_tokens_rejected(self, acl_agent):
        agent, _mgmt, weak = acl_agent
        for token in ("", weak):
            for path in ("/v1/operator/traces",
                         "/v1/operator/slow-evals"):
                with pytest.raises(urllib.error.HTTPError) as ei:
                    _get(agent.http.addr, path, token=token)
                assert ei.value.code == 403

    def test_management_token_reads_slow_evals(self, acl_agent):
        agent, mgmt, _weak = acl_agent
        status, _, body = _get(agent.http.addr,
                               "/v1/operator/slow-evals", token=mgmt)
        assert status == 200
        data = json.loads(body)
        assert "Trees" in data and "ThresholdMs" in data

    def test_management_token_allowed_and_can_toggle(self, acl_agent):
        agent, mgmt, weak = acl_agent
        status, _, body = _get(agent.http.addr, "/v1/operator/traces",
                               token=mgmt)
        assert status == 200
        # toggle endpoint: management can enable, weak cannot
        req = urllib.request.Request(
            agent.http.addr + "/v1/operator/traces",
            data=json.dumps({"Enable": True}).encode(), method="PUT")
        req.add_header("X-Nomad-Token", mgmt)
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert json.loads(resp.read())["Enabled"] is True
        try:
            req = urllib.request.Request(
                agent.http.addr + "/v1/operator/traces",
                data=json.dumps({"Enable": False}).encode(), method="PUT")
            req.add_header("X-Nomad-Token", weak)
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=10)
            assert ei.value.code == 403
        finally:
            telemetry.disable()
            telemetry.reset()


class TestTraceDecomposition:
    def test_traced_burst_attributes_90_percent(self, tmp_path):
        """The acceptance criterion: the live e2e bench path with
        tracing on emits TRACE_DECOMP.json attributing >= 90% of
        per-eval wall time to named spans (CPU backend).

        Runs bench/trace_report.py in a SUBPROCESS — the bench's own
        shape. In-suite, ~550 earlier tests leave daemon threads
        whose GIL slices stretch the burst wall without touching the
        system's attributed CPU; a clean process measures the system,
        not the suite's thread leakage. One retry for CI-neighbor
        contention.
        """
        import subprocess

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        out = tmp_path / "TRACE_DECOMP.json"
        decomp = None
        def _plan_group_ok(d):
            size = d["steady_state"].get("plan_group_size", 0.0)
            wave = d.get("wave", {})
            wave_avg = wave.get("requests", 0) / max(
                wave.get("launches", 1), 1)
            return size >= 0.8 * 32 or size >= 0.85 * wave_avg

        def raw_share(d):
            # instrumentation COVERAGE is a raw-sum question: the
            # deduped attributed_share (≤ 1.0 by construction) folds
            # pipelining overlap out, so a fully-instrumented fast
            # burst can dedupe slightly below 0.9 while every wall
            # second is in fact covered
            return d.get("attributed_raw_s", d["attributed_s"]) \
                / max(d["wall_s"], 1e-9)

        for _attempt in range(2):
            # 300 jobs x 3 allocs (not 100 x 5): the share gates divide
            # NAMED work by burst wall/CPU, and on a fast box a
            # 100-eval burst is over in ~0.15s — fixed per-burst
            # overheads (thread wakeups, GC, monitor) then eat >10% of
            # the denominator and the gate measures the box, not the
            # instrumentation. Tripling the eval count at comparable
            # total allocs (900, still inside the 300-node capacity —
            # 5 allocs/job at 300 jobs saturates it and blocks evals)
            # amortizes those fixed costs to noise level.
            proc = subprocess.run(
                [sys.executable, os.path.join(repo, "bench",
                                              "trace_report.py"),
                 str(out), "--nodes", "300", "--jobs", "300",
                 "--allocs-per-job", "3", "--batch", "32",
                 "--warmup-jobs", "16", "--bursts", "2"],
                capture_output=True, timeout=360,
                env={**os.environ, "JAX_PLATFORMS": "cpu"},
            )
            assert proc.returncode == 0, proc.stderr.decode()[-2000:]
            decomp = json.loads(out.read_text())
            ss = decomp["steady_state"]
            sched_ok = (ss["sched_host_share"] <= 0.65 or sum(
                decomp["stages"].get(s, {}).get("per_eval_ms", 0.0)
                for s in ("sched-host", "sched-reconcile",
                          "sched-feasibility", "sched-assembly",
                          "sched-planbuild")) <= 3.0)
            tail = decomp.get("tail", {})
            tail_ok = (
                tail.get("histogram", {}).get("count")
                == tail.get("committed_evals")
                and tail.get("p50_coverage", 0.0) >= 0.90)
            if raw_share(decomp) >= 0.9 \
                    and ss["jit_cache_misses"] == 0 \
                    and decomp["allocs_placed"] == decomp["allocs_wanted"] \
                    and sched_ok \
                    and tail_ok \
                    and _plan_group_ok(decomp) \
                    and (ss["h2d_share"] <= 0.10 or ss["h2d_bytes"]
                         <= 50_000 * decomp["n_evals"]):
                break
        assert decomp["allocs_placed"] == decomp["allocs_wanted"]
        # raw wall coverage on a quiet host; the steal-invariant busy
        # share (attributed / process CPU actually received) is the
        # fallback when CI neighbors or the parent suite's leaked
        # threads stretch wall with time this process never had
        assert raw_share(decomp) >= 0.9 \
            or decomp["attributed_share_busy"] >= 0.9, decomp
        for stage in ("dequeue", "snapshot", "sched-host",
                      "wave-assembly", "h2d", "execute", "d2h",
                      "plan-apply", "fsm"):
            assert stage in decomp["stages"], stage
        assert "plan-submit" in decomp["overlapped"]
        assert decomp["kernel"]["Launches"] >= 1
        # the 2-burst history separates the compile transient from the
        # steady state the artifact reports
        assert len(decomp["all_bursts"]) == 2
        # ISSUE 2 steady-state gates: with AOT warmup in front, the
        # second burst is compile-free (the regression artifact for
        # compile share) and the dedupe keeps shares within wall
        assert decomp["steady_state"]["jit_cache_misses"] == 0, \
            decomp["kernel"]["PerKey"]
        assert decomp["steady_state"]["compile_share"] < 0.10
        # ISSUE 3 steady gate: with the device-resident cluster state
        # in front of the wave launcher, per-wave h2d is dirty rows +
        # genuinely per-eval planes — its share of steady wall must
        # stay under 10% (was 30.4% when every wave re-uploaded the
        # full shared planes). The share is wall-clocked, so a
        # contended host (GIL theft stretching the firing thread's
        # spans) can inflate it with time the transfer never used; the
        # steal-invariant fallback is the BYTE meter — re-uploading
        # full planes per wave costs >100KB/eval, residency ~10-40KB —
        # which is a property of the system, not of the CI neighbors.
        ss = decomp["steady_state"]
        assert ss["h2d_share"] <= 0.10 \
            or ss["h2d_bytes"] <= 50_000 * decomp["n_evals"], ss
        # and the transfer byte meters actually metered
        assert ss["h2d_bytes"] > 0
        assert ss["d2h_bytes"] > 0
        assert decomp["attributed_share"] <= 1.0
        # wave-shape telemetry rides the artifact
        assert decomp["wave"]["launches"] >= 1
        assert 0.0 < decomp["wave"]["fill_ratio"] <= 1.0
        # device-residency accounting rides it too: the steady burst
        # must be advancing by dirty-row scatter, not full re-uploads
        assert decomp["device_state"]["delta_advances"] >= 1, \
            decomp["device_state"]
        # ISSUE 19 steady gates: every steady wave must run the fused
        # mega-kernel — zero fused fallbacks, fused launches == wave
        # launches — and cost exactly ONE wave-critical device
        # dispatch (the composite's separate eager result fetch is
        # gone; the deferred top-k drain is excluded by definition).
        assert ss["fused_wave_fallbacks"] == 0, (
            ss, decomp.get("wave_fused"))
        assert ss["fused_wave_launches"] == \
            decomp["wave"]["launches"] > 0, (ss, decomp["wave"])
        assert ss["dispatches_per_wave"] == 1.0, (
            ss, decomp["kernel"].get("Dispatches"))
        # the per-program dispatch counter exported in the artifact:
        # fused waves only, no composite program, no eager wave fetch
        disp = decomp["kernel"].get("Dispatches", {})
        assert disp.get("fused_wave", 0) > 0, disp
        assert disp.get("joint", 0) == 0, disp
        assert disp.get("wave_fetch", 0) == 0, disp
        # ISSUE 5 steady gates. sched_host_share sums the
        # eval.schedule residue + the feasibility/assembly/plan-build
        # sub-slices. Post-compiler, the feasibility slice itself is
        # a cache lookup (hit ratio gated below); what remains is the
        # GIL-bound floor of the Go-parity scheduler Python (~2.4
        # ms/eval: reconcile, option/assign, plan build) — on the CPU
        # backend, where wall per eval IS that Python, the share
        # bottoms out near 0.30 at 150+ evals/s (it was 0.52 before
        # the compiler + the tracer's clock-syscall bias fix; docs/
        # PERF.md "The feasibility compiler"). The share's numerator
        # is thread CPU, so host contention stretches the wall
        # denominator and can only shrink it — the steal-invariant
        # fallback bound is the per-eval CPU milliseconds of the same
        # four slices. ISSUE 19 recalibrated the share bound from
        # 0.45: the fused wave cut the execute+fetch leg to one
        # dispatch, shrinking the wall denominator while the Python
        # numerator stayed put — the same healthy scheduler now reads
        # ~0.55-0.60 of the smaller wall (a genuine host regression on
        # fused walls would read 0.7+).
        sched_ms = sum(
            decomp["stages"].get(s, {}).get("per_eval_ms", 0.0)
            for s in ("sched-host", "sched-reconcile",
                      "sched-feasibility", "sched-assembly",
                      "sched-planbuild"))
        assert ss["sched_host_share"] <= 0.65 or sched_ms <= 3.0, \
            (ss["sched_host_share"], sched_ms)
        # ISSUE 10: the reconcile slice is spanned on its own (the
        # fused single-pass classifier's trajectory line)
        assert "sched-reconcile" in decomp["stages"]
        assert "reconcile_share" in ss
        # steady traffic re-uses compiled masks: misses only on node
        # structure forks and novel job specs, never per eval
        assert ss["feasibility_hit_ratio"] >= 0.95, \
            decomp.get("feasibility")
        # ISSUE 6 steady gates: the group-commit pass must prove EVERY
        # plan of the lean burst from the utilization planes — a
        # fallback means the vectorized check silently lost coverage
        # (the exact walk is bit-identical, so only this counter ever
        # reveals the regression) — and the plan-path share is
        # surfaced so the next re-anchor has a trajectory line
        assert ss["plan_group_fallbacks"] == 0, decomp.get("plan_group")
        assert decomp.get("plan_group", {}).get("plans", 0) > 0, \
            decomp.get("plan_group")
        assert "plan_share" in ss
        # batched raft entries actually batch when plans queue up; a
        # serialized applier would pin this at exactly 1.0 (tolerate
        # a trickle-paced burst, but the counter must exist and move)
        assert decomp.get("plan_group", {}).get("commit_batches", 0) > 0
        # ISSUE 10 wave-boundary gate: with the plan queue's drain
        # window armed per wave cohort, a wave's plans commit as ~ONE
        # raft entry — plans per entry must reach 0.8x the worker
        # batch size (the burst runs --batch 32; was ~5.6 before).
        # Steal-tolerant fallback: under CI-neighbor/parent-suite
        # contention the INGEST fragments waves themselves; the
        # mechanism's property is then "the applier commits whole
        # waves", i.e. plans-per-entry tracks the average wave size.
        assert _plan_group_ok(decomp), \
            (decomp.get("plan_group"), decomp.get("wave"))
        # ISSUE 8 tail gates: the tail section exists; every committed
        # eval of the burst landed in the e2e histogram (count
        # equality — no eval escapes the distribution); and the named
        # waterfall segments explain >= 90% of the median cohort's
        # e2e latency (dequeue-wait/snapshot/schedule/park/launch/
        # plan-queue/evaluate/commit/fsm — "other" never counts
        # toward coverage)
        tail = decomp["tail"]
        assert tail["committed_evals"] > 0
        assert tail["histogram"]["count"] == tail["committed_evals"], \
            (tail["histogram"], tail["committed_evals"])
        assert not tail["ring_wrapped"]
        # every committed eval also produced a waterfall (the e2e
        # marker span anchors it)
        assert tail["e2e_count"] == tail["committed_evals"]
        assert tail["p50_coverage"] >= 0.90, tail
        assert tail["segments"], tail
        # the p50-vs-p99 table carries both cohorts for each segment
        for seg, row in tail["segments"].items():
            assert {"p50_ms", "p50_share", "p99_ms", "p99_share"} \
                <= set(row), (seg, row)
        # the distribution rides into steady_state for bench emission
        assert ss["e2e_p99_ms"] >= ss["e2e_p50_ms"] > 0.0
        # the flight recorder observed the burst (captures depend on
        # the distribution's shape; observation must not)
        assert tail["flight_recorder"]["observed"] == \
            tail["committed_evals"]
        # ISSUE 11: the serving section rides the artifact — even a
        # burst with no external subscribers publishes every FSM apply
        # into the event ring, so the publish/watch/heartbeat counters
        # must exist and the ring must have seen the burst's applies
        serving = decomp["serving"]
        assert serving["stream"]["published_events"] > 0, serving
        assert serving["stream"]["lost_events"] == 0
        for section, keys in (
            ("stream", ("subscribers", "published_events",
                        "delivered_events", "lost_events",
                        "max_lag_events", "delivered_bytes")),
            ("watch", ("held_watchers", "wakeups", "spurious_wakeups",
                       "timeouts")),
            ("heartbeat", ("heartbeats", "callers", "batches",
                           "coalesce_ratio")),
        ):
            assert set(keys) <= set(serving[section]), (
                section, serving[section])
        assert "deliver_latency" in serving

    def test_mesh_steady_burst_gates_sharded_keys(self, tmp_path):
        """ISSUE 14 steady gates: with the device mesh on (the
        conftest 8-virtual-CPU mesh via use_device_mesh=True), the
        steady burst's TRACE_DECOMP steady_state must report every
        wave dispatched SHARDED (launches > 0), ZERO single-device
        fallbacks, and — like the unsharded burst — zero jit cache
        misses on the second (steady) burst: the AOT warmup learned
        the sharded signatures. Subprocess for the same reason as the
        main decomposition test (a clean process measures the system);
        smaller shape — the perf share gates stay with the unsharded
        artifact, this one gates the sharding plumbing."""
        import subprocess

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        out = tmp_path / "TRACE_DECOMP_MESH.json"
        proc = subprocess.run(
            [sys.executable, os.path.join(repo, "bench",
                                          "trace_report.py"),
             str(out), "--nodes", "200", "--jobs", "96",
             "--allocs-per-job", "3", "--batch", "16",
             "--warmup-jobs", "10", "--bursts", "2", "--mesh"],
            capture_output=True, timeout=360,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, proc.stderr.decode()[-2000:]
        decomp = json.loads(out.read_text())
        assert decomp["allocs_placed"] == decomp["allocs_wanted"]
        ss = decomp["steady_state"]
        # the new steady keys exist and hold: sharded is THE path on a
        # mesh server (fallbacks would mean single-device dispatches
        # leaked into the steady state)
        assert ss["mesh_devices"] == 8, ss
        assert ss["sharded_wave_launches"] > 0, ss
        assert ss["sharded_wave_launches"] == \
            decomp["wave"]["launches"], (ss, decomp["wave"])
        assert ss["sharded_wave_fallbacks"] == 0, ss
        # steady-state compile discipline holds under sharding too
        assert ss["jit_cache_misses"] == 0, \
            decomp["kernel"]["PerKey"]
        # group-commit health is dispatch-independent
        assert ss["plan_group_fallbacks"] == 0, decomp.get("plan_group")
        # the resident state advanced sharded between waves
        assert decomp["device_state"]["delta_advances"] >= 1, \
            decomp["device_state"]
        # ISSUE 19: sharded waves run FUSED too (fused_wave_sharded),
        # still at one dispatch per wave
        assert ss["fused_wave_fallbacks"] == 0, ss
        assert ss["fused_wave_launches"] == \
            decomp["wave"]["launches"], (ss, decomp["wave"])
        assert ss["dispatches_per_wave"] == 1.0, (
            ss, decomp["kernel"].get("Dispatches"))

    def test_disabled_tracing_leaves_no_spans(self):
        """The disabled live path must record nothing (the <5%
        overhead claim rests on the no-op fast path actually being
        taken everywhere)."""
        telemetry.disable()
        telemetry.reset()
        from nomad_tpu import mock
        from nomad_tpu.server.server import Server, ServerConfig

        server = Server(ServerConfig(num_workers=1, worker_batch_size=4,
                                     heartbeat_ttl=3600.0))
        server.start()
        try:
            for _ in range(10):
                server.node_register(mock.node())
            job = mock.simple_job()
            job.task_groups[0].count = 4
            server.job_register(job)
            deadline = time.time() + 60
            while time.time() < deadline:
                snap = server.state.snapshot()
                if len(snap.allocs_by_job(job.namespace, job.id)) >= 4:
                    break
                time.sleep(0.05)
            assert tracer.stage_totals() == {}
            assert profiler.summary()["Launches"] == 0
        finally:
            server.shutdown()


class TestMVCCStoreTelemetry:
    """ISSUE 16: the MVCC store's telemetry surface — the store_*
    Prometheus series, and the lock-free-reads proof: under the lock
    witness, a read storm records ZERO store-lock hold samples while
    write transactions record on lock_hold_store_write_txn."""

    def test_store_series_exported(self, clean_telemetry):
        from nomad_tpu import mock
        from nomad_tpu.state.store import StateStore

        store = StateStore()
        store.upsert_node(mock.node())
        store.snapshot()
        text = prometheus_text()
        assert "# TYPE nomad_tpu_store_write_txns_total counter" in text
        assert "nomad_tpu_store_snapshots_total" in text
        assert "nomad_tpu_store_restores_total" in text
        assert "nomad_tpu_store_generation" in text
        assert "nomad_tpu_store_live_roots" in text

    def test_read_path_holds_no_store_lock(self):
        from nomad_tpu import mock
        from nomad_tpu.state.store import StateStore
        from nomad_tpu.telemetry.histogram import histograms
        from nomad_tpu.utils import witness

        witness.reset()
        witness.enable()
        try:
            # the witness wraps locks created AFTER enable(): this
            # store's write/watch locks feed lock_hold_* histograms
            store = StateStore()
            nodes = [mock.node() for _ in range(20)]
            for n in nodes:
                store.upsert_node(n)

            def holds(name):
                h = histograms.peek(f"lock_hold_{name}")
                return h.count if h is not None else 0

            write_holds = holds("store_write_txn")
            assert write_holds >= 20  # every txn records its hold

            # the read storm: snapshots, row reads, direct readers,
            # scoped views — none may touch a store lock
            before_txn = holds("store_write_txn")
            before_watch = holds("store_watch")
            for _ in range(200):
                snap = store.snapshot()
                snap.node_by_id(nodes[0].id)
                snap.nodes()
                store.node_by_id_direct(nodes[-1].id)
                store.allocs_by_node_direct(nodes[0].id)
                store.has_draining_nodes()
                store.latest_index()
                store.with_usage_view(lambda planes, allocs: None)
            assert holds("store_write_txn") == before_txn
            assert holds("store_watch") == before_watch
        finally:
            assert witness.violations() == []
            witness.disable()
            witness.reset()

    def test_write_txn_histogram_always_records(self, clean_telemetry):
        """store_write_txn latency records per commit with or without
        the witness — it is the store's own instrumentation, not the
        witness's."""
        from nomad_tpu import mock
        from nomad_tpu.state.store import StateStore
        from nomad_tpu.telemetry.histogram import histograms

        before = histograms.get("store_write_txn").count
        store = StateStore()
        store.upsert_node(mock.node())
        store.upsert_node(mock.node())
        assert histograms.get("store_write_txn").count == before + 2
