"""java/qemu/docker driver tests.

Modeled on reference drivers/java/driver_test.go,
drivers/qemu/driver_test.go, drivers/docker/driver_test.go -- command
construction, config validation, and fingerprint gating (none of the
three binaries exist in this image, so fingerprints must come back
undetected and the catalog must still register the drivers).
"""

import pytest

from nomad_tpu import structs
from nomad_tpu.client.fingerprint import fingerprint_node
from nomad_tpu.drivers import builtin_drivers
from nomad_tpu.drivers.docker import DockerDriver, _container_name
from nomad_tpu.drivers.java import JavaDriver
from nomad_tpu.drivers.qemu import QemuDriver
from nomad_tpu.plugins.drivers import HEALTH_UNDETECTED, TaskConfig


def cfg(driver_config, **kw):
    return TaskConfig(id="t1", name="web", alloc_id="a1-xyz",
                      driver_config=driver_config,
                      resources=kw.pop("resources", structs.Resources()),
                      **kw)


class TestCatalog:
    def test_all_six_registered(self):
        drivers = builtin_drivers()
        assert set(drivers) == {"mock_driver", "raw_exec", "exec",
                                "java", "qemu", "docker"}

    def test_fingerprint_gating_in_node(self):
        node = fingerprint_node("n1", drivers=builtin_drivers())
        # binaries absent in this image -> undetected, never placed on
        assert not node.drivers["java"].detected
        assert not node.drivers["qemu"].detected
        assert not node.drivers["docker"].detected
        assert node.drivers["raw_exec"].detected


class TestJava:
    def test_fingerprint_gated(self):
        assert JavaDriver().fingerprint().health == HEALTH_UNDETECTED

    def test_jar_command(self):
        argv = JavaDriver()._command(cfg({
            "jar_path": "/opt/app.jar",
            "jvm_options": ["-Xmx512m"],
            "args": ["serve"],
        }))
        assert argv == ["java", "-Xmx512m", "-jar", "/opt/app.jar", "serve"]

    def test_class_command(self):
        argv = JavaDriver()._command(cfg({
            "class": "com.example.Main", "class_path": "/opt/lib",
        }))
        assert argv == ["java", "-cp", "/opt/lib", "com.example.Main"]

    def test_requires_jar_or_class(self):
        with pytest.raises(ValueError):
            JavaDriver()._command(cfg({}))


class TestQemu:
    def test_fingerprint_gated(self):
        assert QemuDriver().fingerprint().health == HEALTH_UNDETECTED

    def test_command(self):
        res = structs.Resources(memory_mb=1024)
        argv = QemuDriver()._command(cfg({"image_path": "/img/linux.img"},
                                         resources=res))
        assert argv[0] == "qemu-system-x86_64"
        assert "-nographic" in argv
        assert "file=/img/linux.img" in argv
        assert "1024M" in argv

    def test_port_forwards(self):
        res = structs.Resources(
            memory_mb=512,
            networks=[structs.NetworkResource(
                reserved_ports=[structs.Port(label="ssh", value=2222)],
            )],
        )
        argv = QemuDriver()._command(cfg({
            "image_path": "/img/linux.img",
            "port_map": {"ssh": 22},
        }, resources=res))
        netdev = argv[argv.index("-netdev") + 1]
        assert "hostfwd=tcp::2222-:22" in netdev

    def test_requires_image(self):
        with pytest.raises(ValueError):
            QemuDriver()._command(cfg({}))


class TestDocker:
    def test_fingerprint_gated(self):
        assert DockerDriver().fingerprint().health == HEALTH_UNDETECTED

    def test_command(self):
        res = structs.Resources(cpu=500, memory_mb=256)
        argv = DockerDriver()._command(cfg(
            {"image": "redis:7", "command": "redis-server",
             "args": ["--appendonly", "yes"]},
            env={"FOO": "bar"}, resources=res,
        ))
        assert argv[:3] == ["docker", "run", "--rm"]
        assert "--memory" in argv and "256m" in argv
        assert "--cpu-shares" in argv and "500" in argv
        assert "-e" in argv and "FOO=bar" in argv
        assert argv[argv.index("redis:7"):] == \
            ["redis:7", "redis-server", "--appendonly", "yes"]

    def test_port_publish(self):
        res = structs.Resources(networks=[structs.NetworkResource(
            dynamic_ports=[structs.Port(label="http", value=20001, to=8080)],
        )])
        argv = DockerDriver()._command(cfg(
            {"image": "nginx", "ports": ["http"]}, resources=res,
        ))
        assert "-p" in argv
        assert "20001:8080" in argv

    def test_container_name_stable(self):
        c = cfg({"image": "nginx"})
        assert _container_name(c) == "nomad-web-a1-xyz"[:len(_container_name(c))]
        assert _container_name(c).startswith("nomad-web-")

    def test_requires_image(self):
        with pytest.raises(ValueError):
            DockerDriver()._command(cfg({}))


class TestDockerVolumesGate:
    """Host bind mounts are host-root-equivalent; disabled unless the
    operator sets docker.volumes.enabled (drivers/docker volumes gate)."""

    def test_volumes_rejected_by_default(self):
        with pytest.raises(ValueError, match="volumes are disabled"):
            DockerDriver()._command(
                cfg({"image": "nginx", "volumes": ["/:/host"]}))

    def test_volumes_allowed_when_enabled(self):
        drv = DockerDriver(options={"docker.volumes.enabled": "true"})
        argv = drv._command(
            cfg({"image": "nginx", "volumes": ["/data:/data"]}))
        assert "-v" in argv and "/data:/data" in argv

    def test_no_volumes_fine_without_flag(self):
        argv = DockerDriver()._command(cfg({"image": "nginx"}))
        assert "-v" not in argv


class TestJavaFingerprintDepth:
    """driver.java.version/runtime/vm attributes from `java -version`
    (drivers/java/utils.go parse semantics), via a fake JVM binary."""

    FAKE = (
        "#!/bin/sh\n"
        "echo 'openjdk version \"17.0.2\" 2022-01-18' >&2\n"
        "echo 'OpenJDK Runtime Environment (build 17.0.2+8-86)' >&2\n"
        "echo 'OpenJDK 64-Bit Server VM (build 17.0.2+8-86, mixed mode)'"
        " >&2\n"
    )

    def test_version_runtime_vm_attributes(self, tmp_path):
        import os
        import stat

        fake = tmp_path / "java"
        fake.write_text(self.FAKE)
        os.chmod(fake, stat.S_IRWXU)
        drv = JavaDriver()
        drv.java_bin = str(fake)
        fp = drv.fingerprint()
        assert fp.attributes["driver.java.version"] == "17.0.2"
        assert "Runtime Environment" in fp.attributes["driver.java.runtime"]
        assert "VM" in fp.attributes["driver.java.vm"]

    def test_parse_helper(self):
        from nomad_tpu.drivers.java import parse_java_version

        v, rt, vm = parse_java_version(
            'java version "1.8.0_292"\n'
            "Java(TM) SE Runtime Environment (build 1.8.0_292-b10)\n"
            "Java HotSpot(TM) 64-Bit Server VM (build 25.292-b10)\n")
        assert v == "1.8.0_292"
        assert "Runtime Environment" in rt
        assert "VM" in vm

    def test_executor_resource_opts(self):
        """The JVM runs under the isolating executor with cgroup
        limits from the task resources (driver.go StartTask)."""
        from nomad_tpu.drivers.execdriver import isolation_support

        drv = JavaDriver()
        res = structs.Resources(cpu=750, memory_mb=640)
        opts = drv._executor_opts(cfg({"jar_path": "/a.jar"},
                                      resources=res))
        support = isolation_support()
        if support["cgroups"]:
            assert "-mem_mb" in opts and "640" in opts
            assert "-cpu_shares" in opts and "750" in opts
        if support["namespaces"]:
            assert "-isolate" in opts


class TestQemuGracefulShutdown:
    """QMP monitor-socket shutdown (drivers/qemu/driver.go StopTask's
    graceful path), against a scripted QMP endpoint."""

    def _fake_qmp(self, path, received):
        import json
        import socket
        import threading

        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(path)
        srv.listen(1)

        def serve():
            conn, _ = srv.accept()
            f = conn.makefile("rwb")
            f.write(json.dumps(
                {"QMP": {"version": {}, "capabilities": []}}).encode()
                + b"\n")
            f.flush()
            for line in f:
                msg = json.loads(line)
                received.append(msg.get("execute"))
                f.write(b'{"return": {}}\n')
                f.flush()
                if msg.get("execute") == "system_powerdown":
                    break
            conn.close()

        t = threading.Thread(target=serve, daemon=True)
        t.start()
        return srv

    def test_monitor_arg_in_command(self):
        res = structs.Resources(memory_mb=512)
        c = cfg({"image_path": "/img/linux.img"}, resources=res)
        drv = QemuDriver()
        argv = drv._command(c)
        qmp = argv[argv.index("-qmp") + 1]
        assert qmp.startswith("unix:") and qmp.endswith(",server,nowait")
        assert drv.monitor_path(c) in qmp

    def test_graceful_shutdown_disabled_drops_monitor(self):
        res = structs.Resources(memory_mb=512)
        argv = QemuDriver()._command(cfg({
            "image_path": "/img/linux.img", "graceful_shutdown": False,
        }, resources=res))
        assert "-qmp" not in argv

    def test_qmp_system_powerdown_handshake(self, tmp_path):
        received = []
        path = str(tmp_path / "qmp.sock")
        srv = self._fake_qmp(path, received)
        try:
            ok = QemuDriver.qmp_system_powerdown(path, timeout=5.0)
        finally:
            srv.close()
        assert ok
        assert received == ["qmp_capabilities", "system_powerdown"]

    def test_stop_task_prefers_graceful(self, tmp_path):
        """stop_task sends system_powerdown and waits for the VM to
        exit on its own before any signal."""
        import threading

        drv = QemuDriver()
        c = cfg({"image_path": "/img/linux.img"})
        c.alloc_dir = str(tmp_path)
        # a fake running task whose monitor socket is our scripted QMP
        from nomad_tpu.drivers.rawexec import _RawTask

        task = _RawTask(c)
        task.pid = task.pgid = 999999999        # never signalled
        drv._tasks[c.id] = task
        received = []
        srv = self._fake_qmp(drv.monitor_path(c), received)

        def guest_exits():
            # the guest "powers down" shortly after the QMP command
            while "system_powerdown" not in received:
                pass
            task.done.set()

        threading.Thread(target=guest_exits, daemon=True).start()
        try:
            drv.stop_task(c.id, timeout=5.0)
        finally:
            srv.close()
        assert task.done.is_set()
        assert received == ["qmp_capabilities", "system_powerdown"]
