"""java/qemu/docker driver tests.

Modeled on reference drivers/java/driver_test.go,
drivers/qemu/driver_test.go, drivers/docker/driver_test.go -- command
construction, config validation, and fingerprint gating (none of the
three binaries exist in this image, so fingerprints must come back
undetected and the catalog must still register the drivers).
"""

import pytest

from nomad_tpu import structs
from nomad_tpu.client.fingerprint import fingerprint_node
from nomad_tpu.drivers import builtin_drivers
from nomad_tpu.drivers.docker import DockerDriver, _container_name
from nomad_tpu.drivers.java import JavaDriver
from nomad_tpu.drivers.qemu import QemuDriver
from nomad_tpu.plugins.drivers import HEALTH_UNDETECTED, TaskConfig


def cfg(driver_config, **kw):
    return TaskConfig(id="t1", name="web", alloc_id="a1-xyz",
                      driver_config=driver_config,
                      resources=kw.pop("resources", structs.Resources()),
                      **kw)


class TestCatalog:
    def test_all_six_registered(self):
        drivers = builtin_drivers()
        assert set(drivers) == {"mock_driver", "raw_exec", "exec",
                                "java", "qemu", "docker"}

    def test_fingerprint_gating_in_node(self):
        node = fingerprint_node("n1", drivers=builtin_drivers())
        # binaries absent in this image -> undetected, never placed on
        assert not node.drivers["java"].detected
        assert not node.drivers["qemu"].detected
        assert not node.drivers["docker"].detected
        assert node.drivers["raw_exec"].detected


class TestJava:
    def test_fingerprint_gated(self):
        assert JavaDriver().fingerprint().health == HEALTH_UNDETECTED

    def test_jar_command(self):
        argv = JavaDriver()._command(cfg({
            "jar_path": "/opt/app.jar",
            "jvm_options": ["-Xmx512m"],
            "args": ["serve"],
        }))
        assert argv == ["java", "-Xmx512m", "-jar", "/opt/app.jar", "serve"]

    def test_class_command(self):
        argv = JavaDriver()._command(cfg({
            "class": "com.example.Main", "class_path": "/opt/lib",
        }))
        assert argv == ["java", "-cp", "/opt/lib", "com.example.Main"]

    def test_requires_jar_or_class(self):
        with pytest.raises(ValueError):
            JavaDriver()._command(cfg({}))


class TestQemu:
    def test_fingerprint_gated(self):
        assert QemuDriver().fingerprint().health == HEALTH_UNDETECTED

    def test_command(self):
        res = structs.Resources(memory_mb=1024)
        argv = QemuDriver()._command(cfg({"image_path": "/img/linux.img"},
                                         resources=res))
        assert argv[0] == "qemu-system-x86_64"
        assert "-nographic" in argv
        assert "file=/img/linux.img" in argv
        assert "1024M" in argv

    def test_port_forwards(self):
        res = structs.Resources(
            memory_mb=512,
            networks=[structs.NetworkResource(
                reserved_ports=[structs.Port(label="ssh", value=2222)],
            )],
        )
        argv = QemuDriver()._command(cfg({
            "image_path": "/img/linux.img",
            "port_map": {"ssh": 22},
        }, resources=res))
        netdev = argv[argv.index("-netdev") + 1]
        assert "hostfwd=tcp::2222-:22" in netdev

    def test_requires_image(self):
        with pytest.raises(ValueError):
            QemuDriver()._command(cfg({}))


class TestDocker:
    def test_fingerprint_gated(self):
        assert DockerDriver().fingerprint().health == HEALTH_UNDETECTED

    def test_command(self):
        res = structs.Resources(cpu=500, memory_mb=256)
        argv = DockerDriver()._command(cfg(
            {"image": "redis:7", "command": "redis-server",
             "args": ["--appendonly", "yes"]},
            env={"FOO": "bar"}, resources=res,
        ))
        assert argv[:3] == ["docker", "run", "--rm"]
        assert "--memory" in argv and "256m" in argv
        assert "--cpu-shares" in argv and "500" in argv
        assert "-e" in argv and "FOO=bar" in argv
        assert argv[argv.index("redis:7"):] == \
            ["redis:7", "redis-server", "--appendonly", "yes"]

    def test_port_publish(self):
        res = structs.Resources(networks=[structs.NetworkResource(
            dynamic_ports=[structs.Port(label="http", value=20001, to=8080)],
        )])
        argv = DockerDriver()._command(cfg(
            {"image": "nginx", "ports": ["http"]}, resources=res,
        ))
        assert "-p" in argv
        assert "20001:8080" in argv

    def test_container_name_stable(self):
        c = cfg({"image": "nginx"})
        assert _container_name(c) == "nomad-web-a1-xyz"[:len(_container_name(c))]
        assert _container_name(c).startswith("nomad-web-")

    def test_requires_image(self):
        with pytest.raises(ValueError):
            DockerDriver()._command(cfg({}))


class TestDockerVolumesGate:
    """Host bind mounts are host-root-equivalent; disabled unless the
    operator sets docker.volumes.enabled (drivers/docker volumes gate)."""

    def test_volumes_rejected_by_default(self):
        with pytest.raises(ValueError, match="volumes are disabled"):
            DockerDriver()._command(
                cfg({"image": "nginx", "volumes": ["/:/host"]}))

    def test_volumes_allowed_when_enabled(self):
        drv = DockerDriver(options={"docker.volumes.enabled": "true"})
        argv = drv._command(
            cfg({"image": "nginx", "volumes": ["/data:/data"]}))
        assert "-v" in argv and "/data:/data" in argv

    def test_no_volumes_fine_without_flag(self):
        argv = DockerDriver()._command(cfg({"image": "nginx"}))
        assert "-v" not in argv
