"""Fault-injection plane unit tests (ISSUE 12).

The registry itself: disarmed no-op, deterministic seeded schedules
(fail-Nth, every-Nth, Bernoulli, latency, thread-kill), counters, and
the exporter series. The wired seams are exercised by the mini chaos
smoke (tests/test_chaos.py) and the full chaos cell (stress tier).
"""

import threading
import time

import pytest

from nomad_tpu.utils import faultpoints
from nomad_tpu.utils.faultpoints import (
    FaultError,
    FaultThreadKill,
    fault,
)


@pytest.fixture(autouse=True)
def _clean_plane():
    faultpoints.reset()
    yield
    faultpoints.reset()


class TestDisarmedPath:
    def test_disarmed_fault_is_a_noop(self):
        # no exception, no registry entry, no lock taken
        for _ in range(1000):
            fault("some.point")
        assert faultpoints.stats() == {}
        assert not faultpoints.armed()

    def test_disarm_stops_firing_but_keeps_stats(self):
        faultpoints.arm({"p1": {"kind": "error"}})
        with pytest.raises(FaultError):
            fault("p1")
        faultpoints.disarm()
        fault("p1")                      # no-op again
        assert faultpoints.stats()["p1"]["fires"] == 1


class TestSchedules:
    def test_error_nth_fires_exactly_once_at_nth(self):
        faultpoints.arm({"p": {"kind": "error", "nth": 3}})
        fault("p")
        fault("p")
        with pytest.raises(FaultError) as ei:
            fault("p")
        assert ei.value.point == "p"
        for _ in range(10):
            fault("p")                   # nth defaults max_fires=1
        s = faultpoints.stats()["p"]
        assert s["hits"] == 13 and s["fires"] == 1

    def test_every_nth_with_max_fires(self):
        faultpoints.arm({"p": {"kind": "error", "every": 2,
                               "max_fires": 2}})
        fired = 0
        for _ in range(10):
            try:
                fault("p")
            except FaultError:
                fired += 1
        assert fired == 2
        assert faultpoints.stats()["p"]["fires"] == 2

    def test_probability_is_seed_deterministic(self):
        def pattern(seed):
            faultpoints.reset()
            faultpoints.arm({"p": {"kind": "error", "p": 0.5}},
                            seed=seed)
            out = []
            for _ in range(64):
                try:
                    fault("p")
                    out.append(0)
                except FaultError:
                    out.append(1)
            return out

        a = pattern(42)
        b = pattern(42)
        assert a == b, "same seed must replay the same decisions"
        assert 0 < sum(a) < 64, "p=0.5 over 64 hits fires some, not all"

    def test_latency_sleeps(self):
        faultpoints.arm({"p": {"kind": "latency", "sleep_s": 0.05}})
        t0 = time.perf_counter()
        fault("p")
        assert time.perf_counter() - t0 >= 0.045
        assert faultpoints.stats()["p"]["fires"] == 1

    def test_kill_is_baseexception_not_exception(self):
        faultpoints.arm({"p": {"kind": "kill", "nth": 1}})
        caught_by_except_exception = False
        try:
            try:
                fault("p")
            except Exception:            # the worker's confinement
                caught_by_except_exception = True
        except FaultThreadKill:
            pass
        assert not caught_by_except_exception
        # kill defaults to one-shot
        fault("p")

    def test_kill_escapes_a_thread_but_finally_unwinds(self):
        faultpoints.arm({"p": {"kind": "kill", "nth": 1}})
        unwound = threading.Event()

        def victim():
            try:
                fault("p")
            finally:
                unwound.set()

        th = threading.Thread(target=victim, daemon=True)
        th.start()
        th.join(timeout=5)
        assert unwound.is_set()

    def test_unknown_kind_rejected_at_arm(self):
        with pytest.raises(ValueError):
            faultpoints.arm({"p": {"kind": "nonsense"}})

    def test_unscheduled_point_counts_hits_while_armed(self):
        faultpoints.arm({"scheduled": {"kind": "error", "nth": 99}})
        fault("unscheduled")
        s = faultpoints.stats()["unscheduled"]
        assert s["hits"] == 1 and s["fires"] == 0 and s["kind"] is None

    def test_fires_total(self):
        faultpoints.arm({"a": {"kind": "error"}, "b": {"kind": "error"}})
        for name in ("a", "b", "a"):
            with pytest.raises(FaultError):
                fault(name)
        assert faultpoints.fires() == 3


class TestExporterSeries:
    def test_fault_series_in_prometheus_text(self):
        from nomad_tpu.telemetry.exporter import prometheus_text

        faultpoints.arm({"pt": {"kind": "error", "nth": 1}})
        with pytest.raises(FaultError):
            fault("pt")
        text = prometheus_text()
        assert "nomad_tpu_fault_armed 1" in text
        assert 'nomad_tpu_fault_hits_total{point="pt"} 1' in text
        assert ('nomad_tpu_fault_fires_total{point="pt",kind="error"} 1'
                in text)
        faultpoints.reset()
        assert "nomad_tpu_fault_armed 0" in prometheus_text()
