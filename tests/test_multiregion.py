"""Multiregion job deployments (structs.go:4133 Multiregion).

A job with a multiregion block fans out into per-region copies over
the federation layer; deployments in regions beyond the strategy's
first max_parallel wave start blocked and unblock only when an
earlier region's deployment succeeds (the deployment watcher's
cross-region kick).
"""

import time

from nomad_tpu import mock, structs
from nomad_tpu.api.agent import Agent, AgentConfig
from nomad_tpu.structs import consts


def wait_for(fn, timeout=25.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        out = fn()
        if out:
            return out
        time.sleep(0.05)
    raise AssertionError(f"timeout waiting for {msg}")


def make_mr_job(max_parallel=1):
    job = mock.job()
    job.region = "global"
    job.task_groups[0].count = 2
    task = job.task_groups[0].tasks[0]
    task.driver = "mock_driver"
    task.config = {"run_for": 60}
    job.task_groups[0].update = structs.UpdateStrategy(
        max_parallel=2,
        min_healthy_time_s=0.1,
        healthy_deadline_s=10.0,
        progress_deadline_s=60.0,
    )
    job.multiregion = {
        "strategy": {"max_parallel": max_parallel, "on_failure": ""},
        "regions": [
            {"name": "east", "count": 1, "datacenters": []},
            {"name": "west", "count": 1, "datacenters": []},
        ],
    }
    return job


class TestMultiregion:
    def test_two_region_rollout_gates_on_first_region(self):
        east = Agent(AgentConfig.dev(name="east-1", region="east"))
        west = Agent(AgentConfig.dev(name="west-1", region="west"))
        east.start()
        west.start()
        try:
            east.server.join_region("west", west.http.addr)
            west.server.join_region("east", east.http.addr)

            job = make_mr_job(max_parallel=1)
            out = east.server.job_register(job)
            assert sorted(out["regions"]) == ["east", "west"]

            # both regions got their copy, with the per-region count
            e_job = wait_for(
                lambda: east.server.state.snapshot().job_by_id(
                    job.namespace, job.id), msg="east job")
            w_job = wait_for(
                lambda: west.server.state.snapshot().job_by_id(
                    job.namespace, job.id), msg="west job")
            assert e_job.region == "east" and w_job.region == "west"
            assert e_job.task_groups[0].count == 1
            assert w_job.task_groups[0].count == 1

            # west's deployment starts blocked; east's runs
            w_dep = wait_for(
                lambda: west.server.state.snapshot()
                .latest_deployment_by_job_id(job.namespace, job.id),
                msg="west deployment")
            assert w_dep.status == consts.DEPLOYMENT_STATUS_BLOCKED
            # the gate is real: while blocked, west placed NOTHING
            assert west.server.state.snapshot().allocs_by_job(
                job.namespace, job.id) == []

            # while east is still rolling, west must not place allocs
            # beyond the gate (its reconciler treats blocked as paused)
            e_dep = wait_for(
                lambda: east.server.state.snapshot()
                .latest_deployment_by_job_id(job.namespace, job.id),
                msg="east deployment")
            assert e_dep.status != consts.DEPLOYMENT_STATUS_BLOCKED

            # east succeeds -> watcher kicks west's gate open
            wait_for(
                lambda: east.server.state.snapshot()
                .latest_deployment_by_job_id(job.namespace, job.id).status
                == consts.DEPLOYMENT_STATUS_SUCCESSFUL,
                timeout=40, msg="east deployment successful")
            wait_for(
                lambda: west.server.state.snapshot()
                .latest_deployment_by_job_id(job.namespace, job.id).status
                != consts.DEPLOYMENT_STATUS_BLOCKED,
                timeout=40, msg="west deployment unblocked")
            # and west then completes its own rollout
            wait_for(
                lambda: west.server.state.snapshot()
                .latest_deployment_by_job_id(job.namespace, job.id).status
                == consts.DEPLOYMENT_STATUS_SUCCESSFUL,
                timeout=40, msg="west deployment successful")
        finally:
            east.shutdown()
            west.shutdown()

    def test_region_failure_fails_downstream_regions(self):
        """Default on_failure (''): a region's deployment failure fails
        every region after it in the rollout order; regions before it
        keep their result (structs.go:4133 on_failure semantics).

        West is starved (datacenters override no node matches), so its
        deployment blows the progress deadline after east's success
        unblocks it; central — still gated behind west — must then be
        failed by the cross-region propagation, not left blocked."""
        east = Agent(AgentConfig.dev(name="east-3", region="east"))
        west = Agent(AgentConfig.dev(name="west-3", region="west"))
        central = Agent(AgentConfig.dev(name="central-3", region="central"))
        agents = [east, west, central]
        for a in agents:
            a.start()
        try:
            for a in agents:
                for b in agents:
                    if a is not b:
                        a.server.join_region(b.config.region, b.http.addr)
            job = make_mr_job(max_parallel=1)
            job.task_groups[0].update.progress_deadline_s = 2.0
            job.multiregion["regions"] = [
                {"name": "east", "count": 1, "datacenters": []},
                {"name": "west", "count": 1, "datacenters": ["nowhere"]},
                {"name": "central", "count": 1, "datacenters": []},
            ]
            east.server.job_register(job)

            def dep_status(agent):
                d = agent.server.state.snapshot() \
                    .latest_deployment_by_job_id(job.namespace, job.id)
                return d.status if d else None

            wait_for(lambda: dep_status(east)
                     == consts.DEPLOYMENT_STATUS_SUCCESSFUL,
                     timeout=40, msg="east successful")
            wait_for(lambda: dep_status(west)
                     == consts.DEPLOYMENT_STATUS_FAILED,
                     timeout=40, msg="west failed")
            wait_for(lambda: dep_status(central)
                     == consts.DEPLOYMENT_STATUS_FAILED,
                     timeout=40, msg="central failed by propagation")
            # east keeps its success — default on_failure only fails
            # DOWNSTREAM regions
            assert dep_status(east) == consts.DEPLOYMENT_STATUS_SUCCESSFUL
        finally:
            for a in agents:
                a.shutdown()

    def test_region_failure_fail_local_leaves_others_blocked(self):
        """on_failure='fail_local': only the failing region fails; the
        downstream region stays blocked awaiting operator action."""
        east = Agent(AgentConfig.dev(name="east-4", region="east"))
        west = Agent(AgentConfig.dev(name="west-4", region="west"))
        central = Agent(AgentConfig.dev(name="central-4", region="central"))
        agents = [east, west, central]
        for a in agents:
            a.start()
        try:
            for a in agents:
                for b in agents:
                    if a is not b:
                        a.server.join_region(b.config.region, b.http.addr)
            job = make_mr_job(max_parallel=1)
            job.task_groups[0].update.progress_deadline_s = 2.0
            job.multiregion["strategy"]["on_failure"] = "fail_local"
            job.multiregion["regions"] = [
                {"name": "east", "count": 1, "datacenters": []},
                {"name": "west", "count": 1, "datacenters": ["nowhere"]},
                {"name": "central", "count": 1, "datacenters": []},
            ]
            east.server.job_register(job)

            def dep_status(agent):
                d = agent.server.state.snapshot() \
                    .latest_deployment_by_job_id(job.namespace, job.id)
                return d.status if d else None

            wait_for(lambda: dep_status(west)
                     == consts.DEPLOYMENT_STATUS_FAILED,
                     timeout=40, msg="west failed")
            time.sleep(2.0)   # propagation would have landed by now
            assert dep_status(central) == consts.DEPLOYMENT_STATUS_BLOCKED
        finally:
            for a in agents:
                a.shutdown()

    def test_max_parallel_zero_runs_all_regions(self):
        east = Agent(AgentConfig.dev(name="east-2", region="east"))
        west = Agent(AgentConfig.dev(name="west-2", region="west"))
        east.start()
        west.start()
        try:
            east.server.join_region("west", west.http.addr)
            west.server.join_region("east", east.http.addr)
            job = make_mr_job(max_parallel=0)
            east.server.job_register(job)
            for agent in (east, west):
                dep = wait_for(
                    lambda a=agent: a.server.state.snapshot()
                    .latest_deployment_by_job_id(job.namespace, job.id),
                    msg="deployment")
                assert dep.status != consts.DEPLOYMENT_STATUS_BLOCKED
        finally:
            east.shutdown()
            west.shutdown()
