"""Multi-chip sharding parity: the batched sharded kernel must produce
exactly the single-device results (GSPMD collectives change layout, not
semantics)."""

import jax
import numpy as np
import pytest

from nomad_tpu.ops.kernel import KernelOut, pad_steps, place_taskgroup_jit
from nomad_tpu.parallel.mesh import make_mesh
from nomad_tpu.parallel.sharded import (
    make_place_batch,
    stack_kernel_ins,
    unstack_kernel_outs,
)
from nomad_tpu.parallel.synthetic import synthetic_kernel_in


@pytest.fixture(scope="module")
def problems():
    n_steps = 4
    return n_steps, [
        synthetic_kernel_in(
            n_nodes=200, n_steps=n_steps, with_spread=(i % 2 == 0),
            used_frac=0.5, seed=i,
        )
        for i in range(4)
    ]


def test_mesh_shapes():
    mesh = make_mesh(8)
    assert mesh.shape == {"evals": 2, "nodes": 4}
    mesh = make_mesh(1)
    assert mesh.shape == {"evals": 1, "nodes": 1}
    mesh = make_mesh(8, evals_parallel=4)
    assert mesh.shape == {"evals": 4, "nodes": 2}


def test_sharded_matches_single_device(problems):
    n_steps, kins = problems
    k_pad = pad_steps(n_steps)
    singles = [
        KernelOut(*[np.asarray(x) for x in place_taskgroup_jit(kin, k_pad)])
        for kin in kins
    ]

    mesh = make_mesh(8)
    step = make_place_batch(mesh, k_pad)
    out = step(stack_kernel_ins(kins))
    jax.block_until_ready(out)
    outs = unstack_kernel_outs(out)

    for got, want in zip(outs, singles):
        np.testing.assert_array_equal(got.chosen, want.chosen)
        np.testing.assert_array_equal(got.found, want.found)
        np.testing.assert_allclose(got.scores, want.scores, rtol=1e-5)
        assert int(got.nodes_evaluated) == int(want.nodes_evaluated)
        assert int(got.nodes_feasible) == int(want.nodes_feasible)


def test_sharded_1d_nodes_only(problems):
    """A nodes-only mesh (evals axis 1) also runs: pure sp sharding."""
    n_steps, kins = problems
    k_pad = pad_steps(n_steps)
    mesh = make_mesh(8, evals_parallel=1)
    step = make_place_batch(mesh, k_pad)
    out = step(stack_kernel_ins(kins))
    jax.block_until_ready(out)
    found = np.asarray(out.found)
    assert found[:, :n_steps].all()


def test_fused_schedule_apply_step():
    """Device-resident state loop: placements commit as scatter deltas
    and later batches see them."""
    import jax.numpy as jnp

    from nomad_tpu.ops.kernel import KernelFeatures, build_kernel_in
    from nomad_tpu.parallel.batching import (
        device_put_shared,
        make_schedule_apply_step,
    )
    from nomad_tpu.parallel.synthetic import synthetic_cluster, synthetic_eval

    n_nodes, batch, k = 50, 4, 2
    cluster = synthetic_cluster(n_nodes, seed=1)
    ev = synthetic_eval(cluster, desired_count=k, seed=1)
    shared = device_put_shared(build_kernel_in(cluster, ev, k))
    lean = KernelFeatures(
        n_spreads=0, with_topk=False, with_devices=False, with_ports=False,
        with_cores=False, with_network=False, with_distinct=False,
        with_step_penalties=False, with_preferred=False,
    )
    step = make_schedule_apply_step(k, lean)

    uc = shared.used_cpu
    um = shared.used_mem
    ask_cpu = jnp.full(batch, 500.0, jnp.float32)
    ask_mem = jnp.full(batch, 256.0, jnp.float32)
    n_steps = jnp.full(batch, k, jnp.int32)

    total_cpu0 = float(uc.sum())
    out, uc, um = step(shared, uc, um, ask_cpu, ask_mem, n_steps)
    found = np.asarray(out.found)
    assert found.all()
    # every accepted placement committed 500 MHz
    assert float(uc.sum()) == pytest.approx(total_cpu0 + 500.0 * batch * k)
    # run again: utilization monotonically grows
    out2, uc2, um2 = step(shared, uc, um, ask_cpu, ask_mem, n_steps)
    assert float(uc2.sum()) == pytest.approx(total_cpu0 + 2 * 500.0 * batch * k)


class TestDonatedLoopOwnership:
    """The donated bench loops must never write into caller-owned
    numpy memory. ``jnp.asarray(numpy)`` is zero-copy on the CPU
    backend when the allocator cooperates; donating such a buffer let
    the runtime write the scan carry in place into the caller's array
    — the 1-in-5 test_pallas_kernel top-k parity flake. The
    ``_jit_donating`` wrapper copies donated args into buffers it
    owns; this test re-runs a loop from the same numpy planes and
    must see identical results and untouched inputs every time."""

    def test_numpy_inputs_survive_donated_loop(self):
        import numpy as np
        import jax.numpy as jnp

        from nomad_tpu.ops.kernel import LEAN_FEATURES, build_kernel_in
        from nomad_tpu.parallel.batching import (
            device_put_shared,
            make_schedule_apply_loop,
        )
        from nomad_tpu.parallel.synthetic import (
            synthetic_cluster,
            synthetic_eval,
        )

        n, k, b = 200, 4, 4
        cluster = synthetic_cluster(n, cpu=2000.0, mem=4096.0,
                                    disk=50000.0, seed=11)
        ev = synthetic_eval(cluster, desired_count=k)
        shared = device_put_shared(build_kernel_in(cluster, ev, k))
        npad = shared.cap_cpu.shape[0]
        rng = np.random.default_rng(13)
        used = np.zeros(npad, np.float32)
        used[:n] = 2000.0 * 0.5 * rng.random(n, dtype=np.float32)
        usedm = np.zeros(npad, np.float32)
        usedm[:n] = 4096.0 * 0.5 * rng.random(n, dtype=np.float32)
        used0, usedm0 = used.copy(), usedm.copy()
        asks_cpu = jnp.asarray(
            rng.choice([100.0, 250.0], (3, b)).astype(np.float32))
        asks_mem = jnp.asarray(
            rng.choice([64.0, 128.0], (3, b)).astype(np.float32))
        n_steps = jnp.asarray(np.full(b, k, np.int32))

        loop = make_schedule_apply_loop(k, LEAN_FEATURES, topk=True)
        scores = set()
        for _ in range(4):
            out = loop(shared, jnp.asarray(used), jnp.asarray(usedm),
                       asks_cpu, asks_mem, n_steps)
            scores.add(float(out[0]))
            np.testing.assert_array_equal(used, used0)
            np.testing.assert_array_equal(usedm, usedm0)
        assert len(scores) == 1, "donated loop is not repeatable"
