"""Client hardening tests: logmon rotation, heartbeatstop,
allocwatcher, agent config files.

Modeled on reference client/logmon tests (rotation), heartbeatstop.go
tests (self-stop on disconnect), allocwatcher/alloc_watcher_test.go
(prev-alloc wait + disk migration), and command/agent/config_parse
tests.
"""

import os
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.api.config_file import load_config_files
from nomad_tpu.client.client import Client, ClientConfig, InProcessRPC
from nomad_tpu.client.logmon import LogMon, read_rotated, rotated_files
from nomad_tpu.server.server import Server, ServerConfig
from nomad_tpu.structs import consts
from nomad_tpu.structs.job import EphemeralDisk


def _wait(fn, timeout=15.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(0.05)
    return False


class TestLogMon:
    def test_collects_and_rotates(self, tmp_path):
        import threading

        from nomad_tpu.client.logmon import _Collector

        base = str(tmp_path / "web.stdout")
        collector = _Collector(base, max_files=3, max_file_size_mb=1)
        collector.max_bytes = 100   # tiny rotation threshold for the test
        collector.open()
        t = threading.Thread(target=collector.run, daemon=True)
        t.start()
        try:
            fd = os.open(collector.fifo_path, os.O_WRONLY)
            for i in range(20):
                os.write(fd, f"line-{i:04d} ".encode() * 4)
            os.close(fd)
            assert _wait(lambda: len(rotated_files(base)) >= 2)
        finally:
            collector.request_stop()
            t.join(timeout=3)
        files = rotated_files(base)
        assert 2 <= len(files) <= 3          # pruned to max_files
        data = read_rotated(base)
        assert b"line-0019" in data

    def test_read_rotated_offset_limit(self, tmp_path):
        base = str(tmp_path / "t.stdout")
        for i, content in enumerate([b"aaaa", b"bbbb", b"cccc"]):
            with open(f"{base}.{i}", "wb") as f:
                f.write(content)
        assert read_rotated(base) == b"aaaabbbbcccc"
        assert read_rotated(base, offset=2) == b"aabbbbcccc"
        assert read_rotated(base, offset=5, limit=4) == b"bbbc"

    def test_task_logs_end_to_end(self, tmp_path):
        """rawexec output travels fifo -> logmon -> rotated file ->
        fs logs API."""
        server = Server(ServerConfig(num_workers=1))
        server.start()
        client = Client(InProcessRPC(server),
                        ClientConfig(data_dir=str(tmp_path)))
        client.start()
        try:
            job = mock.job()
            job.type = consts.JOB_TYPE_BATCH
            job.task_groups[0].count = 1
            task = job.task_groups[0].tasks[0]
            task.driver = "raw_exec"
            task.config = {"command": "/bin/sh",
                           "args": ["-c", "echo logmon-works"]}
            server.job_register(job)
            assert _wait(lambda: any(
                ar.is_done() for ar in client.allocs.values()
                if ar.alloc.job_id == job.id), timeout=30)
            ar = next(a for a in client.allocs.values()
                      if a.alloc.job_id == job.id)
            assert _wait(lambda: "logmon-works" in
                         ar.task_logs(task.name, "stdout"))
        finally:
            client.shutdown()
            server.shutdown()


class TestHeartbeatStop:
    def test_alloc_stopped_after_disconnect(self, tmp_path):
        server = Server(ServerConfig(num_workers=1))
        server.start()
        client = Client(InProcessRPC(server),
                        ClientConfig(data_dir=str(tmp_path)))
        client.start()
        try:
            job = mock.job()
            job.task_groups[0].count = 1
            job.task_groups[0].stop_after_client_disconnect_s = 0.2
            task = job.task_groups[0].tasks[0]
            task.driver = "mock_driver"
            task.config = {"run_for": "120s"}
            server.job_register(job)
            assert _wait(lambda: any(
                tr.task_state.state == "running"
                for ar in client.allocs.values()
                for tr in ar.task_runners.values()), timeout=30)
            # sever the transport: every heartbeat now fails
            def broken(*a, **k):
                raise ConnectionError("network partition")
            client.rpc.update_status = broken
            client.rpc.register_node = broken
            client.last_heartbeat_ok = time.time() - 1.0
            client.heartbeat_ttl = 0.2   # speed the loop up
            ar = next(iter(client.allocs.values()))
            assert _wait(ar.is_done, timeout=15), \
                "alloc not self-stopped after disconnect"
        finally:
            client.shutdown()
            server.shutdown()


class TestAllocWatcher:
    def test_waits_for_previous_and_migrates_disk(self, tmp_path):
        server = Server(ServerConfig(num_workers=1))
        server.start()
        client = Client(InProcessRPC(server),
                        ClientConfig(data_dir=str(tmp_path)))
        client.start()
        try:
            job = mock.job()
            job.task_groups[0].count = 1
            job.task_groups[0].ephemeral_disk = EphemeralDisk(
                sticky=True, migrate=True)
            task = job.task_groups[0].tasks[0]
            task.driver = "mock_driver"
            task.config = {"run_for": "60s"}
            server.job_register(job)
            assert _wait(lambda: any(
                tr.task_state.state == "running"
                for ar in client.allocs.values()
                for tr in ar.task_runners.values()), timeout=30)
            old = next(iter(client.allocs.values()))
            # leave a data file in the shared alloc dir
            marker = os.path.join(old.alloc_dir, "alloc", "data.txt")
            os.makedirs(os.path.dirname(marker), exist_ok=True)
            with open(marker, "w") as f:
                f.write("precious")

            # destructive update -> replacement alloc with
            # previous_allocation pointing at the old one
            job2 = job.copy()
            job2.version = 1
            job2.task_groups[0].tasks[0].env = {"NEW": "1"}
            server.job_register(job2)

            def replacement():
                return next(
                    (a for a in client.allocs.values()
                     if a.alloc.id != old.alloc.id
                     and a.alloc.job_id == job.id), None)
            assert _wait(lambda: replacement() is not None, timeout=30)
            new = replacement()
            assert new.alloc.previous_allocation == old.alloc.id
            assert _wait(lambda: any(
                tr.task_state.state == "running"
                for tr in new.task_runners.values()), timeout=30)
            migrated = os.path.join(new.alloc_dir, "alloc", "data.txt")
            assert _wait(lambda: os.path.exists(migrated)), \
                "ephemeral disk not migrated"
            with open(migrated) as f:
                assert f.read() == "precious"
        finally:
            client.shutdown()
            server.shutdown()


class TestLogMonResume:
    def test_resumes_at_highest_index(self, tmp_path):
        """Agent restart must not interleave new output into old
        rotated files (rotation logic lives in the collector)."""
        import threading

        from nomad_tpu.client.logmon import _Collector

        base = str(tmp_path / "t.stdout")
        with open(f"{base}.0", "wb") as f:
            f.write(b"x" * 200)
        with open(f"{base}.1", "wb") as f:
            f.write(b"y" * 200)
        collector = _Collector(base, max_files=5, max_file_size_mb=1)
        collector.max_bytes = 100
        collector.open()
        t = threading.Thread(target=collector.run, daemon=True)
        t.start()
        try:
            # .1 is already over the threshold -> resumed at .2
            assert collector._idx == 2
            fd = os.open(collector.fifo_path, os.O_WRONLY)
            os.write(fd, b"fresh")
            os.close(fd)
            assert _wait(lambda: os.path.exists(f"{base}.2")
                         and b"fresh" in open(f"{base}.2", "rb").read())
        finally:
            collector.request_stop()
            t.join(timeout=3)
        assert open(f"{base}.0", "rb").read() == b"x" * 200
        assert open(f"{base}.1", "rb").read() == b"y" * 200


class TestAllocWatcherRaces:
    def test_stop_during_wait_prevents_task_start(self, tmp_path):
        """An alloc stopped while awaiting its predecessor must never
        start tasks."""
        from nomad_tpu.client.alloc_runner import AllocRunner
        from nomad_tpu.drivers import builtin_drivers
        import threading

        job = mock.job()
        job.task_groups[0].count = 1
        old_alloc = mock.alloc(job=job)
        new_alloc = mock.alloc(job=job)
        new_alloc.previous_allocation = old_alloc.id

        old_runner = AllocRunner(
            alloc=old_alloc, drivers=builtin_drivers(),
            data_dir=str(tmp_path), on_alloc_update=lambda a: None)
        # predecessor never started -> _tasks_started False -> waiter
        # blocks until the successor is stopped
        new_runner = AllocRunner(
            alloc=new_alloc, drivers=builtin_drivers(),
            data_dir=str(tmp_path), on_alloc_update=lambda a: None,
            prev_lookup={old_alloc.id: old_runner}.get)
        t = threading.Thread(target=new_runner.run, daemon=True)
        t.start()
        time.sleep(0.3)
        assert new_runner.task_runners == {}    # still waiting
        new_runner.stop("test stop")
        t.join(timeout=5)
        assert not t.is_alive()
        assert new_runner.task_runners == {}    # never started


class TestAgentConfigFile:
    def test_hcl_config_merge(self, tmp_path):
        (tmp_path / "base.hcl").write_text('''
        name       = "cfg-agent"
        region     = "eu"
        datacenter = "dc9"
        ports { http = 5757 }
        server {
          enabled        = true
          num_schedulers = 3
        }
        client {
          enabled    = true
          node_class = "compute"
          meta { rack = "r4" }
        }
        acl { enabled = true }
        ''')
        (tmp_path / "override.hcl").write_text('region = "ap"')
        cfg = load_config_files([str(tmp_path / "base.hcl"),
                                 str(tmp_path / "override.hcl")])
        assert cfg.name == "cfg-agent"
        assert cfg.region == "ap"            # later file wins
        assert cfg.datacenter == "dc9"
        assert cfg.http_port == 5757
        assert cfg.server_enabled and cfg.client_enabled
        assert cfg.num_schedulers == 3
        assert cfg.node_class == "compute"
        assert cfg.meta == {"rack": "r4"}
        assert cfg.acl_enabled

    def test_json_config_and_directory(self, tmp_path):
        d = tmp_path / "conf.d"
        d.mkdir()
        (d / "01.json").write_text(
            '{"name": "j-agent", "server": {"enabled": true}}')
        (d / "02.hcl").write_text('datacenter = "dcj"')
        cfg = load_config_files([str(d)])
        assert cfg.name == "j-agent"
        assert cfg.server_enabled
        assert cfg.datacenter == "dcj"

    def test_tls_block(self, tmp_path):
        (tmp_path / "tls.hcl").write_text('''
        tls {
          http      = true
          ca_file   = "ca.pem"
          cert_file = "cert.pem"
          key_file  = "key.pem"
          verify_https_client = true
        }
        ''')
        cfg = load_config_files([str(tmp_path / "tls.hcl")])
        assert cfg.tls is not None and cfg.tls.enabled
        assert cfg.tls.verify_https_client
        assert cfg.tls.cert_file == "cert.pem"


class TestTemplateSandbox:
    """template.go:572-601 escapingfs sandbox (CVE-2022-24683 class):
    jobspec-controlled template paths must not escape the task dir."""

    def test_dest_escape_rejected(self, tmp_path):
        from nomad_tpu.client.task_runner import TaskRunner

        task_dir = tmp_path / "task"
        task_dir.mkdir()
        with pytest.raises(PermissionError):
            TaskRunner._sandboxed_path(str(task_dir), "../../etc/cron.d/x")

    def test_symlink_escape_rejected(self, tmp_path):
        from nomad_tpu.client.task_runner import TaskRunner

        task_dir = tmp_path / "task"
        (task_dir / "local").mkdir(parents=True)
        (task_dir / "local" / "link").symlink_to("/etc")
        with pytest.raises(PermissionError):
            TaskRunner._sandboxed_path(str(task_dir), "local/link/passwd")

    def test_normal_paths_allowed(self, tmp_path):
        from nomad_tpu.client.task_runner import TaskRunner

        task_dir = tmp_path / "task"
        task_dir.mkdir()
        got = TaskRunner._sandboxed_path(str(task_dir), "local/config.txt")
        assert got == os.path.realpath(
            os.path.join(str(task_dir), "local/config.txt"))
        # absolute jobspec paths are re-rooted, not trusted
        got = TaskRunner._sandboxed_path(str(task_dir), "/secrets/creds")
        assert got.startswith(os.path.realpath(str(task_dir)))

    def test_shared_alloc_dir_allowed(self, tmp_path):
        """The sandbox root is the alloc dir, so templates may target
        the shared ../alloc dir (reference alloc-dir escapingfs root)."""
        from nomad_tpu.client.task_runner import TaskRunner

        task_dir = tmp_path / "task"
        (tmp_path / "alloc").mkdir()
        task_dir.mkdir()
        got = TaskRunner._sandboxed_path(
            str(task_dir), "../alloc/data/config.json")
        assert got == os.path.realpath(
            os.path.join(str(tmp_path), "alloc/data/config.json"))
