"""Agent monitor / pprof / debug bundle tests.

Modeled on reference command/agent/monitor/monitor_test.go and
agent_endpoint_test.go pprof coverage.
"""

import logging
import threading
import time

from nomad_tpu.api.agent import Agent, AgentConfig
from nomad_tpu.api.client import APIClient
from nomad_tpu.utils.monitor import (
    LogMonitor,
    heap_summary,
    sample_profile,
    thread_dump,
)


class TestLogMonitor:
    def test_subscribe_receives_lines(self):
        mon = LogMonitor.install()
        q = mon.subscribe("info")
        try:
            logging.getLogger("nomad_tpu.test").warning("hello-monitor")
            level, line = q.get(timeout=2)
            assert "hello-monitor" in line
        finally:
            mon.unsubscribe(q)

    def test_level_filter_in_stream(self):
        mon = LogMonitor.install()
        stop = threading.Event()
        got = []

        def consume():
            for line in mon.stream("error", stop):
                if line:
                    got.append(line)
                    stop.set()

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        time.sleep(0.1)
        logging.getLogger("nomad_tpu.test").info("below-threshold")
        logging.getLogger("nomad_tpu.test").error("boom-error")
        t.join(timeout=5)
        assert got and "boom-error" in got[0]
        assert all("below-threshold" not in l for l in got)

    def test_info_records_pass_root_level_gate(self):
        """Regression: the unconfigured root logger gates at WARNING;
        subscribing at info must lower it so LOG.info lines stream,
        and restore it once the last subscriber leaves."""
        mon = LogMonitor.install()
        root = logging.getLogger()
        before = root.level
        q = mon.subscribe("info")
        try:
            logging.getLogger("nomad_tpu.core_sched").info("info-visible")
            level, line = q.get(timeout=2)
            assert "info-visible" in line
        finally:
            mon.unsubscribe(q)
        assert root.level == before


class TestProfiles:
    def test_thread_dump_contains_main(self):
        dump = thread_dump()
        assert "MainThread" in dump
        assert "test_thread_dump_contains_main" in dump

    def test_sample_profile(self):
        done = threading.Event()

        def spin():
            while not done.is_set():
                sum(range(1000))

        t = threading.Thread(target=spin, name="spinner", daemon=True)
        t.start()
        try:
            out = sample_profile(seconds=0.3, hz=50)
        finally:
            done.set()
        assert "samples:" in out
        assert "spin" in out

    def test_heap_summary(self):
        out = heap_summary()
        assert "live objects" in out
        assert "dict" in out


class TestHTTP:
    def test_pprof_endpoints(self):
        agent = Agent(AgentConfig(num_schedulers=0))
        agent.start()
        try:
            api = APIClient(agent.http_addr)
            assert "MainThread" in api.agent.pprof("goroutine")
            assert "live objects" in api.agent.pprof("heap")
            assert "samples:" in api.agent.pprof("profile", seconds=1)
        finally:
            agent.shutdown()

    def test_monitor_streams_logs(self):
        agent = Agent(AgentConfig(num_schedulers=0))
        agent.start()
        try:
            api = APIClient(agent.http_addr)
            lines = []

            def consume():
                # keep draining until OUR marker arrives: under a full
                # suite run, stray daemon threads from earlier tests
                # can log a warning first, and stopping at the first
                # line then misses the marker (observed flake)
                for line in api.agent.monitor(log_level="warning",
                                              timeout=10):
                    lines.append(line)
                    if "stream-me-now" in line:
                        return

            t = threading.Thread(target=consume, daemon=True)
            t.start()
            time.sleep(0.3)
            logging.getLogger("nomad_tpu.server").warning("stream-me-now")
            t.join(timeout=10)
            assert any("stream-me-now" in line for line in lines), lines
        finally:
            agent.shutdown()
