"""End-to-end scenario suite, driven through the public HTTP API.

Reference behavior: e2e/ runs per-component scenario suites against a
real cluster (affinities, spread, drain, rescheduling, deployments;
e2e/framework). Here the cluster is one in-process agent
(server+client) plus a second client node, and every action goes
through the HTTP API the way an operator's CLI would.
"""

import time

import pytest

from nomad_tpu import mock
from nomad_tpu.api.agent import Agent, AgentConfig
from nomad_tpu.api.client import APIClient
from nomad_tpu.api.codec import encode
from nomad_tpu.client.client import Client, ClientConfig, InProcessRPC
from nomad_tpu.structs import consts
from nomad_tpu.structs.job import UpdateStrategy


def _wait(fn, timeout=30.0, every=0.1):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            if fn():
                return True
        except Exception:                       # noqa: BLE001
            pass
        time.sleep(every)
    return False


@pytest.fixture()
def cluster(tmp_path):
    """server+client agent plus a second client node, HTTP in front."""
    agent = Agent(AgentConfig(name="e2e", num_schedulers=1,
                              client_enabled=True))
    agent.client.config.data_dir = str(tmp_path / "c1")
    agent.start()
    c2 = Client(InProcessRPC(agent.server),
                ClientConfig(data_dir=str(tmp_path / "c2"),
                             datacenter="dc2"))
    c2.start()
    api = APIClient(agent.http_addr)
    assert _wait(lambda: len(api.get("/v1/nodes")) == 2)
    yield agent, c2, api
    c2.shutdown()
    agent.shutdown()


def _service_job(count=2, run_for="120s"):
    job = mock.job()
    job.task_groups[0].count = count
    task = job.task_groups[0].tasks[0]
    task.driver = "mock_driver"
    task.config = {"run_for": run_for}
    return job


def _running(api, job_id):
    return [a for a in api.get(f"/v1/job/{job_id}/allocations")
            if a["ClientStatus"] == "running"]


class TestE2ELifecycle:
    def test_submit_scale_stop_via_http(self, cluster):
        agent, c2, api = cluster
        hcl = '''
        job "http-e2e" {
          datacenters = ["dc1", "dc2"]
          group "app" {
            count = 2
            task "t" {
              driver = "mock_driver"
              config { run_for = "120s" }
            }
          }
        }
        '''
        parsed = api.post("/v1/jobs/parse", {"JobHCL": hcl})
        api.jobs.register(parsed)
        assert _wait(lambda: len(_running(api, "http-e2e")) == 2)

        # scale up through the API
        api.post("/v1/job/http-e2e/scale",
                 {"Target": {"Group": "app"}, "Count": 4})
        assert _wait(lambda: len(_running(api, "http-e2e")) == 4)

        # stop; allocs drain to complete
        api.delete("/v1/job/http-e2e")
        assert _wait(lambda: not _running(api, "http-e2e"))

    def test_failed_task_rescheduled(self, cluster):
        agent, c2, api = cluster
        job = mock.job()
        job.task_groups[0].count = 1
        from nomad_tpu.structs.job import ReschedulePolicy, RestartPolicy
        job.task_groups[0].reschedule_policy = ReschedulePolicy(
            attempts=3, interval_s=300.0, delay_s=0.1,
            delay_function="constant")
        job.task_groups[0].restart_policy = RestartPolicy(
            attempts=0, mode="fail")
        task = job.task_groups[0].tasks[0]
        task.driver = "mock_driver"
        task.config = {"run_for": "0.1s", "exit_code": 1}
        api.jobs.register(encode(job))
        # a replacement allocation appears after the failure
        assert _wait(lambda: len(
            api.get(f"/v1/job/{job.id}/allocations")) >= 2, timeout=40)


class TestE2EDrain:
    def test_drain_migrates_allocs(self, cluster):
        agent, c2, api = cluster
        job = _service_job(count=4)
        job.datacenters = ["dc1", "dc2"]
        api.jobs.register(encode(job))
        assert _wait(lambda: len(_running(api, job.id)) == 4)

        # drain the agent's own node via the API
        node_id = agent.client.node_id
        before = {a["NodeID"] for a in _running(api, job.id)}
        assert node_id in before, "expected allocs on the drained node"
        api.post(f"/v1/node/{node_id}/drain",
                 {"DrainSpec": {"Deadline": 60_000_000_000}})
        # all four end up running on the other node
        assert _wait(lambda: (
            len(_running(api, job.id)) == 4
            and {a["NodeID"] for a in _running(api, job.id)}
            == {c2.node_id}
        ), timeout=60), "drain did not migrate all allocs"


class TestE2EDeployment:
    def test_rolling_update_deployment_succeeds(self, cluster):
        agent, c2, api = cluster
        job = _service_job(count=2)
        job.datacenters = ["dc1", "dc2"]
        job.task_groups[0].update = UpdateStrategy(
            max_parallel=1, min_healthy_time_s=0.1,
            healthy_deadline_s=30.0, canary=0)
        api.jobs.register(encode(job))
        assert _wait(lambda: len(_running(api, job.id)) == 2)

        job2 = job.copy()
        job2.task_groups[0].tasks[0].env = {"V": "2"}
        api.jobs.register(encode(job2))
        # the v1 deployment rolls to successful and both running
        # allocs are on the new version (v0's deployment also reads
        # "successful"; key on JobVersion)
        def rollout_done():
            deps = api.get(f"/v1/job/{job.id}/deployments")
            ok = any(d.get("Status") == "successful"
                     and d.get("JobVersion") == 1 for d in deps)
            allocs = _running(api, job.id)
            return ok and len(allocs) == 2 and \
                all(a["JobVersion"] == 1 for a in allocs)
        assert _wait(rollout_done, timeout=60), (
            api.get(f"/v1/job/{job.id}/deployments"),
            _running(api, job.id))


class TestE2EPlacement:
    def test_datacenter_spread(self, cluster):
        agent, c2, api = cluster
        from nomad_tpu.structs.constraints import Spread, SpreadTarget
        job = _service_job(count=4)
        job.datacenters = ["dc1", "dc2"]
        job.spreads = [Spread(
            attribute="${node.datacenter}", weight=100,
            spread_target=[SpreadTarget(value="dc1", percent=50),
                           SpreadTarget(value="dc2", percent=50)])]
        api.jobs.register(encode(job))
        assert _wait(lambda: len(_running(api, job.id)) == 4)
        by_node = {}
        for a in _running(api, job.id):
            by_node[a["NodeID"]] = by_node.get(a["NodeID"], 0) + 1
        assert sorted(by_node.values()) == [2, 2], by_node

    def test_constraint_pins_datacenter(self, cluster):
        agent, c2, api = cluster
        from nomad_tpu.structs.constraints import Constraint
        job = _service_job(count=2)
        job.datacenters = ["dc1", "dc2"]
        job.constraints = [Constraint(
            ltarget="${node.datacenter}", operand="=", rtarget="dc2")]
        api.jobs.register(encode(job))
        assert _wait(lambda: len(_running(api, job.id)) == 2)
        assert all(a["NodeID"] == c2.node_id
                   for a in _running(api, job.id))


class TestE2EDisconnectedClients:
    """e2e/disconnectedclients: a partitioned client's allocs go
    'unknown' under max_client_disconnect (no premature replacement),
    reconcile back on reconnect, and are LOST + replaced without it."""

    def test_max_client_disconnect_rides_out_partition(self, cluster):
        agent, c2, api = cluster
        job = _service_job(count=1)
        job.datacenters = ["dc2"]           # pin to the partition victim
        from nomad_tpu.structs.constraints import Constraint
        job.constraints = [Constraint(
            ltarget="${node.datacenter}", operand="=", rtarget="dc2")]
        job.task_groups[0].max_client_disconnect_s = 60.0
        api.jobs.register(encode(job))
        assert _wait(lambda: len(_running(api, job.id)) == 1)
        alloc_id = _running(api, job.id)[0]["ID"]

        # partition: heartbeats stop, tasks keep running
        c2.partition_heartbeats = True
        assert _wait(lambda: api.get(f"/v1/node/{c2.node_id}")["Status"]
                     == consts.NODE_STATUS_DISCONNECTED, timeout=40), \
            api.get(f"/v1/node/{c2.node_id}")["Status"]
        assert _wait(lambda: api.get(f"/v1/allocation/{alloc_id}")
                     ["ClientStatus"] == consts.ALLOC_CLIENT_UNKNOWN,
                     timeout=30)
        # crucially: no replacement was scheduled inside the window
        allocs = api.get(f"/v1/job/{job.id}/allocations")
        assert len(allocs) == 1, allocs

        # heal the partition: the SAME alloc reconnects
        c2.partition_heartbeats = False
        assert _wait(lambda: api.get(f"/v1/allocation/{alloc_id}")
                     ["ClientStatus"] == "running", timeout=40)
        assert _wait(lambda: api.get(f"/v1/node/{c2.node_id}")["Status"]
                     == consts.NODE_STATUS_READY, timeout=30)

        # the reconciler keeps exactly the reconnecting alloc running;
        # any replacement it scheduled during the window is stopped
        # (its row remains in state as history — reference semantics)
        def reconciled():
            allocs = api.get(f"/v1/job/{job.id}/allocations")
            running = [a for a in allocs
                       if a["ClientStatus"] == "running"
                       and a["DesiredStatus"] == "run"]
            others_stopped = all(
                a["DesiredStatus"] in ("stop", "evict")
                for a in allocs if a["ID"] != alloc_id)
            return (len(running) == 1 and running[0]["ID"] == alloc_id
                    and others_stopped)
        assert _wait(reconciled, timeout=40), \
            api.get(f"/v1/job/{job.id}/allocations")

    def test_lost_client_without_window_is_replaced(self, cluster):
        agent, c2, api = cluster
        job = _service_job(count=1)
        job.datacenters = ["dc1", "dc2"]
        from nomad_tpu.structs.constraints import Constraint
        job.constraints = [Constraint(
            ltarget="${node.datacenter}", operand="=", rtarget="dc2")]
        api.jobs.register(encode(job))
        assert _wait(lambda: len(_running(api, job.id)) == 1)
        old = _running(api, job.id)[0]["ID"]

        # retarget so the replacement has somewhere to go, then drop c2
        job2 = job.copy()
        job2.constraints = []
        api.jobs.register(encode(job2))
        assert _wait(lambda: len(_running(api, job2.id)) == 1, timeout=30)
        c2.partition_heartbeats = True
        # node goes down; the alloc is lost and replaced on the agent node
        assert _wait(lambda: any(
            a["ID"] != old and a["ClientStatus"] == "running"
            and a["NodeID"] == agent.client.node_id
            for a in api.get(f"/v1/job/{job.id}/allocations")), timeout=60), \
            api.get(f"/v1/job/{job.id}/allocations")
        c2.partition_heartbeats = False


class TestE2EPreemption:
    def test_high_priority_service_preempts_under_pressure(self, cluster):
        agent, c2, api = cluster
        # enable service preemption through the operator API
        cfg = api.get("/v1/operator/scheduler/configuration")
        cfg["SchedulerConfig"]["PreemptionConfig"]["ServiceSchedulerEnabled"] = True
        api.put("/v1/operator/scheduler/configuration",
                cfg["SchedulerConfig"])

        # size the ballast from the FINGERPRINTED capacity (the e2e
        # clients report the real host, not mock numbers)
        node = api.get(f"/v1/node/{agent.client.node_id}")
        cap_cpu = node["NodeResources"]["CPU"]["CPUShares"]
        cap_mem = node["NodeResources"]["Memory"]["MemoryMB"]

        # fill BOTH nodes with low-priority ballast
        filler = _service_job(count=2)
        filler.priority = 10
        filler.datacenters = ["dc1", "dc2"]
        t = filler.task_groups[0].tasks[0]
        t.resources.cpu = int(cap_cpu * 0.8)
        t.resources.memory_mb = int(cap_mem * 0.8)
        api.jobs.register(encode(filler))
        assert _wait(lambda: len(_running(api, filler.id)) == 2, timeout=40)

        # the high-priority job must evict ballast to place
        vip = _service_job(count=1)
        vip.priority = 90
        vip.datacenters = ["dc1", "dc2"]
        vt = vip.task_groups[0].tasks[0]
        vt.resources.cpu = int(cap_cpu * 0.5)
        vt.resources.memory_mb = int(cap_mem * 0.5)
        api.jobs.register(encode(vip))
        assert _wait(lambda: len(_running(api, vip.id)) == 1, timeout=60), \
            api.get(f"/v1/job/{vip.id}/allocations")
        # at least one ballast alloc was evicted (desired status evict)
        evicted = [a for a in api.get(f"/v1/job/{filler.id}/allocations")
                   if a["DesiredStatus"] == consts.ALLOC_DESIRED_EVICT]
        assert evicted, "no ballast alloc was preempted"


class TestE2ECSI:
    def test_csi_volume_gates_placement_and_releases_claims(self, cluster):
        agent, c2, api = cluster
        from nomad_tpu.structs import csi as csi_structs

        # only c2 fingerprints the plugin: placement must follow it
        c2.node.csi_node_plugins = {
            "plug-e2e": {"provider": "e2e.csi", "version": "1",
                         "healthy": True}}
        c2.rpc.register_node(c2.node)
        api.put("/v1/volumes", {"Volumes": [{
            "ID": "vol-e2e", "Namespace": "default", "Name": "vol-e2e",
            "ExternalID": "ext-1", "PluginID": "plug-e2e",
            "RequestedCapabilities": [{
                "AccessMode": csi_structs.ACCESS_MODE_SINGLE_NODE_WRITER,
                "AttachmentMode": csi_structs.ATTACHMENT_MODE_FS}],
        }]})
        vols = api.get("/v1/volumes")
        assert any(v["ID"] == "vol-e2e" for v in vols)

        from nomad_tpu.structs.job import VolumeRequest
        job = _service_job(count=1)
        job.datacenters = ["dc1", "dc2"]
        job.task_groups[0].volumes = {
            "data": VolumeRequest(name="data", type="csi",
                                  source="vol-e2e")}
        api.jobs.register(encode(job))
        assert _wait(lambda: len(_running(api, job.id)) == 1, timeout=40)
        assert _running(api, job.id)[0]["NodeID"] == c2.node_id, \
            "placement ignored the CSI plugin constraint"

        # lifecycle: stop the job; claims drain and the volume can be
        # deregistered through the public API
        api.delete(f"/v1/job/{job.id}")
        assert _wait(lambda: not _running(api, job.id))

        def dereg_ok():
            api.delete("/v1/volume/csi/vol-e2e")
            return all(v["ID"] != "vol-e2e"
                       for v in api.get("/v1/volumes"))
        assert _wait(dereg_ok, timeout=40)


class TestE2EOversubscription:
    def test_memory_max_rides_allocs_only_when_enabled(self, cluster):
        agent, c2, api = cluster
        job = _service_job(count=1)
        t = job.task_groups[0].tasks[0]
        t.resources.memory_mb = 64
        t.resources.memory_max_mb = 512
        api.jobs.register(encode(job))
        assert _wait(lambda: len(_running(api, job.id)) == 1)
        a = api.get(f"/v1/allocation/{_running(api, job.id)[0]['ID']}")
        got = a["AllocatedResources"]["Tasks"]["web"]["Memory"]
        assert got["MemoryMaxMB"] == 0, got     # disabled by default

        cfg = api.get("/v1/operator/scheduler/configuration")
        cfg["SchedulerConfig"]["MemoryOversubscriptionEnabled"] = True
        api.put("/v1/operator/scheduler/configuration",
                cfg["SchedulerConfig"])
        job2 = _service_job(count=1)
        t2 = job2.task_groups[0].tasks[0]
        t2.resources.memory_mb = 64
        t2.resources.memory_max_mb = 512
        api.jobs.register(encode(job2))
        assert _wait(lambda: len(_running(api, job2.id)) == 1)
        a2 = api.get(f"/v1/allocation/{_running(api, job2.id)[0]['ID']}")
        got2 = a2["AllocatedResources"]["Tasks"]["web"]["Memory"]
        assert got2["MemoryMaxMB"] == 512, got2


class TestE2EBlockedEvals:
    def test_blocked_job_unblocks_when_capacity_frees(self, cluster):
        agent, c2, api = cluster
        # ballast consumes nearly everything on both nodes
        node = api.get(f"/v1/node/{agent.client.node_id}")
        cap_cpu = node["NodeResources"]["CPU"]["CPUShares"]
        cap_mem = node["NodeResources"]["Memory"]["MemoryMB"]
        filler = _service_job(count=2)
        filler.datacenters = ["dc1", "dc2"]
        ft = filler.task_groups[0].tasks[0]
        ft.resources.cpu = int(cap_cpu * 0.8)
        ft.resources.memory_mb = int(cap_mem * 0.8)
        api.jobs.register(encode(filler))
        assert _wait(lambda: len(_running(api, filler.id)) == 2, timeout=40)

        big = _service_job(count=1)
        big.datacenters = ["dc1", "dc2"]
        bt = big.task_groups[0].tasks[0]
        bt.resources.cpu = int(cap_cpu * 0.5)
        bt.resources.memory_mb = int(cap_mem * 0.5)
        api.jobs.register(encode(big))
        # blocked, not placed
        assert _wait(lambda: any(
            e["Status"] == consts.EVAL_STATUS_BLOCKED
            for e in api.get(f"/v1/job/{big.id}/evaluations")), timeout=30)
        assert not _running(api, big.id)

        # free capacity: the blocked eval unblocks and places
        api.delete(f"/v1/job/{filler.id}")
        assert _wait(lambda: len(_running(api, big.id)) == 1, timeout=60), \
            api.get(f"/v1/job/{big.id}/evaluations")


class TestE2EPeriodicAndDispatch:
    def test_periodic_job_forced_launch(self, cluster):
        agent, c2, api = cluster
        from nomad_tpu.structs.job import PeriodicConfig
        job = _service_job(count=1, run_for="0.2s")
        job.type = consts.JOB_TYPE_BATCH
        job.periodic = PeriodicConfig(enabled=True, spec="0 3 * * *",
                                      spec_type="cron")
        api.jobs.register(encode(job))
        # the parent never runs; a forced launch creates a child
        from urllib.parse import quote
        api.post(f"/v1/job/{job.id}/periodic/force", {})
        def child_done():
            kids = [j for j in api.get("/v1/jobs")
                    if j["ID"].startswith(job.id + "/periodic-")]
            return kids and any(
                a["ClientStatus"] == "complete"
                for k in kids
                for a in api.get(
                    f"/v1/job/{quote(k['ID'], safe='')}/allocations"))
        assert _wait(child_done, timeout=40)

    def test_parameterized_dispatch(self, cluster):
        agent, c2, api = cluster
        from nomad_tpu.structs.job import ParameterizedJobConfig
        job = _service_job(count=1, run_for="0.2s")
        job.type = consts.JOB_TYPE_BATCH
        job.parameterized = ParameterizedJobConfig(
            payload="optional", meta_optional=["color"])
        api.jobs.register(encode(job))
        resp = api.post(f"/v1/job/{job.id}/dispatch",
                        {"Meta": {"color": "green"}})
        from urllib.parse import quote
        child = quote(resp["DispatchedJobID"], safe="")
        assert _wait(lambda: any(
            a["ClientStatus"] == "complete"
            for a in api.get(f"/v1/job/{child}/allocations")), timeout=40)


class TestE2ESystem:
    def test_system_job_covers_every_eligible_node(self, cluster):
        agent, c2, api = cluster
        job = mock.system_job()
        job.datacenters = ["dc1", "dc2"]
        job.constraints = []
        t = job.task_groups[0].tasks[0]
        t.driver = "mock_driver"
        t.config = {"run_for": "120s"}
        api.jobs.register(encode(job))
        assert _wait(lambda: {a["NodeID"] for a in _running(api, job.id)}
                     == {agent.client.node_id, c2.node_id}, timeout=40), \
            _running(api, job.id)


class TestE2EConnect:
    def test_sidecar_service_gets_mesh_port(self, cluster):
        agent, c2, api = cluster
        import sys as _sys
        from nomad_tpu.structs import NetworkResource, Service
        job = mock.job()
        job.constraints = []
        tg = job.task_groups[0]
        tg.count = 1
        tg.networks = [NetworkResource(mode="bridge")]
        tg.services = [Service(
            name="mesh-api",
            connect={"sidecar_service": {
                "proxy": {"local_service_port": 9901}}})]
        task = tg.tasks[0]
        task.driver = "mock_driver"
        task.config = {"run_for": "120s"}
        api.jobs.register(encode(job))
        assert _wait(lambda: len(_running(api, job.id)) == 1, timeout=40)
        a = api.get(f"/v1/allocation/{_running(api, job.id)[0]['ID']}")
        ports = (a["AllocatedResources"]["Shared"] or {}).get("Ports") or []
        mesh = [p for p in ports
                if p.get("Label") == "connect-proxy-mesh-api"]
        assert mesh and mesh[0]["Value"] > 0, ports
