"""End-to-end scenario suite, driven through the public HTTP API.

Reference behavior: e2e/ runs per-component scenario suites against a
real cluster (affinities, spread, drain, rescheduling, deployments;
e2e/framework). Here the cluster is one in-process agent
(server+client) plus a second client node, and every action goes
through the HTTP API the way an operator's CLI would.
"""

import time

import pytest

from nomad_tpu import mock
from nomad_tpu.api.agent import Agent, AgentConfig
from nomad_tpu.api.client import APIClient
from nomad_tpu.api.codec import encode
from nomad_tpu.client.client import Client, ClientConfig, InProcessRPC
from nomad_tpu.structs import consts
from nomad_tpu.structs.job import UpdateStrategy


def _wait(fn, timeout=30.0, every=0.1):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            if fn():
                return True
        except Exception:                       # noqa: BLE001
            pass
        time.sleep(every)
    return False


@pytest.fixture()
def cluster(tmp_path):
    """server+client agent plus a second client node, HTTP in front."""
    agent = Agent(AgentConfig(name="e2e", num_schedulers=1,
                              client_enabled=True))
    agent.client.config.data_dir = str(tmp_path / "c1")
    agent.start()
    c2 = Client(InProcessRPC(agent.server),
                ClientConfig(data_dir=str(tmp_path / "c2"),
                             datacenter="dc2"))
    c2.start()
    api = APIClient(agent.http_addr)
    assert _wait(lambda: len(api.get("/v1/nodes")) == 2)
    yield agent, c2, api
    c2.shutdown()
    agent.shutdown()


def _service_job(count=2, run_for="120s"):
    job = mock.job()
    job.task_groups[0].count = count
    task = job.task_groups[0].tasks[0]
    task.driver = "mock_driver"
    task.config = {"run_for": run_for}
    return job


def _running(api, job_id):
    return [a for a in api.get(f"/v1/job/{job_id}/allocations")
            if a["ClientStatus"] == "running"]


class TestE2ELifecycle:
    def test_submit_scale_stop_via_http(self, cluster):
        agent, c2, api = cluster
        hcl = '''
        job "http-e2e" {
          datacenters = ["dc1", "dc2"]
          group "app" {
            count = 2
            task "t" {
              driver = "mock_driver"
              config { run_for = "120s" }
            }
          }
        }
        '''
        parsed = api.post("/v1/jobs/parse", {"JobHCL": hcl})
        api.jobs.register(parsed)
        assert _wait(lambda: len(_running(api, "http-e2e")) == 2)

        # scale up through the API
        api.post("/v1/job/http-e2e/scale",
                 {"Target": {"Group": "app"}, "Count": 4})
        assert _wait(lambda: len(_running(api, "http-e2e")) == 4)

        # stop; allocs drain to complete
        api.delete("/v1/job/http-e2e")
        assert _wait(lambda: not _running(api, "http-e2e"))

    def test_failed_task_rescheduled(self, cluster):
        agent, c2, api = cluster
        job = mock.job()
        job.task_groups[0].count = 1
        from nomad_tpu.structs.job import ReschedulePolicy, RestartPolicy
        job.task_groups[0].reschedule_policy = ReschedulePolicy(
            attempts=3, interval_s=300.0, delay_s=0.1,
            delay_function="constant")
        job.task_groups[0].restart_policy = RestartPolicy(
            attempts=0, mode="fail")
        task = job.task_groups[0].tasks[0]
        task.driver = "mock_driver"
        task.config = {"run_for": "0.1s", "exit_code": 1}
        api.jobs.register(encode(job))
        # a replacement allocation appears after the failure
        assert _wait(lambda: len(
            api.get(f"/v1/job/{job.id}/allocations")) >= 2, timeout=40)


class TestE2EDrain:
    def test_drain_migrates_allocs(self, cluster):
        agent, c2, api = cluster
        job = _service_job(count=4)
        job.datacenters = ["dc1", "dc2"]
        api.jobs.register(encode(job))
        assert _wait(lambda: len(_running(api, job.id)) == 4)

        # drain the agent's own node via the API
        node_id = agent.client.node_id
        before = {a["NodeID"] for a in _running(api, job.id)}
        assert node_id in before, "expected allocs on the drained node"
        api.post(f"/v1/node/{node_id}/drain",
                 {"DrainSpec": {"Deadline": 60_000_000_000}})
        # all four end up running on the other node
        assert _wait(lambda: (
            len(_running(api, job.id)) == 4
            and {a["NodeID"] for a in _running(api, job.id)}
            == {c2.node_id}
        ), timeout=60), "drain did not migrate all allocs"


class TestE2EDeployment:
    def test_rolling_update_deployment_succeeds(self, cluster):
        agent, c2, api = cluster
        job = _service_job(count=2)
        job.datacenters = ["dc1", "dc2"]
        job.task_groups[0].update = UpdateStrategy(
            max_parallel=1, min_healthy_time_s=0.1,
            healthy_deadline_s=30.0, canary=0)
        api.jobs.register(encode(job))
        assert _wait(lambda: len(_running(api, job.id)) == 2)

        job2 = job.copy()
        job2.task_groups[0].tasks[0].env = {"V": "2"}
        api.jobs.register(encode(job2))
        # the v1 deployment rolls to successful and both running
        # allocs are on the new version (v0's deployment also reads
        # "successful"; key on JobVersion)
        def rollout_done():
            deps = api.get(f"/v1/job/{job.id}/deployments")
            ok = any(d.get("Status") == "successful"
                     and d.get("JobVersion") == 1 for d in deps)
            allocs = _running(api, job.id)
            return ok and len(allocs) == 2 and \
                all(a["JobVersion"] == 1 for a in allocs)
        assert _wait(rollout_done, timeout=60), (
            api.get(f"/v1/job/{job.id}/deployments"),
            _running(api, job.id))


class TestE2EPlacement:
    def test_datacenter_spread(self, cluster):
        agent, c2, api = cluster
        from nomad_tpu.structs.constraints import Spread, SpreadTarget
        job = _service_job(count=4)
        job.datacenters = ["dc1", "dc2"]
        job.spreads = [Spread(
            attribute="${node.datacenter}", weight=100,
            spread_target=[SpreadTarget(value="dc1", percent=50),
                           SpreadTarget(value="dc2", percent=50)])]
        api.jobs.register(encode(job))
        assert _wait(lambda: len(_running(api, job.id)) == 4)
        by_node = {}
        for a in _running(api, job.id):
            by_node[a["NodeID"]] = by_node.get(a["NodeID"], 0) + 1
        assert sorted(by_node.values()) == [2, 2], by_node

    def test_constraint_pins_datacenter(self, cluster):
        agent, c2, api = cluster
        from nomad_tpu.structs.constraints import Constraint
        job = _service_job(count=2)
        job.datacenters = ["dc1", "dc2"]
        job.constraints = [Constraint(
            ltarget="${node.datacenter}", operand="=", rtarget="dc2")]
        api.jobs.register(encode(job))
        assert _wait(lambda: len(_running(api, job.id)) == 2)
        assert all(a["NodeID"] == c2.node_id
                   for a in _running(api, job.id))
