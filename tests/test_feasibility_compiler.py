"""Feasibility compiler (ISSUE 5): compiled mask programs must be
bit-identical to the Python ``FeasibilityBuilder.base_mask`` — over
randomized constraint trees (regex / version / semver / set_contains /
is_set / distinct / DC globs / drivers / volumes), randomized node
populations, node-structure forks, evicted cache generations, and the
escaped-constraint fallback. Metrics tallies and class-eligibility
memoization must replay identically too, because blocked evals and
AllocMetric surface them to operators.
"""

import random
import types

import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.feasibility import (
    apply_program,
    compile_program,
    default_attr_plane_cache,
    default_mask_cache,
)
from nomad_tpu.scheduler.context import EvalContext
from nomad_tpu.scheduler.feasible import FeasibilityBuilder
from nomad_tpu import structs
from nomad_tpu.structs import consts
from nomad_tpu.structs.node import HostVolumeConfig
from nomad_tpu.structs.constraints import Constraint
from nomad_tpu.structs.eval_plan import Plan
from nomad_tpu.tensors.schema import ClusterTensors


@pytest.fixture(autouse=True)
def _fresh_caches():
    """Each test starts from empty feasibility caches (they are
    process-wide by design)."""
    default_mask_cache._programs.clear()
    default_mask_cache._masks.clear()
    default_mask_cache._canonical.clear()
    default_mask_cache.reset_stats()
    default_attr_plane_cache._entries.clear()
    default_attr_plane_cache._latest.clear()
    yield


class _Snap:
    """Minimal scheduler snapshot: node lookups only."""

    def __init__(self, nodes):
        self._nodes = {n.id: n for n in nodes}
        self.usage = None

    def node_by_id(self, nid):
        return self._nodes.get(nid)


def _usage_stub(uid="u1", sv=1, node_events=()):
    return types.SimpleNamespace(
        uid=uid, structure_version=sv, version=sv,
        node_events=tuple(node_events), row_events=(),
        row_events_floor=0)


_DCS = ["dc1", "dc2", "east-1", "east-2", "west-1"]
_KERNELS = ["linux", "windows", "freebsd"]
_VERSIONS = ["1.2.3", "1.10.0", "2.0.0-beta.1", "0.9", "3.4.5+build7",
             "not-a-version"]
_RACKS = ["r1", "r2", "r3", None]


def _rand_node(rng):
    n = mock.node()
    n.datacenter = rng.choice(_DCS)
    n.node_class = rng.choice(["", "c1", "c2"])
    n.node_pool = rng.choice(["default", "gpu"])
    n.attributes = dict(n.attributes)
    n.attributes["kernel.name"] = rng.choice(_KERNELS)
    n.attributes["nomad.version"] = rng.choice(_VERSIONS)
    n.attributes["cpu.features"] = rng.choice(
        ["sse4,avx", "sse4,avx,avx2", "sse4"])
    rack = rng.choice(_RACKS)
    n.meta = dict(n.meta or {})
    if rack is not None:
        n.meta["rack"] = rack
    if rng.random() < 0.3:
        n.attributes["unique.hostname"] = f"host-{rng.randrange(1000)}"
    # driver health varies (part of the computed class hash)
    if rng.random() < 0.2:
        n.drivers = dict(n.drivers)
        n.drivers["mock_driver"] = structs.DriverInfo(
            detected=True, healthy=False)
    if rng.random() < 0.3:
        n.host_volumes = {
            "fast-disk": HostVolumeConfig(
                name="fast-disk", path="/mnt/fast",
                read_only=rng.random() < 0.5),
        }
    if rng.random() < 0.2:
        n.csi_node_plugins = {"ebs0": {"healthy": True}}
    if rng.random() < 0.2:
        n.status = consts.NODE_STATUS_DOWN
    n.compute_class()
    return n


def _rand_constraints(rng, allow_escaped=True):
    pool = [
        Constraint("${attr.kernel.name}", rng.choice(_KERNELS), "="),
        Constraint("${attr.kernel.name}", rng.choice(_KERNELS), "!="),
        Constraint("${attr.nomad.version}", ">= 1.0, < 3.0",
                   consts.CONSTRAINT_VERSION),
        Constraint("${attr.nomad.version}", ">= 1.2.0",
                   consts.CONSTRAINT_SEMVER),
        Constraint("${attr.kernel.name}", "lin.*",
                   consts.CONSTRAINT_REGEX),
        Constraint("${attr.cpu.features}", "avx",
                   consts.CONSTRAINT_SET_CONTAINS),
        Constraint("${meta.rack}", "", consts.CONSTRAINT_ATTRIBUTE_IS_SET),
        Constraint("${meta.rack}", "",
                   consts.CONSTRAINT_ATTRIBUTE_IS_NOT_SET),
        Constraint("${node.datacenter}", rng.choice(_DCS), "="),
        Constraint("${node.class}", "c1", "!="),
        Constraint("${meta.rack}", "r2", "<="),
    ]
    if allow_escaped:
        pool.append(Constraint("${attr.unique.hostname}", "host-1", "!="))
        pool.append(Constraint("${node.unique.name}", "foo.*",
                               consts.CONSTRAINT_REGEX))
    k = rng.randrange(0, 4)
    return [rng.choice(pool).copy() for _ in range(k)]


def _rand_job(rng, allow_escaped=True):
    job = mock.job()
    job.datacenters = rng.choice([
        ["dc1"], ["dc1", "dc2"], ["east-*"], ["*"], _DCS,
    ])
    job.node_pool = rng.choice(["default", "all", "gpu"])
    job.constraints = _rand_constraints(rng, allow_escaped)
    tg = job.task_groups[0]
    tg.constraints = _rand_constraints(rng, allow_escaped)
    tg.tasks[0].constraints = _rand_constraints(rng, allow_escaped)
    tg.tasks[0].driver = rng.choice(["exec", "mock_driver"])
    if rng.random() < 0.3:
        job.constraints.append(
            Constraint("", "", consts.CONSTRAINT_DISTINCT_HOSTS))
    if rng.random() < 0.3:
        tg.constraints.append(
            Constraint("${meta.rack}", rng.choice(["", "2"]),
                       consts.CONSTRAINT_DISTINCT_PROPERTY))
    if rng.random() < 0.3:
        tg.volumes = {"v0": structs.VolumeRequest(
            name="v0", type="host", source="fast-disk",
            read_only=rng.random() < 0.5)}
    elif rng.random() < 0.2:
        tg.volumes = {"v0": structs.VolumeRequest(
            name="v0", type="csi", source="ebs0", read_only=True)}
    return job, tg


def _rand_allocs_by_node(rng, job, tg, nodes):
    out = {}
    for n in nodes:
        if rng.random() < 0.15:
            a = mock.alloc(job=job, node=n) if hasattr(mock, "alloc") \
                else None
            if a is None:
                a = structs_alloc(job, tg, n)
            out.setdefault(n.id, []).append(a)
    return out


def structs_alloc(job, tg, node):
    from nomad_tpu.structs.alloc import Allocation

    return Allocation(
        id=f"a-{node.id[:8]}-{random.randrange(1 << 30)}",
        namespace=job.namespace, job_id=job.id, job=job,
        task_group=tg.name, node_id=node.id,
        desired_status=consts.ALLOC_DESIRED_RUN,
        client_status=consts.ALLOC_CLIENT_RUNNING,
    )


def _python_mask(cluster, snap, job, tg, allocs_by_node):
    ctx = EvalContext(snap, Plan(job=job))
    ctx.eligibility.set_job(job)
    feas = FeasibilityBuilder(cluster, snap, ctx)
    mask = feas.base_mask(job, tg, allocs_by_node)
    return mask, ctx


def _compiled_mask(cluster, snap, job, tg, allocs_by_node,
                   exclude=None):
    ctx = EvalContext(snap, Plan(job=job))
    ctx.eligibility.set_job(job)
    feas = FeasibilityBuilder(cluster, snap, ctx)
    program = compile_program(job, tg)
    if program is None:
        return None, ctx
    if exclude is None:
        exclude = np.zeros(cluster.n_pad, bool)
    mask = apply_program(program, cluster, snap, ctx, job, tg,
                         allocs_by_node, exclude, feas)
    return mask, ctx


def _assert_identical(cluster, snap, job, tg, allocs_by_node, seed):
    py_mask, py_ctx = _python_mask(cluster, snap, job, tg,
                                   allocs_by_node)
    cp_mask, cp_ctx = _compiled_mask(cluster, snap, job, tg,
                                     allocs_by_node)
    if cp_mask is None:
        # uncompilable tree: the live path falls back to the builder —
        # nothing to compare, but the fallback must be well-formed
        assert compile_program(job, tg) is None
        return False
    assert np.array_equal(py_mask, cp_mask), (
        f"seed={seed}: mask mismatch at rows "
        f"{np.nonzero(py_mask != cp_mask)[0][:8]}")
    pm, cm = py_ctx.metrics_obj, cp_ctx.metrics_obj
    assert pm.nodes_filtered == cm.nodes_filtered, seed
    assert pm.class_filtered == cm.class_filtered, seed
    assert pm.constraint_filtered == cm.constraint_filtered, seed
    assert py_ctx.eligibility.job == cp_ctx.eligibility.job, seed
    assert py_ctx.eligibility.tgs == cp_ctx.eligibility.tgs, seed
    return True


class TestBitIdentity:
    def test_randomized_trees(self):
        compared = 0
        for seed in range(40):
            rng = random.Random(seed)
            nodes = [_rand_node(rng) for _ in range(rng.randrange(5, 40))]
            cluster = ClusterTensors.build(nodes)
            snap = _Snap(nodes)
            job, tg = _rand_job(rng)
            allocs = _rand_allocs_by_node(rng, job, tg, nodes)
            if _assert_identical(cluster, snap, job, tg, allocs, seed):
                compared += 1
        # the sweep must actually exercise the compiled path
        assert compared >= 25

    def test_escaped_trees_stay_identical(self):
        """Unique-property constraints escape the class cache; the
        compiled escaped path (vocabulary LUT per node) must match the
        per-node Python walk."""
        for seed in range(20):
            rng = random.Random(1000 + seed)
            nodes = [_rand_node(rng) for _ in range(20)]
            cluster = ClusterTensors.build(nodes)
            snap = _Snap(nodes)
            job, tg = _rand_job(rng)
            job.constraints.append(
                Constraint("${attr.unique.hostname}", "host-.*",
                           consts.CONSTRAINT_REGEX))
            program = compile_program(job, tg)
            assert program is not None and program.escaped
            _assert_identical(cluster, snap, job, tg, {}, seed)

    def test_pair_rtarget_escape_falls_back(self):
        """An escaped tree whose RIGHT target is a node interpolation
        is the declared fallback case: compile refuses, the live path
        keeps the Python builder."""
        rng = random.Random(7)
        job, tg = _rand_job(rng, allow_escaped=False)
        job.constraints = [
            Constraint("${attr.unique.hostname}", "${node.datacenter}",
                       "!=")]
        assert compile_program(job, tg) is None

    def test_exclude_and_distinct_dynamic_path(self):
        """exclude rows + distinct_hosts force the dynamic epilogue
        (a copy, never the frozen cached array) and stay identical to
        builder + manual exclude."""
        rng = random.Random(11)
        nodes = [_rand_node(rng) for _ in range(24)]
        cluster = ClusterTensors.build(nodes)
        snap = _Snap(nodes)
        job, tg = _rand_job(rng, allow_escaped=False)
        job.constraints = [
            Constraint("", "", consts.CONSTRAINT_DISTINCT_HOSTS)]
        allocs = {nodes[0].id: [structs_alloc(job, tg, nodes[0])]}
        exclude = np.zeros(cluster.n_pad, bool)
        exclude[1] = True
        py_mask, _ = _python_mask(cluster, snap, job, tg, allocs)
        py_mask &= ~exclude
        cp_mask, _ = _compiled_mask(cluster, snap, job, tg, allocs,
                                    exclude=exclude)
        assert cp_mask is not None
        assert cp_mask.flags.writeable       # dynamic path copies
        assert np.array_equal(py_mask, cp_mask)

    def test_static_path_returns_frozen_shared_identity(self):
        """No dynamic state: repeated evals get the SAME frozen array
        (the wave-sharing and device-residency contract)."""
        rng = random.Random(13)
        nodes = [_rand_node(rng) for _ in range(16)]
        cluster = ClusterTensors.build(nodes)
        snap = _Snap(nodes)
        job, tg = _rand_job(rng, allow_escaped=False)
        job.constraints = [Constraint("${attr.kernel.name}", "linux", "=")]
        tg.constraints = []
        tg.tasks[0].constraints = []
        tg.volumes = {}
        m1, _ = _compiled_mask(cluster, snap, job, tg, {})
        m2, _ = _compiled_mask(cluster, snap, job, tg, {})
        assert m1 is not None
        assert m1 is m2
        assert not m1.flags.writeable
        stats = default_mask_cache.snapshot()
        assert stats["hits"] >= 1 and stats["misses"] == 1

    def test_content_dedup_across_equal_specs(self):
        """Two different jobs with equal constraint trees share one
        canonical mask by identity."""
        rng = random.Random(17)
        nodes = [_rand_node(rng) for _ in range(16)]
        cluster = ClusterTensors.build(nodes)
        snap = _Snap(nodes)
        job_a, tg_a = _rand_job(rng, allow_escaped=False)
        job_a.constraints = [Constraint("${attr.kernel.name}", "linux", "=")]
        tg_a.constraints = []
        tg_a.tasks[0].constraints = []
        tg_a.volumes = {}
        job_b = mock.job()
        job_b.datacenters = list(job_a.datacenters)
        job_b.node_pool = job_a.node_pool
        job_b.constraints = [c.copy() for c in job_a.constraints]
        tg_b = job_b.task_groups[0]
        tg_b.constraints = []
        tg_b.tasks[0].constraints = []
        tg_b.tasks[0].driver = tg_a.tasks[0].driver
        tg_b.volumes = {}
        m_a, _ = _compiled_mask(cluster, snap, job_a, tg_a, {})
        m_b, _ = _compiled_mask(cluster, snap, job_b, tg_b, {})
        assert m_a is not None and m_a is m_b


class TestStructureForks:
    def test_fork_reevaluates_and_attr_planes_advance(self):
        """A structure_version bump with a node-change log: masks
        re-evaluate against the new rows; the attr-plane cache
        advances by fork instead of a full rebuild."""
        rng = random.Random(23)
        nodes = [_rand_node(rng) for _ in range(20)]
        cluster = ClusterTensors.build(nodes)
        snap = _Snap(nodes)
        usage = _usage_stub(sv=1)
        snap.usage = usage
        job, tg = _rand_job(rng)
        job.constraints.append(
            Constraint("${attr.unique.hostname}", "host-.*",
                       consts.CONSTRAINT_REGEX))   # force escaped/vocab
        program = compile_program(job, tg)
        if program is None:
            pytest.skip("rolled an uncompilable tree")
        _assert_identical(cluster, snap, job, tg, {}, 23)

        # fork: flip one node's attribute, log it, bump the version
        changed = nodes[3]
        changed.attributes = dict(changed.attributes)
        changed.attributes["kernel.name"] = "windows"
        changed.attributes["unique.hostname"] = "host-777"
        changed.compute_class()
        cluster2 = ClusterTensors.build(nodes)
        snap2 = _Snap(nodes)
        snap2.usage = _usage_stub(sv=2, node_events=((2, changed.id),))
        forks0 = default_attr_plane_cache.forks
        _assert_identical(cluster2, snap2, job, tg, {}, 232)
        assert default_attr_plane_cache.forks == forks0 + 1
        # forked column reflects the new value
        planes = default_attr_plane_cache.get(cluster2, snap2.usage)
        col = planes.column("${attr.kernel.name}")
        row = cluster2.index[changed.id]
        assert col.values[col.codes[row]] == "windows"

    def test_poisoned_log_full_rebuild_still_identical(self):
        rng = random.Random(29)
        nodes = [_rand_node(rng) for _ in range(12)]
        snap = _Snap(nodes)
        snap.usage = _usage_stub(sv=5, node_events=((5, None),))
        cluster = ClusterTensors.build(nodes)
        job, tg = _rand_job(rng)
        _assert_identical(cluster, snap, job, tg, {}, 29)


class TestEviction:
    def test_evicted_mask_generations_reevaluate_identically(self):
        """An LRU-evicted mask entry must re-evaluate bit-identically
        (the 'evicted attr-plane generations' acceptance case)."""
        old_max = default_mask_cache.max_masks
        default_mask_cache.max_masks = 2
        try:
            rng = random.Random(31)
            nodes = [_rand_node(rng) for _ in range(16)]
            cluster = ClusterTensors.build(nodes)
            snap = _Snap(nodes)
            jobs = []
            for i in range(4):
                job, tg = _rand_job(rng, allow_escaped=False)
                job.constraints = [Constraint(
                    "${attr.kernel.name}", _KERNELS[i % 3], "=")]
                tg.constraints = []
                tg.tasks[0].constraints = []
                tg.volumes = {}
                jobs.append((job, tg))
            for job, tg in jobs:
                _compiled_mask(cluster, snap, job, tg, {})
            assert len(default_mask_cache._masks) <= 2
            # the first spec was evicted: a fresh evaluation must match
            # the Python builder exactly
            job, tg = jobs[0]
            _assert_identical(cluster, snap, job, tg, {}, 31)
        finally:
            default_mask_cache.max_masks = old_max


class TestHitRatioAccounting:
    def test_steady_repeat_hits(self):
        rng = random.Random(37)
        nodes = [_rand_node(rng) for _ in range(16)]
        cluster = ClusterTensors.build(nodes)
        snap = _Snap(nodes)
        job, tg = _rand_job(rng, allow_escaped=False)
        job.constraints = [Constraint("${attr.kernel.name}", "linux", "=")]
        tg.constraints = []
        tg.tasks[0].constraints = []
        tg.volumes = {}
        for _ in range(30):
            _compiled_mask(cluster, snap, job, tg, {})
        assert default_mask_cache.hit_ratio() >= 0.95
