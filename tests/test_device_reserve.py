"""Device Reserve wiring: scheduler-assigned devices reach the task
as plugin-provided env (device.proto Reserve -> container env), the
path GPUs/TPUs use to become visible to workloads.
"""

import time

import pytest

from nomad_tpu import mock
from nomad_tpu.client.client import Client, ClientConfig, InProcessRPC
from nomad_tpu.plugins.base import PLUGIN_TYPE_DEVICE, PluginInfo
from nomad_tpu.plugins.device import DevicePlugin, ReservationResponse
from nomad_tpu.server.server import Server, ServerConfig
from nomad_tpu.structs import consts
from nomad_tpu.structs.resources import NodeDeviceResource, RequestedDevice


class FakeGpuPlugin(DevicePlugin):
    def __init__(self):
        self.reserved = []

    def plugin_info(self) -> PluginInfo:
        return PluginInfo(name="gpu", type=PLUGIN_TYPE_DEVICE)

    def fingerprint(self):
        return [NodeDeviceResource(
            vendor="acme", type="gpu", name="a100",
            instance_ids=["gpu-0", "gpu-1"],
        )]

    def reserve(self, device_ids):
        self.reserved.append(list(device_ids))
        visible = ",".join(i.split("-")[-1] for i in device_ids)
        return ReservationResponse(
            container_res={"ACME_VISIBLE_DEVICES": visible})


def _wait(fn, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(0.05)
    return False


class TestDeviceReserve:
    def test_assigned_devices_surface_as_env(self, tmp_path):
        plugin = FakeGpuPlugin()
        server = Server(ServerConfig(num_workers=1))
        server.start()
        client = Client(
            InProcessRPC(server),
            ClientConfig(data_dir=str(tmp_path)),
            device_plugins=[plugin],
        )
        client.start()
        try:
            job = mock.job()
            job.task_groups[0].count = 1
            task = job.task_groups[0].tasks[0]
            task.driver = "mock_driver"
            task.config = {"run_for": "30s"}
            task.resources.devices = [
                RequestedDevice(name="acme/gpu", count=1)]
            server.job_register(job)
            assert _wait(lambda: any(
                tr.task_state.state == "running"
                for ar in client.allocs.values()
                for tr in ar.task_runners.values())), "task never ran"
            assert plugin.reserved, "plugin.reserve never called"
            tr = next(tr for ar in client.allocs.values()
                      for tr in ar.task_runners.values())
            env = tr._task_config().env
            assert env.get("ACME_VISIBLE_DEVICES") in ("0", "1"), env
        finally:
            client.shutdown()
            server.shutdown()
