"""HCL2 evaluation tests: variables, locals, functions, interpolation,
dynamic blocks.

Modeled on reference jobspec2/parse_test.go (variable handling,
functions, dynamic blocks) — the HCL2 features jobspec/parse.go HCL1
lacks.
"""

import pytest

from nomad_tpu.jobspec.eval import EvalError, FUNCS, Scope, eval_expr, evaluate
from nomad_tpu.jobspec.hcl import parse
from nomad_tpu.jobspec.parse import parse_hcl


class TestVariables:
    def test_default_and_override(self):
        src = '''
        variable "region" { default = "us-west" }
        variable "count" { default = 3 }
        job "j" {
          region = var.region
          group "g" { count = var.count }
        }
        '''
        job = parse_hcl(src)
        assert job.region == "us-west"
        assert job.task_groups[0].count == 3
        job2 = parse_hcl(src, {"region": "eu-east", "count": 5})
        assert job2.region == "eu-east"
        assert job2.task_groups[0].count == 5

    def test_missing_value_errors(self):
        src = 'variable "x" {}\njob "j" { region = var.x }'
        with pytest.raises(EvalError):
            parse_hcl(src)

    def test_undeclared_override_errors(self):
        src = 'job "j" {}'
        with pytest.raises(EvalError):
            parse_hcl(src, {"nope": 1})


class TestLocals:
    def test_locals_reference_vars_and_each_other(self):
        src = '''
        variable "env" { default = "prod" }
        locals {
          full    = "${var.env}-cluster"
          shouted = upper(local.full)
        }
        job "j" { region = local.shouted }
        '''
        assert parse_hcl(src).region == "PROD-CLUSTER"

    def test_local_cycle_errors(self):
        src = '''
        locals { a = local.b
                 b = local.a }
        job "j" {}
        '''
        with pytest.raises(EvalError):
            parse_hcl(src)


class TestInterpolation:
    def test_expressions_inside_interpolation(self):
        scope = Scope({"var": {"n": 4, "name": "web"}, "local": {}})
        assert eval_expr("var.n + 2", scope) == 6
        assert eval_expr("var.n * 2 - 1", scope) == 7
        assert eval_expr("var.n > 3 && var.n < 10", scope) is True
        assert eval_expr('var.n == 4 ? "big" : "small"', scope) == "big"
        assert eval_expr('upper(var.name)', scope) == "WEB"
        assert eval_expr('format("%s-%d", var.name, var.n)', scope) == "web-4"

    def test_native_type_for_sole_interpolation(self):
        src = '''
        variable "count" { default = 7 }
        job "j" { group "g" { count = "${var.count}" } }
        '''
        assert parse_hcl(src).task_groups[0].count == 7

    def test_runtime_namespaces_pass_through(self):
        """${attr...} / ${node...} / ${env...} resolve at schedule/run
        time; the parser must keep them literal."""
        src = '''
        job "j" {
          constraint {
            attribute = "${attr.kernel.name}"
            value     = "linux"
          }
          group "g" {
            task "t" {
              driver = "mock"
              env { HOST = "${node.unique.name}" }
            }
          }
        }
        '''
        job = parse_hcl(src)
        assert job.constraints[0].ltarget == "${attr.kernel.name}"
        assert job.task_groups[0].tasks[0].env["HOST"] == \
            "${node.unique.name}"

    def test_indexing(self):
        scope = Scope({"var": {"dcs": ["dc1", "dc2"],
                               "m": {"k": "v"}}, "local": {}})
        assert eval_expr("var.dcs[1]", scope) == "dc2"
        assert eval_expr('var.m["k"]', scope) == "v"


class TestFunctions:
    def test_stdlib_subset(self):
        f = FUNCS
        assert f["join"](",", ["a", "b"]) == "a,b"
        assert f["split"](",", "a,b") == ["a", "b"]
        assert f["replace"]("a-b", "-", "_") == "a_b"
        assert f["length"]([1, 2, 3]) == 3
        assert f["concat"]([1], [2, 3]) == [1, 2, 3]
        assert f["contains"](["x"], "x") is True
        assert f["coalesce"](None, "", "v") == "v"
        assert f["ceil"](1.2) == 2 and f["floor"](1.8) == 1
        assert f["range"](3) == [0, 1, 2]
        assert f["element"](["a", "b"], 3) == "b"
        assert f["merge"]({"a": 1}, {"b": 2}) == {"a": 1, "b": 2}
        assert f["flatten"]([[1], [2, 3]]) == [1, 2, 3]
        assert f["distinct"]([1, 1, 2]) == [1, 2]
        assert f["jsondecode"](f["jsonencode"]({"x": 1})) == {"x": 1}
        assert f["base64decode"](f["base64encode"]("hi")) == "hi"
        assert f["lookup"]({"a": 1}, "b", 9) == 9
        assert f["trimprefix"]("abc", "ab") == "c"
        assert f["tonumber"]("4") == 4

    def test_function_call_in_jobspec(self):
        src = '''
        variable "dcs" { default = ["dc1", "dc2"] }
        job "j" {
          datacenters = var.dcs
          region      = join("-", var.dcs)
        }
        '''
        job = parse_hcl(src)
        assert job.datacenters == ["dc1", "dc2"]
        assert job.region == "dc1-dc2"

    def test_unknown_function_errors(self):
        with pytest.raises(EvalError):
            parse_hcl('job "j" { region = frobnicate("x") }')


class TestDynamicBlocks:
    def test_dynamic_expands_services(self):
        src = '''
        variable "ports" { default = ["http", "admin"] }
        job "j" {
          group "g" {
            task "t" {
              driver = "mock"
              dynamic "service" {
                for_each = var.ports
                content {
                  name = "svc-${service.value}"
                  port = service.value
                }
              }
            }
          }
        }
        '''
        task = parse_hcl(src).task_groups[0].tasks[0]
        assert [s.name for s in task.services] == ["svc-http", "svc-admin"]
        assert [s.port_label for s in task.services] == ["http", "admin"]

    def test_dynamic_with_labels_and_iterator(self):
        src = '''
        locals { groups = { web = 2, db = 1 } }
        job "j" {
          dynamic "group" {
            for_each = local.groups
            iterator = it
            labels   = ["${it.key}"]
            content {
              count = it.value
              task "t" { driver = "mock" }
            }
          }
        }
        '''
        job = parse_hcl(src)
        names = {tg.name: tg.count for tg in job.task_groups}
        assert names == {"web": 2, "db": 1}


class TestBodyEvaluate:
    def test_variable_blocks_dropped(self):
        body = evaluate(parse('variable "x" { default = 1 }\na = var.x'))
        assert body.attrs == {"a": 1}
        assert body.get_blocks("variable") == []


class TestReviewRegressions:
    def test_nomad_env_interpolations_stay_literal(self):
        """${NOMAD_TASK_DIR} and friends resolve at the client, never
        at parse time."""
        src = '''
        job "j" { group "g" { task "t" {
          driver = "mock"
          config { command = "${NOMAD_TASK_DIR}/run.sh" }
          env { D = "${NOMAD_ALLOC_DIR}/x" }
        } } }
        '''
        task = parse_hcl(src).task_groups[0].tasks[0]
        assert task.config["command"] == "${NOMAD_TASK_DIR}/run.sh"
        assert task.env["D"] == "${NOMAD_ALLOC_DIR}/x"

    def test_override_converted_to_declared_type(self):
        src = '''
        variable "n" { default = 3 }
        job "j" { group "g" { count = "${var.n * 2}" } }
        '''
        job = parse_hcl(src, {"n": "5"})    # CLI strings coerce to int
        assert job.task_groups[0].count == 10
        src2 = '''
        variable "dcs" { default = ["dc1"] }
        job "j" { datacenters = var.dcs }
        '''
        job2 = parse_hcl(src2, {"dcs": '["a", "b"]'})
        assert job2.datacenters == ["a", "b"]
        with pytest.raises(EvalError):
            parse_hcl(src, {"n": "not-a-number"})

    def test_undeclared_env_variable_ignored(self):
        src = 'variable "x" { default = 1 }\njob "j" {}'
        # env-sourced unknown: fine; explicit flag unknown: error
        parse_hcl(src, env_variables={"stray": "v"})
        with pytest.raises(EvalError):
            parse_hcl(src, variables={"stray": "v"})
        # env value for a DECLARED variable applies (flag wins over env)
        src2 = 'variable "r" { default = "a" }\njob "j" { region = var.r }'
        assert parse_hcl(src2, env_variables={"r": "b"}).region == "b"
        assert parse_hcl(src2, {"r": "c"}, {"r": "b"}).region == "c"

    def test_sole_interpolation_keeps_native_list(self):
        src = '''
        variable "dcs" { default = ["dc1", "dc2"] }
        job "j" { datacenters = "${var.dcs}" }
        '''
        assert parse_hcl(src).datacenters == ["dc1", "dc2"]

    def test_ternary_guard_protects_dead_branch(self):
        scope = Scope({"var": {"l": [], "f": ["x"]}, "local": {}})
        assert eval_expr('length(var.l) > 0 ? var.l[0] : "none"',
                         scope) == "none"
        assert eval_expr('length(var.f) > 0 ? var.f[0] : "none"',
                         scope) == "x"

    def test_runtime_errors_become_eval_errors(self):
        scope = Scope({"var": {"l": []}, "local": {}})
        with pytest.raises(EvalError):
            eval_expr("var.l[5]", scope)
        with pytest.raises(EvalError):
            eval_expr('"a" + 1', scope)


def test_multiregion_block_parses():
    hcl = '''
    job "mr" {
      datacenters = ["dc1"]
      multiregion {
        strategy {
          max_parallel = 1
          on_failure   = "fail_all"
        }
        region "east" {
          count       = 3
          datacenters = ["east-1"]
        }
        region "west" {
          count = 2
        }
      }
      group "web" {
        task "t" {
          driver = "raw_exec"
          config { command = "/bin/true" }
        }
      }
    }
    '''
    job = parse_hcl(hcl)
    assert job.multiregion["strategy"]["max_parallel"] == 1
    assert job.multiregion["strategy"]["on_failure"] == "fail_all"
    regions = job.multiregion["regions"]
    assert [r["name"] for r in regions] == ["east", "west"]
    assert regions[0]["count"] == 3
    assert regions[0]["datacenters"] == ["east-1"]
    # helper semantics used by the scheduler gate
    job.region = "west"
    assert job.multiregion_region_index() == 1
    assert job.multiregion_starts_blocked()
    job.region = "east"
    assert not job.multiregion_starts_blocked()
