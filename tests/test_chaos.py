"""Chaos-plane integration tests (ISSUE 12).

Tier-1 coverage for the fault-injection seams and the hardening fixes
the chaos cell drove: the eval-pool thread-kill respawn, the broker's
auto-nack watcher surviving failed nacks, the delivery-limit path end
to end (always-nacking worker -> failed queue -> backoff follow-up),
heartbeat expiry driven through an open client-update fan-in window,
the plan rejection tracker (Nomad 1.3), explicit LostEvents on a
failed publish, and the pinned-seed MINI CHAOS smoke — a single-server
burst that converges through injected plan-commit/submit/ack failures
and a killed eval thread. The full 3-node cell runs in the stress
tier (tests/test_stress.py::TestChaosCell).
"""

import threading
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.structs import consts
from nomad_tpu.utils import faultpoints
from nomad_tpu.utils.faultpoints import FaultThreadKill


@pytest.fixture(autouse=True)
def _clean_plane():
    faultpoints.reset()
    yield
    faultpoints.reset()


def _wait(fn, timeout=10.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return
        time.sleep(0.02)
    raise AssertionError(f"timeout waiting for {msg}")


class TestEvalPoolRespawn:
    def test_killed_thread_does_not_strand_queued_tasks(self):
        """A task that kills its pool thread (BaseException past the
        Exception confinement) must not leave queued tasks with no
        server — the pool un-books the corpse and spawns a
        replacement (the chaos cell's wedged-batch finding)."""
        from nomad_tpu.server.worker import _EvalPool

        pool = _EvalPool(1, "chaos-test")
        ran = threading.Event()

        def boom():
            raise FaultThreadKill("test")

        t1 = pool.submit(boom)
        t2 = pool.submit(ran.set)
        t1.wait()
        t2.wait()
        assert ran.is_set()
        # bookkeeping is clean: a fresh task still runs
        again = threading.Event()
        pool.submit(again.set).wait()
        assert again.is_set()
        pool.shutdown()


class TestWorkerLoopSurvivesKill:
    def test_single_eval_dispatch_survives_thread_kill(self):
        """In single-eval mode _process runs ON the worker's dispatch
        thread — a killed eval there must abandon the eval (auto-nack
        recovers it) but never take the dispatch loop down (the chaos
        cell's stuck-pending-evals finding)."""
        from nomad_tpu.server.server import Server, ServerConfig

        server = Server(ServerConfig(
            num_workers=1, worker_batch_size=1, heartbeat_ttl=60.0,
            nack_timeout=0.4))
        server.start()
        try:
            server.eval_broker.initial_nack_delay = 0.02
            server.eval_broker.subsequent_nack_delay = 0.05
            for _ in range(4):
                server.node_register(mock.node())
            faultpoints.arm({"worker.eval": {"kind": "kill", "nth": 1}},
                            seed=1)
            job = mock.simple_job()
            job.task_groups[0].count = 2
            server.job_register(job)
            # the first eval is killed mid-dispatch; the auto-nack
            # deadline redelivers it and the SAME worker loop (still
            # alive) must place the job
            _wait(lambda: len([
                a for a in server.state.snapshot().allocs_by_job(
                    job.namespace, job.id)
                if not a.terminal_status()]) == 2,
                timeout=30.0, msg="job placed after dispatch kill")
            assert faultpoints.stats()["worker.eval"]["fires"] == 1
            assert server.workers[0]._thread.is_alive()
        finally:
            server.shutdown()


class TestNackWatcherSurvives:
    def test_auto_nack_retries_through_injected_failure(self):
        """The SHARED deadline watcher must survive a failed nack and
        retry: one dead watcher would strand every future deadline's
        eval unacked forever."""
        from nomad_tpu.server.eval_broker import EvalBroker

        broker = EvalBroker(nack_timeout=0.3, delivery_limit=10,
                            initial_nack_delay=0.0,
                            subsequent_nack_delay=0.0)
        broker.set_enabled(True)
        try:
            ev = mock.eval()
            broker.enqueue(ev)
            got, _token = broker.dequeue(["service"], timeout=2.0)
            assert got is not None
            # the watcher's FIRST auto-nack attempt fails; its retry
            # deadline (<= nack_timeout/4) must redeliver anyway
            faultpoints.arm({"broker.nack": {"kind": "error", "nth": 1}})
            got2, _ = broker.dequeue(["service"], timeout=5.0)
            assert got2 is not None and got2.id == ev.id
            assert faultpoints.stats()["broker.nack"]["fires"] == 1
        finally:
            broker.set_enabled(False)


class TestDeliveryLimit:
    def test_always_nacking_worker_lands_failed_queue_and_follow_up(self):
        """ISSUE 12 satellite: the delivery-limit path end to end. An
        eval nacked to exhaustion must land on the failed queue, the
        leader's reap loop must mark it failed AND create a delayed
        backoff follow-up eval, and the follow-up must become
        dequeueable once its wait elapses."""
        from nomad_tpu.server import fsm as fsm_msgs
        from nomad_tpu.server.eval_broker import FAILED_QUEUE
        from nomad_tpu.server.server import Server, ServerConfig

        server = Server(ServerConfig(
            num_workers=0, eval_delivery_limit=3,
            failed_eval_follow_up_wait=0.3, heartbeat_ttl=60.0))
        server.start()
        try:
            server.eval_broker.initial_nack_delay = 0.0
            server.eval_broker.subsequent_nack_delay = 0.0
            ev = mock.eval()
            server.raft_apply(fsm_msgs.EVAL_UPDATE, {"evals": [ev]})
            # the always-nacking worker
            for i in range(3):
                got, token = server.eval_broker.dequeue(
                    ["service"], timeout=2.0)
                assert got is not None, f"redelivery {i} lost"
                assert got.id == ev.id
                server.eval_broker.nack(got.id, token)
            # exhausted: routed to the failed queue, not redelivered
            assert server.eval_broker.dequeue(["service"], timeout=0.2)[0] \
                is None
            # the leader's reap loop (0.2s cadence) — or this manual
            # call, whoever wins the race — must mark it failed and
            # create the backoff follow-up
            server.reap_failed_evals_once()
            _wait(lambda: any(
                e.id == ev.id
                and e.status == consts.EVAL_STATUS_FAILED
                for e in server.state.snapshot().evals_iter()),
                timeout=5.0, msg="failed-queue eval marked failed")
            snap = server.state.snapshot()
            rows = {e.id: e for e in snap.evals_iter()}
            failed = rows[ev.id]
            assert failed.status == consts.EVAL_STATUS_FAILED
            assert "delivery limit" in failed.status_description
            follow_ups = [e for e in rows.values()
                          if e.previous_eval == ev.id
                          and e.triggered_by == "failed-follow-up"]
            assert len(follow_ups) == 1
            fu = follow_ups[0]
            assert fu.status == consts.EVAL_STATUS_PENDING
            assert fu.wait_until_s > time.time() - 0.1
            # parked in the delay heap until due
            assert server.eval_broker.stats()["delayed_evals"] == 1
            got, token = server.eval_broker.dequeue(
                ["service"], timeout=5.0)
            assert got is not None and got.id == fu.id
            server.eval_broker.ack(got.id, token)
        finally:
            server.shutdown()


class TestHeartbeatExpiryDuringFanIn:
    def test_expiry_fires_while_fan_in_window_holds_a_batch_open(self):
        """ISSUE 12 satellite: the heartbeat-expiry timer thread must
        drive the node-down transition even while the client-update
        fan-in leader is holding its fill window open back to back —
        the two paths share raft but never each other's locks, and
        this pins that interleaving."""
        from nomad_tpu.server.server import Server, ServerConfig

        server = Server(ServerConfig(
            num_workers=1, worker_batch_size=1, heartbeat_ttl=0.5,
            client_update_fill_window_ms=120.0))
        server.start()
        stop = threading.Event()
        storm_errors = []
        try:
            live = mock.node()
            server.node_register(live)
            victim = mock.node()
            server.node_register(victim)
            job = mock.simple_job()
            job.task_groups[0].count = 1
            server.job_register(job)
            _wait(lambda: any(
                not a.terminal_status() for a in
                server.state.snapshot().allocs_by_job(
                    job.namespace, job.id)), timeout=30.0,
                msg="job placed")
            snap = server.state.snapshot()
            alloc = [a for a in snap.allocs_by_job(job.namespace, job.id)
                     if not a.terminal_status()][0]

            def fan_in_storm():
                while not stop.is_set():
                    try:
                        a = alloc.copy()
                        a.client_status = consts.ALLOC_CLIENT_RUNNING
                        server.update_allocs_from_client([a])
                    except Exception as e:          # noqa: BLE001
                        storm_errors.append(e)

            def keep_live_alive():
                while not stop.is_set():
                    try:
                        server.node_heartbeat(live.id, "ready")
                    except Exception:               # noqa: BLE001
                        pass
                    time.sleep(0.1)

            for fn in (fan_in_storm, fan_in_storm, keep_live_alive):
                threading.Thread(target=fn, daemon=True).start()
            # the victim is never heartbeated: TTL (0.5s + jitter)
            # must expire UNDER the storm and mark it down
            _wait(lambda: server.state.snapshot().node_by_id(
                victim.id).status == consts.NODE_STATUS_DOWN,
                timeout=6.0, msg="victim node marked down under fan-in")
            stop.set()
            time.sleep(0.2)
            assert not storm_errors, storm_errors[:3]
            # the placed job still runs exactly once, nowhere stale
            snap = server.state.snapshot()
            final = [a for a in snap.allocs_by_job(job.namespace, job.id)
                     if not a.terminal_status()]
            assert len(final) == 1
            assert final[0].node_id != victim.id or \
                snap.node_by_id(victim.id).status != \
                consts.NODE_STATUS_DOWN
        finally:
            stop.set()
            server.shutdown()


class TestPlanRejection:
    def test_tracker_threshold_and_window(self):
        from nomad_tpu.server.plan_rejection import PlanRejectionTracker

        tr = PlanRejectionTracker(threshold=3, window_s=0.15)
        assert not tr.note_rejection("n1")
        assert not tr.note_rejection("n1")
        time.sleep(0.2)                     # window lapses: count resets
        assert not tr.note_rejection("n1")
        assert not tr.note_rejection("n1")
        assert tr.note_rejection("n1")      # third inside the window
        s = tr.snapshot()
        # the crossing alone does NOT count as a marking — only the
        # caller's committed eligibility flip does
        assert s["nodes_marked"] == 0 and s["rejections"] == 5
        tr.note_marked()
        assert tr.snapshot()["nodes_marked"] == 1
        # crossing reset the node: it must re-cross cleanly
        assert not tr.note_rejection("n1")

    def test_rejected_node_marked_ineligible_through_raft(self):
        """Nomad 1.3's plan_rejection_tracker: a node whose plans keep
        getting rejected by the applier crosses the threshold and is
        marked ineligible through the normal raft path."""
        from nomad_tpu.server.plan_rejection import plan_rejections
        from nomad_tpu.server.server import Server, ServerConfig
        from nomad_tpu.structs.eval_plan import Plan

        server = Server(ServerConfig(
            num_workers=0, heartbeat_ttl=60.0,
            plan_rejection_threshold=3))
        server.start()
        try:
            plan_rejections.reset_stats()
            plan_rejections.configure(3, 300.0)
            node = mock.node()
            server.node_register(node)

            def over_plan():
                big = mock.alloc(node_id=node.id)
                big.allocated_resources.tasks["web"].cpu.cpu_shares = \
                    1_000_000
                return Plan(eval_id="chaos-test",
                            node_allocation={node.id: [big]})

            for _ in range(3):
                result = server.planner.apply_one(over_plan())
                assert not result.node_allocation, "must be rejected"
            _wait(lambda: server.state.snapshot().node_by_id(
                node.id).scheduling_eligibility ==
                consts.NODE_SCHEDULING_INELIGIBLE,
                timeout=5.0, msg="node marked ineligible")
            assert plan_rejections.snapshot()["nodes_marked"] == 1
        finally:
            plan_rejections.reset_stats()
            server.shutdown()


class TestStreamPublishFault:
    def test_failed_publish_becomes_explicit_lost_marker(self):
        """The publish seam's contract: a dropped event batch surfaces
        to every live cursor as a LostEvents marker with the exact
        count — never a silent gap."""
        from nomad_tpu.server import stream

        broker = stream.EventBroker()
        sub = broker.subscribe({stream.TOPIC_ALL: ["*"]})
        faultpoints.arm({"stream.publish": {"kind": "error", "nth": 1}})
        dropped = [
            stream.Event(topic=stream.TOPIC_JOB, type="JobRegistered",
                         key=f"j{i}", index=5) for i in range(3)]
        broker.publish(dropped)             # injected publish failure
        broker.publish([stream.Event(
            topic=stream.TOPIC_JOB, type="JobRegistered", key="after",
            index=6)])
        evs = sub.next_events(timeout=2.0)
        assert evs[0].topic == stream.TOPIC_LOST
        assert evs[0].payload["LostEvents"] == 3
        assert [e.key for e in evs[1:]] == ["after"]
        assert broker.snapshot()["publish_failures"] == 1
        assert broker.snapshot()["lost_events"] == 3

    def test_resume_spanning_dropped_publish_gets_marker(self):
        """A subscriber ABSENT during the dropped publish must still
        see the gap on a later from_index resume (the drop joins the
        trimmed-history watermark — never a silent gap)."""
        from nomad_tpu.server import stream

        broker = stream.EventBroker()
        broker.publish([stream.Event(
            topic=stream.TOPIC_JOB, type="JobRegistered", key="seen",
            index=4)])
        faultpoints.arm({"stream.publish": {"kind": "error", "nth": 1}})
        broker.publish([stream.Event(
            topic=stream.TOPIC_JOB, type="JobRegistered", key="gone",
            index=7)])                      # dropped, nobody subscribed
        broker.publish([stream.Event(
            topic=stream.TOPIC_JOB, type="JobRegistered", key="after",
            index=9)])
        sub = broker.subscribe({stream.TOPIC_ALL: ["*"]}, from_index=4)
        evs = sub.next_events(timeout=2.0)
        assert evs[0].topic == stream.TOPIC_LOST
        assert evs[0].payload["LostEvents"] == -1   # unknown-size gap
        assert [e.key for e in evs[1:] if e.index > 4] == ["after"]


#: the tier-1 mini chaos schedule — pinned seed, bounded faults, one
#: server. Reproduce failures with faultpoints.arm(MINI_CHAOS, 4242).
MINI_CHAOS = {
    "plan.queue.enqueue": {"kind": "error", "nth": 1},
    "plan.commit.raft": {"kind": "error", "nth": 1},
    "broker.ack": {"kind": "error", "nth": 2},
    "worker.eval": {"kind": "kill", "nth": 3},
}
MINI_CHAOS_SEED = 4242


class TestMiniChaosSmoke:
    def test_pinned_seed_burst_converges_through_faults(self):
        """The tier-1 chaos smoke: a single-server burst with a failed
        plan submit, a failed commit batch, a failed ack, and a KILLED
        eval thread — every eval must still reach a terminal state,
        every job place exactly once, and the usage planes stay
        bit-identical to a from-scratch rebuild."""
        from nomad_tpu.server.server import Server, ServerConfig
        from nomad_tpu.state.usage import usage_rebuild_diff

        server = Server(ServerConfig(
            num_workers=1, worker_batch_size=4, heartbeat_ttl=60.0,
            nack_timeout=0.5, eval_delivery_limit=6,
            failed_eval_follow_up_wait=0.2))
        server.start()
        try:
            server.eval_broker.initial_nack_delay = 0.02
            server.eval_broker.subsequent_nack_delay = 0.05
            for _ in range(12):
                server.node_register(mock.node())
            faultpoints.arm(MINI_CHAOS, seed=MINI_CHAOS_SEED)
            jobs = []
            for _ in range(8):
                job = mock.simple_job()
                job.task_groups[0].count = 2
                server.job_register(job)
                jobs.append(job)

            def converged():
                snap = server.state.snapshot()
                live = sum(
                    1 for j in jobs
                    for a in snap.allocs_by_job(j.namespace, j.id)
                    if not a.terminal_status())
                if live != 16:
                    return False
                if any(e.status == consts.EVAL_STATUS_PENDING
                       for e in snap.evals_iter()):
                    return False
                b = server.eval_broker.stats()
                return (b["total_ready"] == 0
                        and b["total_unacked"] == 0
                        and b["total_waiting"] == 0)

            _wait(converged, timeout=90.0,
                  msg="mini chaos burst converged")
            fired = faultpoints.fires()
            stats = faultpoints.stats()
            faultpoints.disarm()
            assert fired >= 3, stats
            assert stats["worker.eval"]["fires"] == 1, stats
            assert usage_rebuild_diff(server.state) == []
            # no duplicate live slots anywhere
            snap = server.state.snapshot()
            for j in jobs:
                live = [a for a in snap.allocs_by_job(j.namespace, j.id)
                        if not a.terminal_status()]
                names = [a.name for a in live]
                assert len(set(names)) == len(names) == 2
        finally:
            server.shutdown()
