"""Event-stream ACL enforcement.

Reference behavior: nomad/stream/event_broker.go:55-111 —
``SubscribeWithACLCheck`` resolves the token at subscribe time and
``handleACLUpdates`` re-validates on ACL changes, closing subscriptions
whose token disappears; events are filtered by the token's namespace
capabilities. Without this, ``/v1/event/stream`` leaks every
namespace's change feed to any holder of any token.
"""

import json
import socket
import threading
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.acl.policy import ACLPolicy, ACLToken
from nomad_tpu.api.agent import Agent, AgentConfig
from nomad_tpu.server import fsm as fsm_msgs
from nomad_tpu.structs.namespace import Namespace


def _open_stream(addr: str, token: str, query: str = ""):
    """Raw chunked NDJSON reader over the event stream endpoint;
    returns (socket, line-iterator). ``query`` narrows topics
    (e.g. "topic=Allocation&topic=Deployment")."""
    host, port = addr.rsplit(":", 1)
    host = host.replace("http://", "")
    path = "/v1/event/stream" + (f"?{query}" if query else "")
    s = socket.create_connection((host, int(port)), timeout=30)
    s.sendall((
        f"GET {path} HTTP/1.1\r\n"
        f"Host: {host}\r\nX-Nomad-Token: {token}\r\n\r\n"
    ).encode())
    f = s.makefile("rb")
    status = f.readline().decode()
    while f.readline().strip():      # drain headers
        pass

    def lines():
        while True:
            size = f.readline().strip()          # chunk size
            if not size:
                return
            try:
                n = int(size, 16)
            except ValueError:
                return
            if n == 0:
                return
            data = f.read(n)
            f.read(2)                            # trailing CRLF
            for ln in data.splitlines():
                if ln.strip():
                    yield ln

    return s, status, lines()


@pytest.fixture()
def acl_agent():
    cfg = AgentConfig.dev()
    cfg.acl_enabled = True
    agent = Agent(cfg)
    agent.start()
    try:
        yield agent
    finally:
        agent.shutdown()


class TestEventStreamACL:
    def test_namespace_scoped_token_sees_only_its_namespace(self, acl_agent):
        server = acl_agent.server
        server.raft_apply(fsm_msgs.NAMESPACE_UPSERT, {
            "namespaces": [Namespace(name="secret")]})
        policy = ACLPolicy(name="default-read",
                          rules='namespace "default" { policy = "read" }')
        server.raft_apply(fsm_msgs.ACL_POLICY_UPSERT,
                          {"policies": [policy]})
        tok = ACLToken.create(name="scoped", type="client",
                              policies=["default-read"])
        server.raft_apply(fsm_msgs.ACL_TOKEN_UPSERT, {"tokens": [tok]})

        s, status, lines = _open_stream(acl_agent.http.addr, tok.secret_id)
        assert " 200 " in status
        got = []
        stop = threading.Event()

        def reader():
            for ln in lines:
                batch = json.loads(ln)
                got.extend(batch.get("Events") or [])
                if stop.is_set():
                    return

        t = threading.Thread(target=reader, daemon=True)
        t.start()
        try:
            visible = mock.job()
            visible.id = "visible-job"
            server.job_register(visible)
            hidden = mock.job()
            hidden.id = "hidden-job"
            hidden.namespace = "secret"
            server.job_register(hidden)

            deadline = time.time() + 15
            while time.time() < deadline:
                if any(e.get("Key") == "visible-job" for e in got):
                    break
                time.sleep(0.2)
            keys = {e.get("Key") for e in got}
            assert "visible-job" in keys, f"saw only {keys}"
            # the secret-namespace job never crosses this stream
            time.sleep(1.0)
            namespaces = {e.get("Namespace", "") for e in got}
            assert "secret" not in namespaces
            assert not any(e.get("Key") == "hidden-job" for e in got)
        finally:
            stop.set()
            s.close()

    def test_namespaced_token_topic_filter_scopes_alloc_events(
            self, acl_agent):
        """ISSUE 11 satellite: topic/key/namespace filtering under
        ACLs — a namespaced token subscribed to Allocation/Deployment
        topics sees only its own namespace's events; synthetic events
        published straight into the ring keep the test about the
        filter, not the scheduler."""
        from nomad_tpu.server import stream

        server = acl_agent.server
        server.raft_apply(fsm_msgs.NAMESPACE_UPSERT, {
            "namespaces": [Namespace(name="secret")]})
        policy = ACLPolicy(name="default-read",
                          rules='namespace "default" { policy = "read" }')
        server.raft_apply(fsm_msgs.ACL_POLICY_UPSERT,
                          {"policies": [policy]})
        tok = ACLToken.create(name="scoped", type="client",
                              policies=["default-read"])
        server.raft_apply(fsm_msgs.ACL_TOKEN_UPSERT, {"tokens": [tok]})

        s, status, lines = _open_stream(
            acl_agent.http.addr, tok.secret_id,
            query="topic=Allocation&topic=Deployment")
        assert " 200 " in status
        got = []
        threading.Thread(
            target=lambda: [got.append(json.loads(ln))
                            for ln in lines],
            daemon=True).start()
        idx = server.state.latest_index() + 1
        server.event_broker.publish([
            stream.Event("Allocation", "AllocationUpdated", "a-vis",
                         idx, namespace="default"),
            stream.Event("Allocation", "AllocationUpdated", "a-hid",
                         idx, namespace="secret"),
            stream.Event("Deployment", "DeploymentUpdate", "d-hid",
                         idx, namespace="secret"),
            stream.Event("Job", "JobRegistered", "j-wrong-topic",
                         idx, namespace="default"),
            stream.Event("Deployment", "DeploymentUpdate", "d-vis",
                         idx, namespace="default"),
        ])
        deadline = time.time() + 15
        while time.time() < deadline:
            keys = {e.get("Key") for b in got
                    for e in (b.get("Events") or [])}
            if {"a-vis", "d-vis"} <= keys:
                break
            time.sleep(0.2)
        try:
            keys = {e.get("Key") for b in got
                    for e in (b.get("Events") or [])}
            assert {"a-vis", "d-vis"} <= keys, keys
            # namespace scope: the secret namespace's events never cross
            assert "a-hid" not in keys and "d-hid" not in keys
            # topic scope: unsubscribed topics never cross either
            assert "j-wrong-topic" not in keys
        finally:
            s.close()

    def test_management_token_sees_all_namespaces(self, acl_agent):
        from nomad_tpu.server import stream

        server = acl_agent.server
        server.raft_apply(fsm_msgs.NAMESPACE_UPSERT, {
            "namespaces": [Namespace(name="secret")]})
        mgmt = ACLToken.create(name="root", type="management")
        server.raft_apply(fsm_msgs.ACL_TOKEN_UPSERT, {"tokens": [mgmt]})

        s, status, lines = _open_stream(acl_agent.http.addr,
                                        mgmt.secret_id)
        assert " 200 " in status
        got = []
        threading.Thread(
            target=lambda: [got.append(json.loads(ln))
                            for ln in lines],
            daemon=True).start()
        idx = server.state.latest_index() + 1
        server.event_broker.publish([
            stream.Event("Job", "JobRegistered", "j-default", idx,
                         namespace="default"),
            stream.Event("Job", "JobRegistered", "j-secret", idx,
                         namespace="secret"),
        ])
        deadline = time.time() + 15
        while time.time() < deadline:
            keys = {e.get("Key") for b in got
                    for e in (b.get("Events") or [])}
            if {"j-default", "j-secret"} <= keys:
                break
            time.sleep(0.2)
        try:
            keys = {e.get("Key") for b in got
                    for e in (b.get("Events") or [])}
            # the operator's stream spans every namespace
            assert {"j-default", "j-secret"} <= keys, keys
        finally:
            s.close()

    def test_revoked_token_loses_stream(self, acl_agent):
        server = acl_agent.server
        policy = ACLPolicy(name="default-read",
                          rules='namespace "default" { policy = "read" }')
        server.raft_apply(fsm_msgs.ACL_POLICY_UPSERT,
                          {"policies": [policy]})
        tok = ACLToken.create(name="doomed", type="client",
                              policies=["default-read"])
        server.raft_apply(fsm_msgs.ACL_TOKEN_UPSERT, {"tokens": [tok]})

        s, status, lines = _open_stream(acl_agent.http.addr, tok.secret_id)
        assert " 200 " in status
        ended = threading.Event()

        def reader():
            for _ in lines:
                pass
            ended.set()

        threading.Thread(target=reader, daemon=True).start()
        try:
            server.raft_apply(fsm_msgs.ACL_TOKEN_DELETE,
                              {"accessor_ids": [tok.accessor_id]})
            # next poll re-resolves the token and drops the stream
            assert ended.wait(timeout=12), \
                "stream survived token revocation"
        finally:
            s.close()

    def test_bad_token_rejected_at_subscribe(self, acl_agent):
        s, status, _ = _open_stream(acl_agent.http.addr, "no-such-token")
        s.close()
        assert " 403 " in status

    def test_anonymous_rejected_at_subscribe(self, acl_agent):
        # anonymous resolves but holds no read capability anywhere:
        # no 600s heartbeat-only stream for unauthenticated clients
        s, status, _ = _open_stream(acl_agent.http.addr, "")
        s.close()
        assert " 403 " in status

    def test_policy_narrowed_to_deny_drops_stream(self, acl_agent):
        server = acl_agent.server
        policy = ACLPolicy(name="flip",
                          rules='namespace "default" { policy = "read" }')
        server.raft_apply(fsm_msgs.ACL_POLICY_UPSERT,
                          {"policies": [policy]})
        tok = ACLToken.create(name="flipped", type="client",
                              policies=["flip"])
        server.raft_apply(fsm_msgs.ACL_TOKEN_UPSERT, {"tokens": [tok]})

        s, status, lines = _open_stream(acl_agent.http.addr, tok.secret_id)
        assert " 200 " in status
        ended = threading.Event()

        def reader():
            for _ in lines:
                pass
            ended.set()

        threading.Thread(target=reader, daemon=True).start()
        try:
            # the EDIT (not deletion) of the policy must reach the
            # stream: compiled-ACL caches are invalidated by the
            # acl_policy table index
            server.raft_apply(fsm_msgs.ACL_POLICY_UPSERT, {"policies": [
                ACLPolicy(name="flip",
                          rules='namespace "default" { policy = "deny" }')]})
            assert ended.wait(timeout=12), \
                "stream survived policy narrowing to deny"
        finally:
            s.close()
