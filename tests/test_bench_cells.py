"""The non-headline timed bench cells (BASELINE.md:22-25): GPU device
asks and preemption-enabled placement as fused device loops.

Reference behavior: devices — rank.go AssignDevice / device.go:32
(deduct device instances between placements); preemption —
generic_sched.go:800 (preemption is a second pass entered only when no
node fits), rank.go:799 PreemptionScoringIterator (score averages the
binpack fit after eviction with the net-priority preemption score).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from nomad_tpu.ops.kernel import build_kernel_in  # noqa: E402
from nomad_tpu.parallel.batching import (  # noqa: E402
    make_device_apply_loop,
    make_preemption_apply_loop,
)
from nomad_tpu.parallel.synthetic import (  # noqa: E402
    synthetic_cluster,
    synthetic_eval,
)

K = 4


def _shared(n=8, cpu=4000.0, mem=8192.0):
    cluster = synthetic_cluster(n, cpu=cpu, mem=mem, seed=3)
    ev = synthetic_eval(cluster, desired_count=K)
    return cluster, build_kernel_in(cluster, ev, K)


class TestDeviceLoop:
    def test_respects_gpu_capacity_and_deducts(self):
        cluster, shared = _shared()
        n_pad = cluster.n_pad
        df0 = np.zeros((n_pad, shared.dev_free.shape[1]), np.float32)
        df0[0, 0] = 2.0          # two gpu nodes, 2 instances each
        df0[1, 0] = 2.0

        loop = make_device_apply_loop(K)
        T, B = 2, 1
        a_cpu = jnp.full((T, B), 100.0)
        a_mem = jnp.full((T, B), 100.0)
        a_gpu = jnp.full((T, B), 1.0)
        n_steps = jnp.full((B,), K, jnp.int32)
        score, placed, uc, um, df = loop(
            shared, jnp.zeros(n_pad), jnp.zeros(n_pad), jnp.asarray(df0),
            a_cpu, a_mem, a_gpu, n_steps)
        # 4 gpu instances total; 2 batches x 4 asked placements can
        # only ever place 4 — the second batch finds no devices left
        assert int(placed) == 4
        df = np.asarray(df)
        assert df.min() >= 0.0
        assert df[:2, 0].sum() == 0.0
        # cpu committed only on the gpu nodes
        uc = np.asarray(uc)
        assert uc[:2].sum() == pytest.approx(400.0)
        assert uc[2:].sum() == 0.0

    def test_reset_every_restores_device_plane(self):
        cluster, shared = _shared()
        n_pad = cluster.n_pad
        df0 = np.zeros((n_pad, shared.dev_free.shape[1]), np.float32)
        df0[0, 0] = 1.0

        loop = make_device_apply_loop(K, reset_every=1)
        T, B = 3, 1
        a_cpu = jnp.full((T, B), 100.0)
        a_mem = jnp.full((T, B), 100.0)
        a_gpu = jnp.full((T, B), 1.0)
        n_steps = jnp.full((B,), K, jnp.int32)
        _, placed, *_ = loop(
            shared, jnp.zeros(n_pad), jnp.zeros(n_pad), jnp.asarray(df0),
            a_cpu, a_mem, a_gpu, n_steps)
        # every batch sees the pristine plane again: 1 gpu per batch
        assert int(placed) == 3


class TestPreemptionLoop:
    def _planes(self, n_pad, used, pre_rows):
        uc = np.full(n_pad, float(used), np.float32)
        um = np.full(n_pad, float(used), np.float32)
        pc = np.zeros(n_pad, np.float32)
        pm = np.zeros(n_pad, np.float32)
        ps = np.zeros(n_pad, np.float32)
        for row, amount, score in pre_rows:
            pc[row] = pm[row] = amount
            ps[row] = score
        return uc, um, pc, pm, ps

    def test_preempts_only_when_nothing_fits(self):
        cluster, shared = _shared(n=4, cpu=1000.0, mem=1000.0)
        n_pad = cluster.n_pad
        # every node 900/1000 used; node 2 holds 800 of evictable
        # lower-priority capacity
        uc, um, pc, pm, ps = self._planes(n_pad, 900.0,
                                          [(2, 800.0, 0.5)])
        uc[cluster.n_real:] = 1000.0   # pad rows unusable
        um[cluster.n_real:] = 1000.0

        loop = make_preemption_apply_loop(K)
        T, B = 1, 1
        a_cpu = jnp.full((T, B), 500.0)
        a_mem = jnp.full((T, B), 500.0)
        n_steps = jnp.full((B,), K, jnp.int32)
        score, placed, preempted, uc2, um2 = loop(
            shared, jnp.asarray(uc), jnp.asarray(um),
            jnp.asarray(pc), jnp.asarray(pm), jnp.asarray(ps),
            a_cpu, a_mem, n_steps)
        # one placement lands via eviction; the freed capacity is spent
        # so the remaining K-1 steps find nothing
        assert int(placed) == 1
        assert int(preempted) == 1
        uc2 = np.asarray(uc2)
        assert uc2[2] == pytest.approx(900.0 - 800.0 + 500.0)

    def test_same_node_evicted_by_two_members_credits_once(self):
        """Two batch members preempting the SAME node must free its
        preemptible capacity once, not once per member."""
        cluster, shared = _shared(n=4, cpu=1000.0, mem=1000.0)
        n_pad = cluster.n_pad
        uc, um, pc, pm, ps = self._planes(n_pad, 900.0,
                                          [(2, 800.0, 0.5)])
        uc[cluster.n_real:] = 1000.0
        um[cluster.n_real:] = 1000.0

        loop = make_preemption_apply_loop(K)
        T, B = 1, 2
        a_cpu = jnp.full((T, B), 500.0)
        a_mem = jnp.full((T, B), 500.0)
        n_steps = jnp.full((B,), 1, jnp.int32)
        _, placed, preempted, uc2, _ = loop(
            shared, jnp.asarray(uc), jnp.asarray(um),
            jnp.asarray(pc), jnp.asarray(pm), jnp.asarray(ps),
            a_cpu, a_mem, n_steps)
        # both members (same optimistic snapshot) evict node 2 and
        # place: adds 500+500, eviction credit 800 applied ONCE
        assert int(placed) == 2 and int(preempted) == 2
        assert np.asarray(uc2)[2] == pytest.approx(
            900.0 + 500.0 + 500.0 - 800.0)

    def test_normal_fit_wins_over_preemption(self):
        cluster, shared = _shared(n=4, cpu=1000.0, mem=1000.0)
        n_pad = cluster.n_pad
        uc, um, pc, pm, ps = self._planes(n_pad, 900.0,
                                          [(2, 800.0, 0.5)])
        uc[3] = um[3] = 400.0          # node 3 fits normally
        uc[cluster.n_real:] = 1000.0
        um[cluster.n_real:] = 1000.0

        loop = make_preemption_apply_loop(K)
        T, B = 1, 1
        a_cpu = jnp.full((T, B), 500.0)
        a_mem = jnp.full((T, B), 500.0)
        n_steps = jnp.full((B,), 1, jnp.int32)
        _, placed, preempted, uc2, _ = loop(
            shared, jnp.asarray(uc), jnp.asarray(um),
            jnp.asarray(pc), jnp.asarray(pm), jnp.asarray(ps),
            a_cpu, a_mem, n_steps)
        assert int(placed) == 1
        assert int(preempted) == 0     # second pass never entered
        assert np.asarray(uc2)[3] == pytest.approx(900.0)


class TestDonationAlignment:
    """The reset-loop variants must not donate their plane arguments:
    with ``reset_every`` the scan consumes ``p + 0`` copies and the
    originals never alias an output — device backends then warn "Some
    donated buffers were not usable" (promoted to an error suite-wide
    in conftest, which is what this class feeds: the BENCH_r05
    device/preemption bench path ran exactly these shapes). The loops
    here re-use their input planes across two calls — donation, if it
    ever came back, would invalidate the buffers and fail loudly."""

    def test_device_loop_reset_inputs_survive(self):
        cluster, shared = _shared()
        n_pad = cluster.n_pad
        df0 = jnp.zeros((n_pad, shared.dev_free.shape[1]))
        uc = jnp.zeros(n_pad)
        um = jnp.zeros(n_pad)
        loop = make_device_apply_loop(K, reset_every=1)
        T, B = 2, 1
        a = jnp.full((T, B), 100.0)
        a_gpu = jnp.zeros((T, B))
        n_steps = jnp.full((B,), 1, jnp.int32)
        out1 = loop(shared, uc, um, df0, a, a, a_gpu, n_steps)
        out2 = loop(shared, uc, um, df0, a, a, a_gpu, n_steps)
        assert int(out1[1]) == int(out2[1]) == 2

    def test_preemption_loop_reset_inputs_survive(self):
        cluster, shared = _shared(n=4, cpu=1000.0, mem=1000.0)
        n_pad = cluster.n_pad
        uc = jnp.zeros(n_pad)
        um = jnp.zeros(n_pad)
        pc = jnp.zeros(n_pad)
        pm = jnp.zeros(n_pad)
        ps = jnp.zeros(n_pad)
        loop = make_preemption_apply_loop(K, reset_every=1)
        T, B = 2, 1
        a = jnp.full((T, B), 100.0)
        n_steps = jnp.full((B,), 1, jnp.int32)
        out1 = loop(shared, uc, um, pc, pm, ps, a, a, n_steps)
        out2 = loop(shared, uc, um, pc, pm, ps, a, a, n_steps)
        assert int(out1[1]) == int(out2[1]) == 2

    def test_schedule_loop_reset_inputs_survive(self):
        from nomad_tpu.ops.kernel import LEAN_FEATURES
        from nomad_tpu.parallel.batching import make_schedule_apply_loop

        cluster, shared = _shared()
        n_pad = cluster.n_pad
        uc = jnp.zeros(n_pad)
        um = jnp.zeros(n_pad)
        loop = make_schedule_apply_loop(K, LEAN_FEATURES, topk=True,
                                        reset_every=1)
        T, B = 2, 2
        a = jnp.full((T, B), 100.0)
        n_steps = jnp.full((B,), 1, jnp.int32)
        out1 = loop(shared, uc, um, a, a, n_steps)
        out2 = loop(shared, uc, um, a, a, n_steps)
        assert int(out1[1]) == int(out2[1]) == 4


class TestReplayCells:
    """Integration: the bench cells run end-to-end on a small replay."""

    @pytest.fixture(scope="class")
    def planes(self, tmp_path_factory):
        import os
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "bench"))
        import bench
        import c2m

        p = tmp_path_factory.mktemp("cells") / "replay.snap"
        c2m.generate(str(p), n_nodes=200, n_allocs=800, seed=9,
                     verbose=False)
        return bench._replay_planes(str(p))

    def test_device_cell(self, planes, monkeypatch):
        import bench

        monkeypatch.setattr(bench, "CELL_BATCHES", 2)
        monkeypatch.setattr(bench, "BATCH", 8)
        cluster, snap, used_cpu, used_mem, used_disk, _asks, _ = planes
        out = bench.run_replay_device(
            cluster, snap, used_cpu, used_mem, used_disk)
        assert out["device_evals_per_sec"] > 0
        # the replay really contains gpu capacity to schedule against
        assert out["device_free_gpus"] >= 0

    def test_preemption_cell(self, planes, monkeypatch):
        import bench

        monkeypatch.setattr(bench, "CELL_BATCHES", 2)
        monkeypatch.setattr(bench, "BATCH", 8)
        cluster, snap, used_cpu, used_mem, _used_disk, asks, _ = planes
        out = bench.run_replay_preemption(
            cluster, snap, used_cpu, used_mem, asks)
        assert out["preemption_evals_per_sec"] > 0
        assert out["preemption_placed"] > 0
