"""exec-driver isolation: namespaces + cgroup limits via the native
executor (reference drivers/shared/executor/executor_linux.go).

Tests skip on hosts without the corresponding privilege (the reference
exec driver likewise refuses to fingerprint there).
"""

import os
import time
import uuid

import pytest

from nomad_tpu import structs
from nomad_tpu.drivers.execdriver import ExecDriver, isolation_support
from nomad_tpu.plugins.drivers import TaskConfig


def _task_config(tmp_path, name, command, args, resources=None):
    return TaskConfig(
        id=f"{uuid.uuid4()}-{name}",
        name=name,
        alloc_id=str(uuid.uuid4()),
        driver_config={"command": command, "args": args},
        resources=resources,
        alloc_dir=str(tmp_path),
    )


def _wait_exit(driver, task_id, timeout=20.0):
    result = driver.wait_task(task_id, timeout=timeout)
    assert result is not None, "task did not exit"
    return result


@pytest.mark.skipif(not isolation_support()["namespaces"],
                    reason="host cannot unshare namespaces")
class TestNamespaces:
    def test_task_is_pid_1_and_cannot_see_host_pids(self, tmp_path):
        driver = ExecDriver()
        cfg = _task_config(
            tmp_path, "ns", "/bin/sh",
            ["-c", "echo mypid=$$; ls /proc | grep -c '^[0-9][0-9]*$'"],
        )
        driver.start_task(cfg)
        result = _wait_exit(driver, cfg.id)
        assert result.exit_code == 0
        time.sleep(0.2)
        out = open(os.path.join(str(tmp_path), "stdout")).read()
        # pid 1 of its own pid namespace...
        assert "mypid=1" in out, out
        # ...and /proc (remounted inside) shows only the task's tree
        n_procs = int(out.strip().splitlines()[-1])
        assert n_procs <= 5, out
        driver.destroy_task(cfg.id, force=True)


@pytest.mark.skipif(not isolation_support()["cgroups"],
                    reason="host cgroups not writable")
class TestCgroupLimits:
    def test_memory_limit_kills_overallocation(self, tmp_path):
        driver = ExecDriver()
        cfg = _task_config(
            tmp_path, "oom", "/usr/bin/env",
            ["python3", "-c",
             "x = bytearray(256 * 1024 * 1024); print('survived')"],
            resources=structs.Resources(cpu=100, memory_mb=32),
        )
        driver.start_task(cfg)
        result = _wait_exit(driver, cfg.id)
        time.sleep(0.2)
        out = open(os.path.join(str(tmp_path), "stdout")).read()
        assert "survived" not in out
        # killed by the OOM killer (SIGKILL), not a clean exit
        assert (result.signal == 9) or (result.exit_code != 0), (
            result.exit_code, result.signal)
        driver.destroy_task(cfg.id, force=True)

    def test_within_limit_runs_fine(self, tmp_path):
        driver = ExecDriver()
        cfg = _task_config(
            tmp_path, "ok", "/usr/bin/env",
            ["python3", "-c", "x = bytearray(8 * 1024 * 1024); print('ok')"],
            resources=structs.Resources(cpu=100, memory_mb=512),
        )
        driver.start_task(cfg)
        result = _wait_exit(driver, cfg.id)
        assert result.exit_code == 0
        time.sleep(0.2)
        out = open(os.path.join(str(tmp_path), "stdout")).read()
        assert "ok" in out
        driver.destroy_task(cfg.id, force=True)


@pytest.mark.skipif(not isolation_support()["namespaces"],
                    reason="host cannot unshare namespaces")
class TestExecSessionsShareIsolation:
    def test_exec_enters_task_namespaces(self, tmp_path):
        """Exec sessions must run INSIDE the task's namespaces (the
        reference execs inside the container), not on the host."""
        driver = ExecDriver()
        cfg = _task_config(
            tmp_path, "iso", "/bin/sh", ["-c", "sleep 30"],
        )
        driver.start_task(cfg)
        try:
            out = driver.exec_task(
                cfg.id,
                ["/bin/sh", "-c", "ls /proc | grep -c '^[0-9][0-9]*$'"],
            )
            assert out["exit_code"] == 0, out
            n_procs = int(out["stdout"].strip().splitlines()[-1])
            # inside the pid namespace only the task tree is visible
            assert n_procs <= 6, out
        finally:
            driver.stop_task(cfg.id, timeout=2)
            driver.destroy_task(cfg.id, force=True)
