"""Consul Connect analog: sidecar proxies, mesh identity, upstreams.

Reference behavior: client/allocrunner/taskrunner/envoy_bootstrap_hook.go
(sidecar proxy per connect service), connect_native_hook.go (workload
identity for native services), nomad/job_endpoint_hook_connect.go
(sidecar mesh-port injection at admission), and the sidecar service
registration other allocations discover upstream endpoints from.

The headline property ("done" per VERDICT r2 missing #3): two services
in ONE job reach each other ONLY through the mesh path — the app binds
loopback inside its namespace, the sidecar's mesh port is token-gated,
and the client's upstream listener is the sole working route.
"""

import json
import socket
import subprocess
import sys
import time

import pytest

from nomad_tpu import mock, structs
from nomad_tpu.api.agent import Agent, AgentConfig
from nomad_tpu.client.network_manager import bridge_supported
from nomad_tpu.structs.job import Service

pytestmark = pytest.mark.skipif(
    not bridge_supported(), reason="host cannot create netns/veth")


def wait_for(fn, timeout=40.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        out = fn()
        if out:
            return out
        time.sleep(0.1)
    raise AssertionError(f"timeout waiting for {msg}")


def make_mesh_job():
    """One job, two groups: "api" serves on loopback inside its netns
    behind a connect sidecar; "web" declares an upstream to it."""
    job = mock.job()
    job.id = f"mesh-{job.id[-12:]}"
    job.constraints = []
    api = job.task_groups[0]
    api.name = "api"
    api.count = 1
    api.networks = [structs.NetworkResource(mode="bridge")]
    api.services = [Service(
        name="count-api",
        connect={"sidecar_service": {
            "proxy": {"local_service_port": 9001}}},
    )]
    task = api.tasks[0]
    task.name = "api"
    task.driver = "raw_exec"
    # the app binds LOOPBACK inside the namespace: nothing but the
    # sidecar (same namespace) can reach it
    task.config = {
        "command": sys.executable,
        "args": ["-S", "-c", (
            "import socket\n"
            "s = socket.socket()\n"
            "s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)\n"
            "s.bind((\"127.0.0.1\", 9001))\n"
            "s.listen(4)\n"
            "while True:\n"
            "    c, _ = s.accept()\n"
            "    c.sendall(b\"count-api-response\")\n"
            "    c.close()\n"
        )],
    }

    web = api.copy()
    web.name = "web"
    web.networks = [structs.NetworkResource(mode="bridge")]
    web.services = [Service(
        name="count-dashboard",
        connect={"sidecar_service": {"proxy": {
            "local_service_port": 9002,
            "upstreams": [{"destination_name": "count-api",
                           "local_bind_port": 8081}],
        }}},
    )]
    wt = web.tasks[0]
    wt.name = "web"
    wt.config = {
        "command": sys.executable,
        "args": ["-S", "-c", "import time\ntime.sleep(300)\n"],
    }
    job.task_groups = [api, web]
    return job


def _netns_fetch(ns: str, port: int, payload: bytes = b"") -> bytes:
    """Connect to 127.0.0.1:<port> INSIDE the namespace, return reply."""
    prog = (
        "import socket, sys\n"
        "c = socket.create_connection((\"127.0.0.1\", %d), timeout=5)\n"
        "c.sendall(%r)\n" % (port, payload)
        + "sys.stdout.buffer.write(c.recv(200))\n"
    )
    out = subprocess.run(
        ["ip", "netns", "exec", ns, sys.executable, "-S", "-c", prog],
        capture_output=True, timeout=15)
    return out.stdout


class TestServiceMesh:
    def test_two_services_reach_each_other_only_through_mesh(self):
        agent = Agent(AgentConfig.dev())
        agent.start()
        try:
            job = make_mesh_job()
            agent.server.job_register(job)

            # both allocs run; the api sidecar service is registered
            def regs():
                return agent.server.services_by_name(
                    "default", "count-api-sidecar-proxy")
            sidecars = wait_for(lambda: regs() or None,
                                msg="sidecar registration")
            assert sidecars[0]["Port"] > 0
            mesh_addr = (sidecars[0]["Address"], sidecars[0]["Port"])

            # find web's netns
            snap = agent.server.state.snapshot()
            web_alloc = wait_for(
                lambda: next(
                    (a for a in agent.server.state.snapshot()
                     .allocs_by_job(job.namespace, job.id)
                     if a.task_group == "web"
                     and a.client_status == "running"), None),
                msg="web alloc running")
            web_net = wait_for(
                lambda: agent.client.network_manager.network_of(
                    web_alloc.id), msg="web netns")

            # 1) THE MESH PATH WORKS: web's upstream listener inside its
            # namespace reaches the api app through both sidecars
            data = wait_for(
                lambda: _netns_fetch(web_net.ns_name, 8081) or None,
                msg="mesh response")
            assert data == b"count-api-response"

            # 2) the api app itself is NOT reachable from the host:
            # it binds loopback inside its own namespace
            api_alloc = next(
                a for a in agent.server.state.snapshot()
                .allocs_by_job(job.namespace, job.id)
                if a.task_group == "api")
            api_net = agent.client.network_manager.network_of(api_alloc.id)
            with pytest.raises(OSError):
                socket.create_connection((api_net.ip, 9001), timeout=2)

            # 3) the sidecar's mesh port refuses unauthenticated
            # connections (the intentions-deny analog): without the
            # mesh identity token, no bytes come back
            c = socket.create_connection(mesh_addr, timeout=5)
            c.sendall(b"SI wrong-token\n")
            c.settimeout(3)
            got = b""
            try:
                got = c.recv(100)
            except socket.timeout:
                pass
            finally:
                c.close()
            assert got == b"", "mesh port answered an unauthenticated peer"

            # ... and WITH the token, the same port serves (the
            # upstream proxy's handshake)
            token = agent.server.mesh_identity_token(
                "default", "count-api")
            c = socket.create_connection(mesh_addr, timeout=5)
            c.sendall(b"SI " + token.encode() + b"\n")
            got = c.recv(100)
            c.close()
            assert got == b"count-api-response"

            # 4) derivation is SCOPED to the alloc's declared
            # services/upstreams (consul.go DeriveSITokens): web's
            # alloc may derive its own service and its declared
            # upstream, but not an arbitrary destination
            assert agent.server.mesh_identity_token(
                "default", "count-api", alloc_id=web_alloc.id)
            assert agent.server.mesh_identity_token(
                "default", "count-dashboard", alloc_id=web_alloc.id)
            with pytest.raises(PermissionError):
                agent.server.mesh_identity_token(
                    "default", "some-other-service",
                    alloc_id=web_alloc.id)
            with pytest.raises(PermissionError):
                agent.server.mesh_identity_token(
                    "default", "count-api", alloc_id="no-such-alloc")
        finally:
            agent.shutdown()

    def test_connect_native_gets_identity_env(self):
        """connect-native services skip the sidecar; the task gets the
        mesh identity token as env (connect_native_hook.go)."""
        agent = Agent(AgentConfig.dev())
        agent.start()
        try:
            job = mock.job()
            job.id = f"native-{job.id[-12:]}"
            job.constraints = []
            tg = job.task_groups[0]
            tg.count = 1
            tg.services = [Service(name="nativesvc",
                                   connect={"native": True})]
            task = tg.tasks[0]
            task.driver = "raw_exec"
            task.config = {
                "command": "/bin/sh",
                "args": ["-c", "echo -n \"$NOMAD_SI_TOKEN_NATIVESVC\" "
                         "> \"$NOMAD_ALLOC_DIR_HOST\"/token.out 2>/dev/null"
                         " || echo -n \"$NOMAD_SI_TOKEN_NATIVESVC\""],
            }
            agent.server.job_register(job)
            alloc = wait_for(
                lambda: next(
                    (a for a in agent.server.state.snapshot()
                     .allocs_by_job(job.namespace, job.id)), None),
                msg="alloc placed")
            runner = wait_for(
                lambda: agent.client.allocs.get(alloc.id),
                msg="alloc runner")
            conn = wait_for(lambda: runner.alloc_connect,
                            msg="connect state")
            token = agent.server.mesh_identity_token("default", "nativesvc")
            assert conn.env["NOMAD_SI_TOKEN_NATIVESVC"] == token
            assert not conn.proxies      # native: no sidecar processes
        finally:
            agent.shutdown()
