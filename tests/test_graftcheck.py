"""graftcheck (ISSUE 9): the AST rule engine, its rules' fixture
self-tests (positive + negative per rule), the tier-1 baseline gate,
and the runtime lock witness.

The gate test at the bottom is the enforcement point: graftcheck over
``nomad_tpu/`` must produce NO finding that is not in the committed
baseline (which ships empty), and no stale baseline entries — the
baseline may only shrink. The fix-regression tests pin the specific
lock-discipline repairs the initial sweep produced.
"""

import os
import subprocess
import sys
import threading
import time

import pytest

from tools.graftcheck.engine import (
    Engine,
    default_engine,
    load_baseline,
    repo_root,
)
from tools.graftcheck.rules_frozen import FrozenPlaneRule
from tools.graftcheck.rules_hygiene import (
    BareExceptRule,
    DeadLockRule,
    MutableDefaultRule,
    NonDaemonThreadRule,
)
from tools.graftcheck.rules_ipc import IpcBoundaryRule
from tools.graftcheck.rules_jit import JitHygieneRule
from tools.graftcheck.rules_locks import LockDisciplineRule
from tools.graftcheck.rules_store import StoreAccessRule
from tools.graftcheck.rules_telemetry import TelemetryDriftRule

REPO = repo_root()


def run_rule(rule, texts, extra=None):
    return Engine([rule]).run_texts(texts, extra_texts=extra)


def rules_of(findings):
    return [(f.rule, f.slug) for f in findings if not f.suppressed]


# ---------------------------------------------------------------------------
# engine mechanics


class TestEngine:
    def test_suppression_with_justification(self):
        src = (
            "import time, threading\n"
            "LOCK = threading.Lock()\n"
            "def f():\n"
            "    with LOCK:\n"
            "        time.sleep(1)  # graft: ok R2 - test fixture\n"
        )
        out = run_rule(LockDisciplineRule(), {"m.py": src})
        assert len(out) == 1
        assert out[0].suppressed
        assert out[0].justification == "test fixture"

    def test_suppression_without_justification_is_a_finding(self):
        src = (
            "import time, threading\n"
            "LOCK = threading.Lock()\n"
            "def f():\n"
            "    with LOCK:\n"
            "        time.sleep(1)  # graft: ok R2\n"
        )
        out = run_rule(LockDisciplineRule(), {"m.py": src})
        assert any("unjustified" in f.slug for f in out)
        assert not any(f.suppressed for f in out)

    def test_fingerprint_is_line_free(self):
        src = "LOCK = __import__('threading').Lock()\n" \
              "def f():\n    with LOCK:\n        import time\n" \
              "        time.sleep(1)\n"
        shifted = "\n\n\n" + src
        a = run_rule(LockDisciplineRule(), {"m.py": src})
        b = run_rule(LockDisciplineRule(), {"m.py": shifted})
        assert [f.fingerprint for f in a] == [f.fingerprint for f in b]
        assert a[0].line != b[0].line


# ---------------------------------------------------------------------------
# R1 frozen-plane mutation


R1_PRODUCER = (
    "import numpy as np\n"
    "def make_plane(n):  # graft: frozen\n"
    "    return np.zeros(n)\n"
    "def make_pair(n):  # graft: frozen\n"
    "    return np.zeros(n), np.zeros(n)\n"
)


class TestR1FrozenPlane:
    def _run(self, body):
        return rules_of(run_rule(FrozenPlaneRule(),
                                 {"m.py": R1_PRODUCER + body}))

    def test_subscript_assignment_flagged(self):
        out = self._run("def use(n):\n"
                        "    p = make_plane(n)\n"
                        "    p[0] = 1\n")
        assert out == [("R1", "mutate:p")]

    def test_augassign_and_fill_flagged(self):
        out = self._run("def use(n):\n"
                        "    p = make_plane(n)\n"
                        "    p += 1\n"
                        "    p.fill(0)\n")
        assert ("R1", "mutate:p") in out and len(out) == 2

    def test_copyto_and_tuple_unpack_flagged(self):
        out = self._run("def use(n):\n"
                        "    a, b = make_pair(n)\n"
                        "    np.copyto(b, a)\n")
        assert out == [("R1", "mutate:b")]

    def test_attribute_of_tainted_flagged(self):
        out = self._run("def use(n):\n"
                        "    planes = make_plane(n)\n"
                        "    planes.zeros[2] = 1\n")
        assert out == [("R1", "mutate:planes.zeros")]

    def test_rebinding_untaints_and_copy_is_fine(self):
        out = self._run("def use(n):\n"
                        "    p = make_plane(n)\n"
                        "    p = np.array(p)\n"     # copy-on-write
                        "    p[0] = 1\n"
                        "    q = make_plane(n).copy()\n")
        assert out == []

    def test_unannotated_producer_not_tracked(self):
        src = ("import numpy as np\n"
               "def plain(n):\n    return np.zeros(n)\n"
               "def use(n):\n    p = plain(n)\n    p[0] = 1\n")
        assert rules_of(run_rule(FrozenPlaneRule(), {"m.py": src})) == []

    def test_real_producers_annotated(self):
        """The live producer sites carry the annotation (the rule is
        only as good as its seeds)."""
        for rel, name in [
            ("nomad_tpu/ops/kernel.py", "def neutral_planes"),
            ("nomad_tpu/ops/kernel.py", "def neutral_step_planes"),
            ("nomad_tpu/scheduler/scaffold.py", "def lean_planes"),
        ]:
            text = open(os.path.join(REPO, rel)).read()
            i = text.index(name)
            line = text[i:text.index("\n", i)]
            prev = text[:i].rsplit("\n", 2)[-2]
            assert "graft: frozen" in line or "graft: frozen" in prev, \
                (rel, name)


# ---------------------------------------------------------------------------
# R2 lock discipline


class TestR2LockDiscipline:
    def _run(self, src):
        return rules_of(run_rule(LockDisciplineRule(), {"m.py": src}))

    def test_device_and_sleep_under_lock_flagged(self):
        src = ("import threading, time, jax\n"
               "class C:\n"
               "    def __init__(self):\n"
               "        self._lock = threading.Lock()\n"
               "    def f(self, x):\n"
               "        with self._lock:\n"
               "            jax.device_put(x)\n"
               "            time.sleep(0.1)\n")
        out = self._run(src)
        assert ("R2", "blocking:jax.device_put") in out
        assert ("R2", "blocking:time.sleep") in out

    def test_one_level_method_resolution(self):
        src = ("import threading, pickle\n"
               "class C:\n"
               "    def __init__(self):\n"
               "        self._lock = threading.Lock()\n"
               "    def helper(self, x):\n"
               "        return pickle.dumps(x)\n"
               "    def f(self, x):\n"
               "        with self._lock:\n"
               "            return self.helper(x)\n")
        out = self._run(src)
        assert any(s.startswith("blocking-via:helper") for _, s in out)

    def test_same_lock_condition_wait_ok(self):
        src = ("import threading\n"
               "class C:\n"
               "    def __init__(self):\n"
               "        self._lock = threading.Lock()\n"
               "        self._cond = threading.Condition(self._lock)\n"
               "    def f(self):\n"
               "        with self._lock:\n"
               "            self._cond.wait(1.0)\n")
        assert self._run(src) == []

    def test_foreign_wait_under_lock_flagged(self):
        src = ("import threading\n"
               "class C:\n"
               "    def __init__(self):\n"
               "        self._lock = threading.Lock()\n"
               "        self._done = threading.Event()\n"
               "    def f(self):\n"
               "        with self._lock:\n"
               "            self._done.wait()\n")
        out = self._run(src)
        assert out and out[0][1].startswith("blocking:self._done.wait")

    def test_work_outside_lock_ok(self):
        src = ("import threading, pickle\n"
               "LOCK = threading.Lock()\n"
               "def f(x):\n"
               "    data = pickle.dumps(x)\n"
               "    with LOCK:\n"
               "        return data\n")
        assert self._run(src) == []

    def test_lock_order_cycle_detected(self):
        src = ("import threading\n"
               "A_LOCK = threading.Lock()\n"
               "B_LOCK = threading.Lock()\n"
               "def f():\n"
               "    with A_LOCK:\n"
               "        with B_LOCK:\n"
               "            pass\n"
               "def g():\n"
               "    with B_LOCK:\n"
               "        with A_LOCK:\n"
               "            pass\n")
        out = self._run(src)
        assert any(s.startswith("lock-cycle:") for _, s in out)

    def test_consistent_order_no_cycle(self):
        src = ("import threading\n"
               "A_LOCK = threading.Lock()\n"
               "B_LOCK = threading.Lock()\n"
               "def f():\n"
               "    with A_LOCK:\n"
               "        with B_LOCK:\n"
               "            pass\n"
               "def g():\n"
               "    with A_LOCK:\n"
               "        with B_LOCK:\n"
               "            pass\n")
        assert self._run(src) == []


# ---------------------------------------------------------------------------
# R3 jit-boundary hygiene


class TestR3JitHygiene:
    def _run(self, src):
        return rules_of(run_rule(JitHygieneRule(), {"m.py": src}))

    def test_impure_call_in_jitted_fn_flagged(self):
        src = ("import jax, time\n"
               "def kernel(x):\n"
               "    time.monotonic()\n"
               "    return x\n"
               "kernel_jit = jax.jit(kernel)\n")
        out = self._run(src)
        assert ("R3", "impure:time.monotonic") in out

    def test_transitive_callee_checked(self):
        src = ("import jax, random\n"
               "def helper(x):\n"
               "    return x + random.random()\n"
               "def kernel(x):\n"
               "    return helper(x)\n"
               "kernel_jit = jax.jit(kernel)\n")
        out = self._run(src)
        assert ("R3", "impure:random.random") in out

    def test_mutable_global_read_flagged(self):
        src = ("import jax\n"
               "COUNTER = 0\n"
               "def bump():\n"
               "    global COUNTER\n"
               "    COUNTER += 1\n"
               "@jax.jit\n"
               "def kernel(x):\n"
               "    return x + COUNTER\n")
        out = self._run(src)
        assert ("R3", "mutable-global:COUNTER") in out

    def test_constant_global_and_unjitted_fn_ok(self):
        src = ("import jax, time\n"
               "SCALE = 4\n"
               "def host_helper():\n"
               "    return time.monotonic()\n"   # not jit-reachable
               "@jax.jit\n"
               "def kernel(x):\n"
               "    return x * SCALE\n")
        assert self._run(src) == []

    def test_real_kernels_clean(self):
        """The live jit roots (ops/kernel.py, tensors/device_state.py,
        parallel/*) pass R3 — the steady-state no-recompile promise."""
        texts = {}
        for rel in ("nomad_tpu/ops/kernel.py",
                    "nomad_tpu/tensors/device_state.py",
                    "nomad_tpu/parallel/batching.py",
                    "nomad_tpu/parallel/sharded.py"):
            texts[rel] = open(os.path.join(REPO, rel)).read()
        assert rules_of(run_rule(JitHygieneRule(), texts)) == []


# ---------------------------------------------------------------------------
# R4 store access


class TestR4StoreAccess:
    def _run(self, src, rel="nomad_tpu/server/x.py"):
        return rules_of(run_rule(StoreAccessRule(), {rel: src}))

    def test_raw_internal_flagged(self):
        src = ("class V:\n"
               "    def __init__(self, store):\n"
               "        self._store = store\n"
               "    def f(self, nid):\n"
               "        with self._store._lock:\n"
               "            return self._store._nodes.get(nid)\n")
        out = self._run(src)
        assert ("R4", "internal:_store._lock") in out
        assert ("R4", "internal:_store._nodes") in out

    def test_accessors_ok(self):
        src = ("def f(store, nid):\n"
               "    return store.node_by_id_direct(nid)\n")
        assert self._run(src) == []

    def test_store_module_itself_exempt(self):
        src = ("class StateStore:\n"
               "    def f(self, state_store):\n"
               "        return state_store._nodes\n")
        assert self._run(src, rel="nomad_tpu/state/store.py") == []

    def test_mvcc_internals_flagged(self):
        src = ("def f(store):\n"
               "    return store._root.tables\n")
        assert ("R4", "internal:store._root") in self._run(src)

    def test_snapshot_row_attribute_write_flagged(self):
        # the exact shape of the seed set_job_stability bug: a row read
        # off a snapshot is shared across generations — writing an
        # attribute in place corrupts history for every holder
        src = ("def f(store, nid):\n"
               "    snap = store.snapshot()\n"
               "    node = snap.node_by_id(nid)\n"
               "    node.status = 'down'\n")
        assert ("R4", "snapshot-mutate:node") in self._run(src)

    def test_direct_reader_row_mutation_flagged(self):
        src = ("def f(store, nid):\n"
               "    node = store.node_by_id_direct(nid)\n"
               "    node.meta.update({'k': 'v'})\n")
        assert ("R4", "snapshot-mutate:node.meta") in self._run(src)

    def test_copy_launders_taint(self):
        # .copy() is the sanctioned copy-on-write move: the copy is
        # caller-owned and free to mutate before the write txn
        src = ("def f(store, nid):\n"
               "    node = store.node_by_id_direct(nid)\n"
               "    mine = node.copy()\n"
               "    mine.status = 'down'\n"
               "    return mine\n")
        assert self._run(src) == []

    def test_rebinding_untaints(self):
        src = ("def f(store, nid):\n"
               "    snap = store.snapshot()\n"
               "    snap = {}\n"
               "    snap['k'] = 1\n")
        assert self._run(src) == []


# ---------------------------------------------------------------------------
# R6 IPC boundary


class TestR6IpcBoundary:
    IMPORT = "from nomad_tpu.utils.ipc import Channel\n"

    def _run(self, src, rel="nomad_tpu/server/wp.py"):
        return rules_of(run_rule(IpcBoundaryRule(), {rel: src}))

    def test_lock_in_send_payload_flagged(self):
        src = (self.IMPORT +
               "class H:\n"
               "    def f(self):\n"
               "        self.chan.send({'t': 'x', 'l': self._lock})\n")
        assert ("R6", "ipc-send:self._lock") in self._run(src)

    def test_witness_and_tracer_handles_flagged(self):
        src = (self.IMPORT +
               "def f(chan, witness_lock, tracer):\n"
               "    chan.send([witness_lock])\n"
               "    chan.send({'h': tracer})\n")
        out = self._run(src)
        assert ("R6", "ipc-send:witness_lock") in out
        assert ("R6", "ipc-send:tracer") in out

    def test_device_and_process_objects_flagged(self):
        src = (self.IMPORT +
               "def f(chan, h):\n"
               "    chan.send({'m': h.wave_mesh})\n"
               "    chan.send((h.proc, 1))\n"
               "    chan.send({'s': h.sock})\n")
        out = self._run(src)
        assert ("R6", "ipc-send:h.wave_mesh") in out
        assert ("R6", "ipc-send:h.proc") in out
        assert ("R6", "ipc-send:h.sock") in out

    def test_constructed_denylisted_object_flagged(self):
        src = (self.IMPORT +
               "import threading\n"
               "import jax.numpy as jnp\n"
               "def f(chan):\n"
               "    chan.send(threading.Lock())\n"
               "    chan.send({'a': jnp.zeros(4)})\n")
        out = self._run(src)
        assert ("R6", "ipc-send:threading.Lock()") in out
        assert ("R6", "ipc-send:jnp.zeros()") in out

    def test_plain_data_and_serializer_shims_ok(self):
        # the production message shapes: rows from drain_rows(), ids,
        # stamps, conditional None — call results are presumed data
        src = (self.IMPORT +
               "def f(chan, tracer, eid, token, stamps, batch):\n"
               "    chan.send({'t': 'lease', 'evals': batch,\n"
               "               'stamps': stamps, 'trace': tracer.enabled})\n"
               "    chan.send({'t': 'ack', 'eval_id': eid,\n"
               "               'token': token,\n"
               "               'spans': tracer.drain_rows()\n"
               "               if tracer.enabled else None})\n")
        assert self._run(src) == []

    def test_non_channel_send_not_flagged(self):
        # membership/transport sockets have their own send(); the rule
        # only polices channel-ish receivers
        src = (self.IMPORT +
               "def f(sock, data, lock):\n"
               "    sock.send(lock)\n")
        assert self._run(src) == []

    def test_file_without_ipc_import_not_scanned(self):
        src = ("def f(chan, lock):\n"
               "    chan.send(lock)\n")
        assert self._run(src) == []


# ---------------------------------------------------------------------------
# R5 telemetry drift


R5_DOC = """# T
## Instrumented spans
```
eval.schedule   one eval
wave.launch     firing member
```
## Prometheus series
```
nomad_tpu_latency_seconds   histogram
```
## Bench emission keys
```
trace_per_eval_ms   per-eval ms
```
"""

R5_SRC = (
    "from nomad_tpu.telemetry.trace import tracer\n"
    "def f():\n"
    "    with tracer.span('eval.schedule'):\n"
    "        tracer.record(\"wave.launch\", 1.0)\n"
    "    x = 'nomad_tpu_latency_seconds'\n"
)

R5_BENCH = "def emit(**kw): pass\nemit(trace_per_eval_ms=1.0)\n"


class TestR5TelemetryDrift:
    def _run(self, src=R5_SRC, doc=R5_DOC, bench=R5_BENCH):
        return rules_of(run_rule(
            TelemetryDriftRule(), {"nomad_tpu/x.py": src},
            extra={"docs/TELEMETRY.md": doc, "bench.py": bench}))

    def test_in_sync_passes(self):
        assert self._run() == []

    def test_undocumented_span_flagged(self):
        src = R5_SRC.replace("wave.launch", "wave.newstage")
        out = self._run(src=src)
        assert ("R5", "span-undocumented:wave.newstage") in out
        assert ("R5", "span-stale:wave.launch") in out

    def test_stale_prom_series_flagged(self):
        src = R5_SRC.replace("nomad_tpu_latency_seconds", "plain")
        out = self._run(src=src)
        assert ("R5", "span-stale:nomad_tpu_latency_seconds") not in out
        assert ("R5", "prom-stale:nomad_tpu_latency_seconds") in out

    def test_undocumented_prom_series_flagged(self):
        src = R5_SRC + "y = 'nomad_tpu_new_series_total'\n"
        out = self._run(src=src)
        assert ("R5", "prom-undocumented:nomad_tpu_new_series_total") in out

    def test_bench_key_drift_both_directions(self):
        out = self._run(bench="def emit(**kw): pass\n"
                              "emit(trace_new_key=1)\n")
        assert ("R5", "bench-undocumented:trace_new_key") in out
        assert ("R5", "bench-stale:trace_per_eval_ms") in out

    def test_unregistered_dynamic_span_flagged(self):
        src = ("from nomad_tpu.telemetry.trace import tracer\n"
               "def f(stage):\n"
               "    tracer.record(f'custom.{stage}', 1.0)\n")
        out = self._run(src=R5_SRC + src)
        assert any(s.startswith("span-dynamic:custom.{}") for _, s in out)

    def test_bg_prefix_exempt(self):
        src = R5_SRC + ("def g(name):\n"
                        "    tracer.record(f'bg.{name}', 1.0)\n")
        assert self._run(src=src) == []

    def test_real_repo_in_sync(self):
        """The replacement for PR 8's TestSpanNameDriftGuard: the live
        tree vs the live docs, spans + Prometheus series + bench keys,
        both directions."""
        texts = {}
        for dirpath, dirs, files in os.walk(os.path.join(REPO,
                                                         "nomad_tpu")):
            dirs[:] = [d for d in dirs if d != "__pycache__"]
            for fn in files:
                if fn.endswith(".py"):
                    p = os.path.join(dirpath, fn)
                    texts[os.path.relpath(p, REPO)] = open(p).read()
        out = run_rule(TelemetryDriftRule(), texts)
        assert rules_of(out) == [], [f.render() for f in out]
        # sanity: the scan actually saw the hot path
        from tools.graftcheck.engine import Context, SourceFile
        ctx = Context([SourceFile(rel, t) for rel, t in texts.items()],
                      REPO)
        emitted, _ = TelemetryDriftRule()._emitted_spans(ctx)
        assert "eval.schedule" in emitted and "eval.e2e" in emitted


# ---------------------------------------------------------------------------
# stock hygiene


class TestHygiene:
    def test_mutable_default_flagged_and_none_ok(self):
        src = ("def f(a, b=[], c={}):\n    pass\n"
               "def g(a, b=None, c=()):\n    pass\n")
        out = rules_of(run_rule(MutableDefaultRule(), {"m.py": src}))
        assert len(out) == 2 and all(r == "H1" for r, _ in out)

    def test_bare_except_flagged_typed_ok(self):
        src = ("def f():\n"
               "    try:\n        pass\n"
               "    except:\n        pass\n"
               "def g():\n"
               "    try:\n        pass\n"
               "    except Exception:\n        pass\n")
        out = rules_of(run_rule(BareExceptRule(), {"m.py": src}))
        assert out == [("H2", "bare-except")]

    def test_non_daemon_thread_flagged(self):
        src = ("import threading\n"
               "def f():\n"
               "    t = threading.Thread(target=f)\n"
               "    t.start()\n")
        out = rules_of(run_rule(NonDaemonThreadRule(), {"m.py": src}))
        assert out == [("H3", "non-daemon-thread")]

    def test_daemon_kw_or_attr_ok(self):
        src = ("import threading\n"
               "def f():\n"
               "    a = threading.Thread(target=f, daemon=True)\n"
               "    b = threading.Thread(target=f)\n"
               "    b.daemon = True\n")
        assert rules_of(run_rule(NonDaemonThreadRule(),
                                 {"m.py": src})) == []

    def test_dead_lock_flagged_used_ok(self):
        src = ("import threading\n"
               "class C:\n"
               "    def __init__(self):\n"
               "        self.dead = threading.Lock()\n"
               "        self.live = threading.Lock()\n"
               "    def f(self):\n"
               "        with self.live:\n"
               "            pass\n")
        out = rules_of(run_rule(DeadLockRule(), {"m.py": src}))
        assert out == [("H4", "dead-lock:C.dead")]


# ---------------------------------------------------------------------------
# the tier-1 gate


class TestGate:
    def test_nomad_tpu_clean_against_baseline(self):
        """THE gate: graftcheck over nomad_tpu/ has no finding outside
        the committed baseline, and the baseline carries no stale
        entries (it may only shrink)."""
        findings = default_engine().run_paths(["nomad_tpu"], REPO)
        active = {f.fingerprint: f for f in findings if not f.suppressed}
        baseline = load_baseline(
            os.path.join(REPO, "tools", "graftcheck", "baseline.txt"))
        new = [f.render() for fp, f in sorted(active.items())
               if fp not in baseline]
        assert not new, "\n".join(
            ["graftcheck found NEW findings (fix them or justify an "
             "inline suppression; see docs/ANALYSIS.md):"] + new)
        stale = sorted(baseline - set(active))
        assert not stale, (
            f"baseline entries whose findings no longer exist — the "
            f"baseline may only shrink, delete them: {stale}")

    def test_suppressions_all_justified(self):
        findings = default_engine().run_paths(["nomad_tpu"], REPO)
        for f in findings:
            if f.suppressed:
                assert f.justification, f.render()

    def test_cli_exits_clean(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.graftcheck", "nomad_tpu"],
            cwd=REPO, capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# the runtime lock witness


class TestLockWitness:
    @pytest.fixture(autouse=True)
    def _clean_witness(self):
        from nomad_tpu.utils import witness
        witness.reset()
        witness.enable()
        yield witness
        witness.disable()
        witness.reset()

    def test_inversion_detected(self):
        """The acceptance self-test: an injected A→B / B→A inversion
        is detected and reported."""
        from nomad_tpu.utils import witness
        A = witness.witness_lock("selftest.A")
        B = witness.witness_lock("selftest.B")
        with A:
            with B:
                pass
        with B:
            with A:
                pass
        v = witness.violations()
        assert len(v) == 1
        held, acquiring, path, _thread = v[0]
        assert (held, acquiring) == ("selftest.B", "selftest.A")
        assert path[0] == "selftest.A" and path[-1] == "selftest.A"

    def test_transitive_inversion_detected(self):
        from nomad_tpu.utils import witness
        A = witness.witness_lock("t.A")
        B = witness.witness_lock("t.B")
        C = witness.witness_lock("t.C")
        with A:
            with B:
                pass
        with B:
            with C:
                pass
        with C:
            with A:
                pass
        assert len(witness.violations()) == 1

    def test_same_name_cross_instance_nesting_flagged(self):
        """Two DIFFERENT instances under one witness name cannot hide
        behind the reentrancy skip: nesting them is flagged
        (DUPOK-style) unless the name is sanctioned."""
        from nomad_tpu.utils import witness
        A1 = witness.witness_lock("dup.L")
        A2 = witness.witness_lock("dup.L")
        with A1:
            with A2:
                pass
        v = witness.violations()
        assert v and v[0][2] == ("DUPOK", "dup.L")

    def test_consistent_order_clean_across_threads(self):
        from nomad_tpu.utils import witness
        A = witness.witness_lock("c.A")
        B = witness.witness_lock("c.B")

        def worker():
            for _ in range(50):
                with A:
                    with B:
                        pass

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10)
        assert witness.violations() == []
        assert "c.B" in witness.order_edges().get("c.A", set())

    def test_hold_times_feed_histograms(self):
        from nomad_tpu.telemetry.histogram import histograms
        from nomad_tpu.utils import witness
        L = witness.witness_lock("held.L")
        before = histograms.get("lock_hold_held.L").count
        with L:
            time.sleep(0.001)
        h = histograms.get("lock_hold_held.L")
        assert h.count == before + 1

    def test_disabled_returns_plain_lock(self):
        from nomad_tpu.utils import witness
        witness.disable()
        lk = witness.witness_lock("plain.L")
        assert type(lk) is type(threading.Lock())
        witness.enable()

    def test_condition_wait_keeps_bookkeeping(self):
        from nomad_tpu.utils import witness
        L = witness.witness_lock("cond.L")
        cond = threading.Condition(L)
        hit = []

        def waiter():
            with cond:
                cond.wait(5.0)
                hit.append(1)

        t = threading.Thread(target=waiter, daemon=True)
        t.start()
        time.sleep(0.05)
        with cond:
            cond.notify_all()
        t.join(5)
        assert hit == [1]
        assert witness.violations() == []

    def test_raise_mode(self, monkeypatch):
        from nomad_tpu.utils import witness
        monkeypatch.setattr(witness, "_RAISE", True)
        A = witness.witness_lock("r.A")
        B = witness.witness_lock("r.B")
        with A:
            with B:
                pass
        with pytest.raises(witness.WitnessInversion):
            with B:
                with A:
                    pass


# ---------------------------------------------------------------------------
# regression tests for the R2/R4 fixes the initial sweep produced


class TestR2FixRegressions:
    def test_broker_tokens_unique_without_rng(self):
        """eval_broker fix: delivery tokens come from a per-broker
        counter, not per-eval generate_uuid() under the broker lock."""
        from nomad_tpu import mock
        from nomad_tpu.server.eval_broker import EvalBroker
        import nomad_tpu.structs.eval_plan as ep

        broker = EvalBroker(nack_timeout=0)
        broker.set_enabled(True)
        try:
            for i in range(20):
                ev = mock.eval()
                ev.job_id = f"job-{i}"
                broker.enqueue(ev)
            import nomad_tpu.server.eval_broker as broker_mod

            calls = []
            orig = ep.generate_uuid

            def counting_uuid():
                calls.append(1)
                return orig()

            ep.generate_uuid = counting_uuid
            broker_mod.generate_uuid = counting_uuid
            try:
                batch = broker.dequeue_batch(["service"], 20,
                                             timeout=5.0)
            finally:
                ep.generate_uuid = orig
                broker_mod.generate_uuid = orig
            tokens = [tok for _, tok in batch]
            assert len(batch) == 20
            assert len(set(tokens)) == 20
            assert not calls, "dequeue still generates uuids per eval"
            for ev, tok in batch:
                broker.ack(ev.id, tok)      # tokens still correlate
        finally:
            broker.set_enabled(False)

    def test_wavetopk_fetch_runs_off_lock_and_once(self):
        """coalesce fix: the d2h fetch happens outside _WaveTopK._lock
        and exactly once for any number of concurrent readers."""
        from nomad_tpu.parallel.coalesce import _WaveTopK

        fetches = []
        holder = {}

        class SlowDev:
            def __init__(self, val):
                self.val = val

            def __array__(self, dtype=None, copy=None):
                import numpy as np
                assert not holder["wt"]._lock.locked(), \
                    "device fetch ran under the lock"
                fetches.append(1)
                time.sleep(0.02)
                return np.full(4, self.val)

        wt = _WaveTopK(SlowDev(1), SlowDev(2))
        holder["wt"] = wt
        results = []
        threads = [threading.Thread(
            target=lambda: results.append(wt.host()))
            for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10)
        assert len(results) == 8
        assert all(r is results[0] for r in results)
        assert len(fetches) == 2        # idx + scores, fetched once

    def test_store_snapshot_bytes_pickles_off_lock(self):
        """store fix (now structural): to_snapshot_bytes pins one MVCC
        root and serializes it without EVER taking the write lock —
        writers keep committing during a big dump."""
        import nomad_tpu.state.store as store_mod
        from nomad_tpu.state.store import StateStore

        store = StateStore()
        seen = []
        orig = store_mod.pickle.dumps

        def checking_dumps(obj, *a, **kw):
            seen.append(store._write_lock._is_owned())
            return orig(obj, *a, **kw)

        store_mod.pickle = type("P", (), {
            "dumps": staticmethod(checking_dumps),
            "loads": staticmethod(store_mod.pickle.loads)})
        try:
            data = store.to_snapshot_bytes()
        finally:
            import pickle
            store_mod.pickle = pickle
        assert data and seen == [False]

    def test_group_checker_folds_off_store_lock(self):
        """plan_apply fix (now structural): _GroupFitChecker reads one
        MVCC root — the fold never holds the store's write lock."""
        from nomad_tpu import mock
        from nomad_tpu.server.plan_apply import (
            _GroupFitChecker,
            _PlanOverlay,
        )
        from nomad_tpu.state.store import StateStore
        from nomad_tpu.structs.eval_plan import PlanResult

        store = StateStore()
        node = mock.node()
        store.upsert_node(node)
        alloc = mock.alloc(node_id=node.id)
        store.upsert_allocs([alloc])
        overlay = _PlanOverlay()
        overlay.add(PlanResult(
            node_update={node.id: [alloc]}, node_allocation={},
            node_preemptions={}))
        owned_during_fold = []
        orig = _GroupFitChecker._fold_result

        def checking_fold(self, r, rows):
            owned_during_fold.append(store._write_lock._is_owned())
            return orig(self, r, rows)

        _GroupFitChecker._fold_result = checking_fold
        try:
            checker = _GroupFitChecker(store, overlay)
        finally:
            _GroupFitChecker._fold_result = orig
        assert checker.ok
        assert owned_during_fold == [False]

    def test_liveview_uses_store_accessors(self):
        """plan_apply R4 fix: _LiveView reads through the *_direct
        accessors; functionally, a node's rows and the overlay merge
        still come back right."""
        from nomad_tpu import mock
        from nomad_tpu.server.plan_apply import _LiveView
        from nomad_tpu.state.store import StateStore

        store = StateStore()
        node = mock.node()
        store.upsert_node(node)
        alloc = mock.alloc(node_id=node.id)
        store.upsert_allocs([alloc])
        view = _LiveView(store)
        assert view.node_by_id(node.id) is store.node_by_id_direct(node.id)
        got = view.allocs_by_node(node.id)
        assert [a.id for a in got] == [alloc.id]

    def test_ott_exchange_raft_delete_off_lock(self):
        """server fix: the raft delete runs outside _ott_lock while the
        claim set keeps the exchange single-use (functional single-use
        coverage lives in tests/test_operator.py)."""
        from nomad_tpu.acl.policy import ACLToken
        from nomad_tpu.server.server import Server, ServerConfig

        srv = Server(ServerConfig(num_workers=0))
        srv.start()
        try:
            token = ACLToken.create(name="ops", type="management")
            srv.raft_apply("ACLTokenUpsertRequestType",
                           {"tokens": [token]})
            ott = srv.create_one_time_token(token.accessor_id)
            locked_during_delete = []
            orig = srv.raft_apply

            def checking_apply(kind, req):
                if "OneTimeTokenDelete" in str(kind):
                    locked_during_delete.append(
                        srv._ott_lock.locked())
                return orig(kind, req)

            srv.raft_apply = checking_apply
            try:
                got = srv.exchange_one_time_token(
                    ott["one_time_secret_id"])
            finally:
                srv.raft_apply = orig
            assert got.accessor_id == token.accessor_id
            assert locked_during_delete == [False]
            with pytest.raises(ValueError):
                srv.exchange_one_time_token(ott["one_time_secret_id"])
        finally:
            srv.shutdown()

    def test_frozen_upload_off_registry_lock(self):
        """device_state fix: a first-sight frozen upload runs outside
        the registry lock; concurrent lookups upload once."""
        import numpy as np
        from nomad_tpu.tensors.device_state import DeviceClusterState

        ds = DeviceClusterState()
        arr = np.zeros(16, np.float32)
        arr.setflags(write=False)
        uploads = []
        orig = DeviceClusterState._upload

        def checking_upload(self, planes, sharding=None):
            assert not self._lock.locked(), "upload ran under the lock"
            uploads.append(1)
            time.sleep(0.01)
            return orig(self, planes, sharding=sharding)

        DeviceClusterState._upload = checking_upload
        try:
            out = []
            threads = [threading.Thread(
                target=lambda: out.append(ds.lookup(arr)))
                for _ in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(10)
        finally:
            DeviceClusterState._upload = orig
        assert len(uploads) == 1
        assert all(o is out[0] and o is not None for o in out)

    def test_state_db_pickles_before_lock(self, tmp_path):
        """client StateDB fix: row serialization happens before the
        sqlite connection lock is taken."""
        import nomad_tpu.client.state_db as sdb
        from nomad_tpu import mock

        db = sdb.StateDB(str(tmp_path / "state.db"))
        alloc = mock.alloc()
        locked = []
        orig = sdb.pickle.dumps

        def checking_dumps(obj, *a, **kw):
            locked.append(db._lock.locked())
            return orig(obj, *a, **kw)

        real_pickle = sdb.pickle
        sdb.pickle = type("P", (), {
            "dumps": staticmethod(checking_dumps),
            "loads": staticmethod(real_pickle.loads)})
        try:
            db.put_allocation(alloc)
            db.put_meta("k", {"v": 1})
        finally:
            sdb.pickle = real_pickle
        assert locked and not any(locked)
        assert [a.id for a in db.get_allocations()] == [alloc.id]
        assert db.get_meta("k") == {"v": 1}

    def test_membership_seal_off_lock(self):
        """membership fix: datagram serialization happens outside the
        membership lock."""
        from nomad_tpu.server.membership import Membership

        m = Membership(name="w1", probe_interval=60.0)
        try:
            sealed_locked = []
            orig = Membership._seal

            def checking_seal(self, msg):
                sealed_locked.append(self._lock.locked())
                return orig(self, msg)

            Membership._seal = checking_seal
            try:
                m.leave()
            finally:
                Membership._seal = orig
            assert sealed_locked == [False]
        finally:
            m.shutdown(leave=False)
