"""Tail-latency observability (ISSUE 8): streaming histograms,
per-eval critical-path waterfalls, and the slow-eval flight recorder.

Covers the acceptance surface directly:
- histogram quantile estimates vs numpy.percentile within the bucket
  relative-error bound; merge associativity; concurrent-record thread
  safety; bounded memory
- the shared nearest-rank ``percentile`` helper (the unified p50/p99
  math — including the ``int(len*0.99)`` off-by-one it fixes)
- flight recorder: bounded ring, adaptive (EWMA-of-p99) threshold,
  no captures when tracing is disabled
- waterfall reduction: segment claims, applier-envelope overlap,
  coverage accounting, p50-vs-p99 aggregation

(The span-name drift guard moved to graftcheck R5 — see
tests/test_graftcheck.py and docs/ANALYSIS.md.)
"""

import math
import os
import random
import re
import threading

import numpy as np
import pytest

from nomad_tpu import telemetry
from nomad_tpu.telemetry.histogram import (
    BOUNDS,
    GROWTH,
    N_BUCKETS,
    LatencyHistogram,
    histograms,
    percentile,
)
from nomad_tpu.telemetry.trace import FlightRecorder, Span, tracer
from nomad_tpu.telemetry.waterfall import (
    aggregate_tail,
    build_waterfall,
    build_waterfalls,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def clean_telemetry():
    telemetry.enable()
    telemetry.reset()
    yield
    telemetry.disable()
    telemetry.reset()


class TestPercentile:
    def test_nearest_rank_semantics(self):
        vals = list(range(1, 101))           # 1..100
        random.Random(3).shuffle(vals)
        assert percentile(vals, 0.5) == 50
        # the off-by-one the shared helper fixes: int(100*0.99) == 99
        # indexed the MAX; nearest-rank p99 of 1..100 is the 99th value
        assert percentile(vals, 0.99) == 99
        assert percentile(vals, 1.0) == 100
        assert percentile(vals, 0.0) == 1
        assert percentile(vals, 0.01) == 1

    def test_empty_and_single(self):
        assert percentile([], 0.5) == 0.0
        assert percentile([7.0], 0.99) == 7.0

    def test_matches_numpy_nearest_on_random_samples(self):
        rng = random.Random(11)
        for n in (3, 10, 97, 500):
            vals = [rng.lognormvariate(0, 1) for _ in range(n)]
            for q in (0.1, 0.5, 0.9, 0.99):
                exact = percentile(vals, q)
                lo = float(np.percentile(vals, q * 100, method="lower"))
                hi = float(np.percentile(vals, q * 100,
                                         method="higher"))
                assert lo <= exact <= hi


class TestHistogram:
    def test_quantiles_within_bucket_error_bound_vs_numpy(self):
        """Property: estimates land within the bucket geometry's
        relative-error bound of numpy.percentile, across shapes."""
        rng = random.Random(1234)
        cases = [
            [rng.lognormvariate(-4, 1.2) for _ in range(4000)],
            [rng.uniform(1e-4, 2.0) for _ in range(3000)],
            [rng.expovariate(10.0) + 1e-5 for _ in range(2500)],
        ]
        for vals in cases:
            h = LatencyHistogram("t")
            for v in vals:
                h.record(v)
            for q in (0.5, 0.9, 0.99):
                est = h.quantile(q)
                ref = float(np.percentile(vals, q * 100))
                # bucket midpoint error ≤ sqrt(G)-1; allow the full
                # bucket width for rank-definition differences
                assert abs(est - ref) / ref <= GROWTH - 1.0, \
                    (q, est, ref)

    def test_exact_error_bound_vs_nearest_rank(self):
        """Against the histogram's own rank definition the bound is
        the tight one: sqrt(GROWTH) - 1."""
        rng = random.Random(7)
        vals = [rng.lognormvariate(-3, 1.5) for _ in range(5000)]
        h = LatencyHistogram("t")
        for v in vals:
            h.record(v)
        for q in (0.25, 0.5, 0.75, 0.9, 0.99):
            est = h.quantile(q)
            exact = percentile(vals, q)
            assert abs(est - exact) / exact \
                <= math.sqrt(GROWTH) - 1.0 + 1e-9, (q, est, exact)

    def test_merge_is_associative_and_commutative(self):
        rng = random.Random(5)
        parts = []
        for _ in range(3):
            h = LatencyHistogram("p")
            for _ in range(500):
                h.record(rng.expovariate(100.0))
            parts.append(h)

        def fold(order):
            acc = LatencyHistogram("acc")
            for i in order:
                acc.merge(parts[i])
            return acc

        a = fold([0, 1, 2])
        b = fold([2, 0, 1])
        c = fold([1, 2, 0])
        assert a._counts == b._counts == c._counts
        assert a.count == b.count == c.count == 1500
        assert abs(a.sum_s - b.sum_s) < 1e-9
        assert a.quantile(0.99) == b.quantile(0.99) == c.quantile(0.99)

    def test_concurrent_record_is_thread_safe(self):
        h = LatencyHistogram("c")
        n_threads, per_thread = 8, 5000

        def work(k):
            rng = random.Random(k)
            for _ in range(per_thread):
                h.record(rng.uniform(1e-4, 1e-1))

        threads = [threading.Thread(target=work, args=(k,))
                   for k in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert h.count == n_threads * per_thread
        assert sum(h._counts) == n_threads * per_thread

    def test_bounded_memory_and_overflow(self):
        h = LatencyHistogram("b")
        for v in (0.0, 1e-9, 1e-6, 1.0, 1e5, 1e9):
            h.record(v)
        assert len(h._counts) == N_BUCKETS + 1
        # extremes land in the edge buckets, never grow the table
        assert h._counts[0] >= 3          # 0, 1e-9, 1e-6
        assert h._counts[N_BUCKETS] >= 1  # 1e9 overflow
        assert h.quantile(1.0) == 1e9     # overflow reports the max

    def test_prometheus_lines_shape(self):
        h = LatencyHistogram("e")
        for v in (0.001, 0.002, 0.004, 0.5):
            h.record(v)
        lines = h.prometheus_lines("m", 'op="x"')
        assert lines[-1] == 'm_count{op="x"} 4'
        assert lines[-2].startswith('m_sum{op="x"} 0.507')
        assert lines[-3] == 'm_bucket{op="x",le="+Inf"} 4'
        # cumulative counts are non-decreasing
        cums = [int(ln.rsplit(" ", 1)[1]) for ln in lines[:-2]]
        assert cums == sorted(cums)
        # le bounds parse and are increasing
        les = [float(re.search(r'le="([^"]+)"', ln).group(1))
               for ln in lines[:-3]]
        assert les == sorted(les)
        assert all(le in [round(b, 12) or b for b in BOUNDS] or True
                   for le in les)

    def test_registry_get_reset(self):
        telemetry.reset()
        h = histograms.get("unit_reg")
        h.record(0.5)
        assert histograms.get("unit_reg") is h
        assert histograms.snapshot()["unit_reg"]["count"] == 1
        telemetry.reset()                 # telemetry.reset clears it
        assert h.count == 0


class TestFlightRecorder:
    def _feed(self, fr, e2e_hist, value, trace_id="t"):
        e2e_hist.record(value)
        return fr.observe(trace_id, value)

    def test_bounded_ring_and_span_cap(self, clean_telemetry):
        fr = FlightRecorder(capacity=4)
        fr.min_capture_interval_s = 0.0   # rapid-fire in-test captures
        e2e = histograms.get("e2e")
        # arm: uniform fast traffic
        for i in range(fr.MIN_SAMPLES):
            self._feed(fr, e2e, 0.010, f"warm-{i}")
        # slow evals with real span trees
        captured = 0
        for i in range(12):
            tid = f"slow-{i}"
            with tracer.span("eval.schedule", trace_id=tid):
                pass
            if self._feed(fr, e2e, 1.0 + i, tid):
                captured += 1
        assert captured >= 1
        assert fr.captured == captured
        trees = fr.trees()
        assert len(trees) <= 4            # ring bound
        for tree in trees:
            assert tree["Spans"]
            assert len(tree["Spans"]) <= fr.MAX_SPANS_PER_TREE
            assert tree["E2eMs"] >= tree["ThresholdMs"]

    def test_threshold_tracks_p99_ewma(self, clean_telemetry):
        fr = FlightRecorder()
        e2e = histograms.get("e2e")
        for i in range(64):
            self._feed(fr, e2e, 0.010, f"a-{i}")
        thr_fast = fr.threshold_s()
        assert thr_fast is not None
        # ~10ms p99 (within bucket error)
        assert 0.005 <= thr_fast <= 0.02
        # the workload slows 20x: the EWMA follows the new p99 up
        for i in range(400):
            self._feed(fr, e2e, 0.200, f"b-{i}")
        assert fr.threshold_s() > thr_fast * 2

    def test_no_capture_when_disarmed_or_disabled(self, clean_telemetry):
        fr = FlightRecorder()
        e2e = histograms.get("e2e")
        # disarmed: below MIN_SAMPLES nothing captures, however slow
        assert not self._feed(fr, e2e, 10.0, "early")
        telemetry.disable()
        for i in range(fr.MIN_SAMPLES + 8):
            self._feed(fr, e2e, 0.01, f"w-{i}")
        # tracing off: no span trees exist, observe must not capture
        assert not self._feed(fr, e2e, 50.0, "slow-no-trace")
        assert fr.captured == 0

    def test_capture_rate_limit(self, clean_telemetry):
        """Captures are throttled: the recorder runs on the eval
        threads it measures and must not become the tail it records
        (burst of threshold-crossers -> one capture per interval)."""
        fr = FlightRecorder()
        fr.min_capture_interval_s = 10.0
        e2e = histograms.get("e2e")
        for i in range(fr.MIN_SAMPLES):
            self._feed(fr, e2e, 0.01, f"w-{i}")
        for i in range(8):
            tid = f"s-{i}"
            with tracer.span("eval.schedule", trace_id=tid):
                pass
            self._feed(fr, e2e, 2.0 + i, tid)
        assert fr.captured == 1

    def test_reset_clears_everything(self, clean_telemetry):
        fr = FlightRecorder()
        e2e = histograms.get("e2e")
        for i in range(fr.MIN_SAMPLES + 4):
            self._feed(fr, e2e, 0.01, f"x-{i}")
        assert fr.snapshot()["observed"] > 0
        fr.reset()
        snap = fr.snapshot()
        assert snap == {"observed": 0, "captured": 0, "retained": 0,
                        "threshold_ms": 0.0}


def _span(name, trace_id, start, dur, span_id=0, parent=0):
    return Span(name, trace_id, span_id, parent, start, dur,
                0.0, 0.0, 0.0, "t")


class TestWaterfall:
    def _spans(self, tid="ev1", base=0.0):
        return [
            _span("eval.e2e", tid, base + 0.000, 0.100),
            _span("eval.schedule", tid, base + 0.010, 0.080),
            _span("wave.park", tid, base + 0.020, 0.030),
            _span("wave.launch", tid, base + 0.050, 0.020),
            _span("plan.wait", tid, base + 0.070, 0.020),
            _span("plan.queue_wait", tid, base + 0.070, 0.004),
        ]

    def _globals(self, base=0.0):
        return [
            _span("plan.evaluate", "", base + 0.074, 0.006),
            _span("plan.commit", "", base + 0.080, 0.008),
            _span("fsm.apply", "", base + 0.082, 0.004),
        ]

    def test_segment_claims(self):
        wf = build_waterfall(self._spans(), self._globals())
        assert wf is not None
        segs = wf["segments"]
        approx = lambda a, b: abs(a - b) < 1e-9     # noqa: E731
        assert approx(wf["e2e_s"], 0.100)
        assert approx(segs["dequeue-wait"], 0.010)
        # schedule = envelope minus park/launch/plan-wait-window claims
        assert approx(segs["schedule"], 0.010)
        assert approx(segs["park"], 0.030)
        assert approx(segs["launch"], 0.020)
        assert approx(segs["plan-queue"], 0.004)
        assert approx(segs["evaluate"], 0.006)
        # fsm claims inside the commit envelope first
        assert approx(segs["fsm"], 0.004)
        assert approx(segs["commit"], 0.004)
        # plan.wait residue after queue/evaluate/commit/fsm claims
        assert approx(segs["plan-wait"], 0.002)
        # 0.090..0.100 (after schedule, before commit stamp) unclaimed
        assert approx(segs["other"], 0.010)
        assert approx(wf["covered_s"], 0.090)
        assert approx(wf["coverage"], 0.90)
        # claims partition the window: segments sum to e2e exactly
        assert approx(sum(segs.values()), wf["e2e_s"])

    def test_applier_envelopes_only_claim_inside_plan_wait(self):
        # a commit from ANOTHER batch, outside this eval's plan.wait
        # window, must not be attributed to this eval
        glob = self._globals() + [_span("plan.commit", "", 0.010, 0.030)]
        wf = build_waterfall(self._spans(), glob)
        assert abs(wf["segments"]["commit"] - 0.004) < 1e-9

    def test_missing_e2e_marker_returns_none(self):
        spans = [s for s in self._spans() if s.name != "eval.e2e"]
        assert build_waterfall(spans, self._globals()) is None

    def test_build_waterfalls_groups_by_trace(self):
        spans = (self._spans("a", 0.0) + self._spans("b", 1.0)
                 + self._globals(0.0) + self._globals(1.0))
        wfs = build_waterfalls(spans)
        assert {w["trace_id"] for w in wfs} == {"a", "b"}

    def test_aggregate_tail_p50_vs_p99(self):
        rng = random.Random(2)
        wfs = []
        # 99 fast evals dominated by schedule, 1 slow eval dominated
        # by dequeue-wait: the tail table must show dequeue-wait's
        # share GROWING at p99 — the "what makes the tail slow" signal
        for i in range(99):
            e2e = 0.010 + rng.uniform(0, 0.002)
            wfs.append({
                "trace_id": f"f{i}", "e2e_s": e2e,
                "segments": {"schedule": e2e * 0.7, "park": e2e * 0.3},
                "covered_s": e2e, "coverage": 1.0,
            })
        wfs.append({
            "trace_id": "slow", "e2e_s": 0.5,
            "segments": {"dequeue-wait": 0.45, "schedule": 0.05},
            "covered_s": 0.5, "coverage": 1.0,
        })
        tail = aggregate_tail(wfs)
        assert tail["e2e_count"] == 100
        assert tail["p50_coverage"] >= 0.99
        segs = tail["segments"]
        assert segs["schedule"]["p50_share"] > 0.6
        assert segs["dequeue-wait"]["p99_share"] > 0.8
        assert segs["dequeue-wait"].get("p50_share", 0.0) < 0.05
        # nearest-rank p99 of 100 samples is the 99th value (a fast
        # eval) — NOT the max, which is exactly the off-by-one the
        # shared helper exists to fix
        assert 10.0 <= tail["e2e_p99_ms"] <= 13.0
        assert tail["slowest"][0]["trace_id"] == "slow"
        assert tail["slowest"][0]["e2e_ms"] == 500.0

    def test_aggregate_tail_empty(self):
        tail = aggregate_tail([])
        assert tail["e2e_count"] == 0
        assert tail["segments"] == {}


@pytest.mark.slow
class TestContentionCell:
    """The open-item-4 standing gate cell, scaled down: sustained eval
    ingest under a heartbeat storm must report the e2e distribution
    and capture at least one slow-eval tree. Excluded from tier-1
    (slow); bench.py runs the full-size cell."""

    def test_contention_burst_emits_tail_and_captures(self):
        import sys
        sys.path.insert(0, os.path.join(REPO, "bench"))
        from trace_report import run_contention_burst

        cell = None
        for _attempt in range(2):       # one retry for CI-neighbor luck
            cell = run_contention_burst(
                n_nodes=60, n_jobs=64, allocs_per_job=3, batch_size=8,
                warmup_jobs=8, heartbeat_threads=4, submit_group=4,
                submit_pace_s=0.05, spike_s=1.0, deadline_s=120.0)
            if cell["slow_trees_captured"] >= 1 \
                    and cell["allocs_placed"] == cell["allocs_wanted"]:
                break
        assert cell["allocs_placed"] == cell["allocs_wanted"]
        assert cell["e2e_p99_ms"] >= cell["e2e_p50_ms"] > 0.0
        assert cell["e2e_count"] == cell["committed_evals"]
        assert cell["heartbeats"] > 0
        # the acceptance criterion: the cell captures >= 1 complete
        # slow-eval span tree through the adaptive threshold
        assert cell["slow_trees_captured"] >= 1, cell["flight_recorder"]
        assert cell["tail"]["p50_coverage"] >= 0.85, cell["tail"]


# The span-name drift guard that lived here (TestSpanNameDriftGuard)
# became graftcheck's R5 engine rule — tools/graftcheck/
# rules_telemetry.py, gated tier-1 by tests/test_graftcheck.py — which
# keeps the both-direction span coverage and extends it to Prometheus
# series names and bench emission keys.
