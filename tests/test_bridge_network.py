"""Bridge-mode allocation networking (networking_bridge_linux.go).

Capability-gated like the reference (needs netns/veth privileges).
The headline property: two allocations on ONE node bind the SAME
container port without conflict, each reachable through its own
scheduler-assigned host port.
"""

import socket
import time

import pytest

from nomad_tpu import mock, structs
from nomad_tpu.api.agent import Agent, AgentConfig
from nomad_tpu.api.client import APIClient
from nomad_tpu.client.network_manager import (
    BridgeNetworkManager,
    bridge_supported,
)

pytestmark = pytest.mark.skipif(
    not bridge_supported(), reason="host cannot create netns/veth")


def wait_for(fn, timeout=30.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        out = fn()
        if out:
            return out
        time.sleep(0.1)
    raise AssertionError(f"timeout waiting for {msg}")


class TestManager:
    def test_create_destroy_roundtrip(self):
        mgr = BridgeNetworkManager()
        net = mgr.create("11112222-3333-4444-5555-666677778888", [])
        try:
            assert net.ip.startswith("172.26.")
            assert mgr.network_of("11112222-3333-4444-5555-666677778888")
        finally:
            mgr.destroy("11112222-3333-4444-5555-666677778888")
        assert mgr.network_of("11112222-3333-4444-5555-666677778888") is None


class TestSameContainerPort:
    def test_two_allocs_bind_same_container_port(self):
        """Both allocs run a listener on container port 8080 inside
        their own namespace; each is reached via its own host port."""
        agent = Agent(AgentConfig.dev())
        agent.start()
        try:
            api = APIClient(agent.http_addr)
            job = mock.job()
            tg = job.task_groups[0]
            tg.count = 2
            # group-level bridge network with a dynamic port mapping to
            # container port 8080 (the jobspec `port "http" { to = 8080 }`)
            tg.networks = [structs.NetworkResource(
                mode="bridge",
                dynamic_ports=[structs.Port(label="http", to=8080)],
            )]
            task = tg.tasks[0]
            task.driver = "raw_exec"
            # a tiny stdlib server inside the netns answering with the
            # alloc id on container port 8080
            task.config = {
                "command": "/usr/local/bin/python3",
                "args": ["-S", "-c", (
                    "import os, socket\n"
                    "s = socket.socket()\n"
                    "s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)\n"
                    "s.bind((\"0.0.0.0\", 8080))\n"
                    "s.listen(4)\n"
                    "while True:\n"
                    "    c, _ = s.accept()\n"
                    "    c.sendall(os.environ[\"NOMAD_ALLOC_ID\"].encode())\n"
                    "    c.close()\n"
                )],
            }
            agent.server.job_register(job)
            allocs = wait_for(
                lambda: [a for a in api.jobs.allocations(job.id)
                         if a["ClientStatus"] == "running"] or None,
                msg="allocs running")
            wait_for(lambda: len([
                a for a in api.jobs.allocations(job.id)
                if a["ClientStatus"] == "running"]) == 2,
                msg="both allocs running")
            allocs = [a for a in api.jobs.allocations(job.id)
                      if a["ClientStatus"] == "running"]

            def host_port(alloc_summary):
                info = api.allocations.info(alloc_summary["ID"])
                res = info.get("AllocatedResources") or {}
                shared = res.get("Shared") or {}
                ports = []
                for net in shared.get("Networks") or []:
                    ports += (net.get("DynamicPorts") or [])
                for p in shared.get("Ports") or []:
                    ports.append(p)
                for p in ports:
                    if p.get("Label") == "http":
                        return p.get("Value")
                return None

            ports = {a["ID"]: host_port(a) for a in allocs}
            assert all(ports.values()), ports
            assert len(set(ports.values())) == 2, ports

            def read_alloc_id(port):
                deadline = time.time() + 20
                last = None
                while time.time() < deadline:
                    try:
                        c = socket.create_connection(
                            ("127.0.0.1", port), timeout=3)
                        data = c.recv(200).decode()
                        c.close()
                        if data:
                            return data
                    except OSError as e:
                        last = e
                    time.sleep(0.3)
                raise AssertionError(f"no answer on host port {port}: {last}")

            for alloc_id, port in ports.items():
                assert read_alloc_id(port) == alloc_id
        finally:
            agent.shutdown()


class TestNativeRelay:
    """native/relay.cc: the DNAT-analog splice relay — detached from
    the agent, restart-survivable, torn down via the persisted pid."""

    def _echo_server(self):
        srv = socket.socket()
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", 0))
        srv.listen(4)
        import threading

        def serve():
            while True:
                try:
                    c, _ = srv.accept()
                except OSError:
                    return

                def h(c=c):
                    try:
                        while True:
                            d = c.recv(65536)
                            if not d:
                                break
                            c.sendall(d)
                    except OSError:
                        pass
                    finally:
                        c.close()

                threading.Thread(target=h, daemon=True).start()

        threading.Thread(target=serve, daemon=True).start()
        return srv, srv.getsockname()[1]

    def test_spawn_relay_and_teardown_by_persisted_pid(self):
        import os

        from nomad_tpu.client.network_manager import _NativeRelay

        srv, tport = self._echo_server()
        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        lport = probe.getsockname()[1]
        probe.close()
        relay = _NativeRelay.spawn(
            "test-relay-alloc", [(lport, tport)], "127.0.0.1")
        try:
            c = socket.create_connection(("127.0.0.1", lport), timeout=5)
            c.sendall(b"relay-roundtrip")
            c.shutdown(socket.SHUT_WR)
            got = b""
            while True:
                d = c.recv(65536)
                if not d:
                    break
                got += d
            assert got == b"relay-roundtrip"
            # the relay is NOT a child the agent must wait on: it has
            # its own session (survives agent exit, like DNAT rules)
            assert os.getsid(relay.pid) != os.getsid(os.getpid())
        finally:
            # teardown via the persisted status file, the path an
            # agent that restarted (lost the pid from memory) takes
            _NativeRelay.kill_persisted("test-relay-alloc")
            srv.close()
        def gone(pid):
            # kill(pid, 0) succeeds on zombies (the relay is our
            # unreaped child here); /proc state tells the truth
            try:
                with open(f"/proc/{pid}/stat") as f:
                    return f.read().split(")")[1].split()[0] == "Z"
            except OSError:
                return True

        deadline = time.time() + 5
        while time.time() < deadline and not gone(relay.pid):
            time.sleep(0.05)
        assert gone(relay.pid), "relay survived persisted-pid teardown"

    def test_bridge_alloc_uses_native_relay(self):
        from nomad_tpu.client.network_manager import BridgeNetworkManager

        mgr = BridgeNetworkManager()
        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        hport = probe.getsockname()[1]
        probe.close()
        net = mgr.create("relaytest-1111-2222-3333-444455556666",
                         [(hport, 8080)])
        try:
            assert net.native_relay is not None, \
                "bridge alloc should carry ports via the native relay"
            assert not net.forwards
        finally:
            mgr.destroy("relaytest-1111-2222-3333-444455556666")

    def test_udp_datagrams_relay_both_ways(self):
        """Every mapping forwards UDP too (the CNI portmap programs
        tcp AND udp rules per port)."""
        from nomad_tpu.client.network_manager import _NativeRelay

        usrv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        usrv.bind(("127.0.0.1", 0))
        tport = usrv.getsockname()[1]
        import threading

        def echo():
            while True:
                try:
                    d, a = usrv.recvfrom(65536)
                except OSError:
                    return
                usrv.sendto(b"udp-ack:" + d, a)

        threading.Thread(target=echo, daemon=True).start()
        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        lport = probe.getsockname()[1]
        probe.close()
        relay = _NativeRelay.spawn(
            "test-udp-alloc", [(lport, tport)], "127.0.0.1")
        try:
            c = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            c.settimeout(5)
            c.sendto(b"ping-1", ("127.0.0.1", lport))
            data, _ = c.recvfrom(65536)
            assert data == b"udp-ack:ping-1"
            # replies keep routing to the RIGHT client per session
            c2 = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            c2.settimeout(5)
            c2.sendto(b"ping-2", ("127.0.0.1", lport))
            assert c2.recvfrom(65536)[0] == b"udp-ack:ping-2"
            c.sendto(b"ping-3", ("127.0.0.1", lport))
            assert c.recvfrom(65536)[0] == b"udp-ack:ping-3"
            c.close()
            c2.close()
        finally:
            _NativeRelay.kill_persisted("test-udp-alloc")
            usrv.close()

    def test_udp_fallback_forward(self):
        """The in-process UDP relay (native binary unavailable)."""
        from nomad_tpu.client.network_manager import _UdpForward

        usrv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        usrv.bind(("127.0.0.1", 0))
        tport = usrv.getsockname()[1]
        import threading

        def echo():
            while True:
                try:
                    d, a = usrv.recvfrom(65536)
                except OSError:
                    return
                usrv.sendto(b"fb:" + d, a)

        threading.Thread(target=echo, daemon=True).start()
        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        lport = probe.getsockname()[1]
        probe.close()
        fwd = _UdpForward(lport, "127.0.0.1", tport)
        fwd.start()
        try:
            c = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            c.settimeout(5)
            c.sendto(b"hello", ("127.0.0.1", lport))
            assert c.recvfrom(65536)[0] == b"fb:hello"
            c.close()
        finally:
            fwd.stop()
            usrv.close()

    def test_watchdog_respawns_dead_relay(self):
        """A killed relay is respawned within a heartbeat and the port
        map carries traffic again (iptables rules cannot crash; a
        relay process can)."""
        import os
        import signal

        from nomad_tpu.client.network_manager import BridgeNetworkManager

        srv, tport = self._echo_server()
        mgr = BridgeNetworkManager()
        mgr.WATCHDOG_INTERVAL = 0.3
        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        hport = probe.getsockname()[1]
        probe.close()
        alloc_id = "watchdog-1111-2222-3333-444455556666"
        net = mgr.create(alloc_id, [(hport, tport)])
        try:
            assert net.native_relay is not None
            # the relay targets the alloc IP; rewire the recorded
            # mappings at the echo server for a host-level roundtrip
            net.ip = "127.0.0.1"
            old_pid = net.native_relay.pid
            os.kill(old_pid, signal.SIGKILL)
            deadline = time.time() + 10
            while time.time() < deadline and \
                    net.native_relay.pid == old_pid:
                time.sleep(0.05)
            assert net.native_relay.pid != old_pid, \
                "watchdog never respawned the relay"
            c = socket.create_connection(("127.0.0.1", hport), timeout=5)
            c.sendall(b"after-respawn")
            c.shutdown(socket.SHUT_WR)
            got = b""
            while True:
                d = c.recv(65536)
                if not d:
                    break
                got += d
            assert got == b"after-respawn"
        finally:
            mgr.stop_watchdog()
            mgr.destroy(alloc_id)
            srv.close()
