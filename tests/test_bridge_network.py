"""Bridge-mode allocation networking (networking_bridge_linux.go).

Capability-gated like the reference (needs netns/veth privileges).
The headline property: two allocations on ONE node bind the SAME
container port without conflict, each reachable through its own
scheduler-assigned host port.
"""

import socket
import time

import pytest

from nomad_tpu import mock, structs
from nomad_tpu.api.agent import Agent, AgentConfig
from nomad_tpu.api.client import APIClient
from nomad_tpu.client.network_manager import (
    BridgeNetworkManager,
    bridge_supported,
)

pytestmark = pytest.mark.skipif(
    not bridge_supported(), reason="host cannot create netns/veth")


def wait_for(fn, timeout=30.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        out = fn()
        if out:
            return out
        time.sleep(0.1)
    raise AssertionError(f"timeout waiting for {msg}")


class TestManager:
    def test_create_destroy_roundtrip(self):
        mgr = BridgeNetworkManager()
        net = mgr.create("11112222-3333-4444-5555-666677778888", [])
        try:
            assert net.ip.startswith("172.26.")
            assert mgr.network_of("11112222-3333-4444-5555-666677778888")
        finally:
            mgr.destroy("11112222-3333-4444-5555-666677778888")
        assert mgr.network_of("11112222-3333-4444-5555-666677778888") is None


class TestSameContainerPort:
    def test_two_allocs_bind_same_container_port(self):
        """Both allocs run a listener on container port 8080 inside
        their own namespace; each is reached via its own host port."""
        agent = Agent(AgentConfig.dev())
        agent.start()
        try:
            api = APIClient(agent.http_addr)
            job = mock.job()
            tg = job.task_groups[0]
            tg.count = 2
            # group-level bridge network with a dynamic port mapping to
            # container port 8080 (the jobspec `port "http" { to = 8080 }`)
            tg.networks = [structs.NetworkResource(
                mode="bridge",
                dynamic_ports=[structs.Port(label="http", to=8080)],
            )]
            task = tg.tasks[0]
            task.driver = "raw_exec"
            # a tiny stdlib server inside the netns answering with the
            # alloc id on container port 8080
            task.config = {
                "command": "/usr/local/bin/python3",
                "args": ["-S", "-c", (
                    "import os, socket\n"
                    "s = socket.socket()\n"
                    "s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)\n"
                    "s.bind((\"0.0.0.0\", 8080))\n"
                    "s.listen(4)\n"
                    "while True:\n"
                    "    c, _ = s.accept()\n"
                    "    c.sendall(os.environ[\"NOMAD_ALLOC_ID\"].encode())\n"
                    "    c.close()\n"
                )],
            }
            agent.server.job_register(job)
            allocs = wait_for(
                lambda: [a for a in api.jobs.allocations(job.id)
                         if a["ClientStatus"] == "running"] or None,
                msg="allocs running")
            wait_for(lambda: len([
                a for a in api.jobs.allocations(job.id)
                if a["ClientStatus"] == "running"]) == 2,
                msg="both allocs running")
            allocs = [a for a in api.jobs.allocations(job.id)
                      if a["ClientStatus"] == "running"]

            def host_port(alloc_summary):
                info = api.allocations.info(alloc_summary["ID"])
                res = info.get("AllocatedResources") or {}
                shared = res.get("Shared") or {}
                ports = []
                for net in shared.get("Networks") or []:
                    ports += (net.get("DynamicPorts") or [])
                for p in shared.get("Ports") or []:
                    ports.append(p)
                for p in ports:
                    if p.get("Label") == "http":
                        return p.get("Value")
                return None

            ports = {a["ID"]: host_port(a) for a in allocs}
            assert all(ports.values()), ports
            assert len(set(ports.values())) == 2, ports

            def read_alloc_id(port):
                deadline = time.time() + 20
                last = None
                while time.time() < deadline:
                    try:
                        c = socket.create_connection(
                            ("127.0.0.1", port), timeout=3)
                        data = c.recv(200).decode()
                        c.close()
                        if data:
                            return data
                    except OSError as e:
                        last = e
                    time.sleep(0.3)
                raise AssertionError(f"no answer on host port {port}: {last}")

            for alloc_id, port in ports.items():
                assert read_alloc_id(port) == alloc_id
        finally:
            agent.shutdown()


class TestNativeRelay:
    """native/relay.cc: the DNAT-analog splice relay — detached from
    the agent, restart-survivable, torn down via the persisted pid."""

    def _echo_server(self):
        srv = socket.socket()
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", 0))
        srv.listen(4)
        import threading

        def serve():
            while True:
                try:
                    c, _ = srv.accept()
                except OSError:
                    return

                def h(c=c):
                    try:
                        while True:
                            d = c.recv(65536)
                            if not d:
                                break
                            c.sendall(d)
                    except OSError:
                        pass
                    finally:
                        c.close()

                threading.Thread(target=h, daemon=True).start()

        threading.Thread(target=serve, daemon=True).start()
        return srv, srv.getsockname()[1]

    def test_spawn_relay_and_teardown_by_persisted_pid(self):
        import os

        from nomad_tpu.client.network_manager import _NativeRelay

        srv, tport = self._echo_server()
        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        lport = probe.getsockname()[1]
        probe.close()
        relay = _NativeRelay.spawn(
            "test-relay-alloc", [(lport, tport)], "127.0.0.1")
        try:
            c = socket.create_connection(("127.0.0.1", lport), timeout=5)
            c.sendall(b"relay-roundtrip")
            c.shutdown(socket.SHUT_WR)
            got = b""
            while True:
                d = c.recv(65536)
                if not d:
                    break
                got += d
            assert got == b"relay-roundtrip"
            # the relay is NOT a child the agent must wait on: it has
            # its own session (survives agent exit, like DNAT rules)
            assert os.getsid(relay.pid) != os.getsid(os.getpid())
        finally:
            # teardown via the persisted status file, the path an
            # agent that restarted (lost the pid from memory) takes
            _NativeRelay.kill_persisted("test-relay-alloc")
            srv.close()
        def gone(pid):
            # kill(pid, 0) succeeds on zombies (the relay is our
            # unreaped child here); /proc state tells the truth
            try:
                with open(f"/proc/{pid}/stat") as f:
                    return f.read().split(")")[1].split()[0] == "Z"
            except OSError:
                return True

        deadline = time.time() + 5
        while time.time() < deadline and not gone(relay.pid):
            time.sleep(0.05)
        assert gone(relay.pid), "relay survived persisted-pid teardown"

    def test_bridge_alloc_uses_native_relay(self):
        from nomad_tpu.client.network_manager import BridgeNetworkManager

        mgr = BridgeNetworkManager()
        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        hport = probe.getsockname()[1]
        probe.close()
        net = mgr.create("relaytest-1111-2222-3333-444455556666",
                         [(hport, 8080)])
        try:
            assert net.native_relay is not None, \
                "bridge alloc should carry ports via the native relay"
            assert not net.forwards
        finally:
            mgr.destroy("relaytest-1111-2222-3333-444455556666")
