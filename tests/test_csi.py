"""CSI subsystem tests.

Modeled on reference nomad/structs/csi_test.go (claim admission),
nomad/csi_endpoint_test.go (register/claim/deregister),
nomad/volumewatcher/volumes_watcher_test.go (claim reaping), and
scheduler/feasible_test.go TestCSIVolumeChecker.
"""

import time

import pytest

from nomad_tpu import mock, structs
from nomad_tpu.client import Client, ClientConfig, InProcessRPC
from nomad_tpu.plugins.csi import CSIClientError, FakeCSIClient
from nomad_tpu.server import fsm as fsm_msgs
from nomad_tpu.server.server import Server, ServerConfig
from nomad_tpu.structs import consts
from nomad_tpu.structs import csi as csi


def wait_for(fn, timeout=15.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return
        time.sleep(0.02)
    raise AssertionError(f"timeout waiting for {msg}")


def make_volume(vol_id="vol-1", access=csi.ACCESS_MODE_SINGLE_NODE_WRITER,
                **kw):
    return csi.CSIVolume(
        id=vol_id,
        namespace=kw.pop("namespace", "default"),
        name=vol_id,
        external_id=f"ext-{vol_id}",
        plugin_id=kw.pop("plugin_id", "plug-1"),
        requested_capabilities=[
            csi.CSIVolumeCapability(
                access_mode=access,
                attachment_mode=csi.ATTACHMENT_MODE_FS,
            )
        ],
        **kw,
    )


def claim_for(alloc_id, node_id="node-1", mode=csi.CLAIM_WRITE):
    return csi.CSIVolumeClaim(alloc_id=alloc_id, node_id=node_id, mode=mode)


class TestClaimAdmission:
    # csi_test.go TestCSIVolumeClaim

    def test_single_writer_blocks_second_writer(self):
        v = make_volume()
        v.claim(claim_for("a1"))
        assert not v.claimable(csi.CLAIM_WRITE)
        with pytest.raises(ValueError):
            v.claim(claim_for("a2"))

    def test_single_writer_reclaim_idempotent(self):
        v = make_volume()
        v.claim(claim_for("a1"))
        v.claim(claim_for("a1"))
        assert len(v.write_claims) == 1

    def test_multi_writer_allows_many(self):
        v = make_volume(access=csi.ACCESS_MODE_MULTI_NODE_MULTI_WRITER)
        v.claim(claim_for("a1"))
        v.claim(claim_for("a2"))
        assert len(v.write_claims) == 2

    def test_reader_only_volume_rejects_writer(self):
        v = make_volume(access=csi.ACCESS_MODE_MULTI_NODE_READER)
        assert not v.write_schedulable()
        assert v.read_schedulable()

    def test_release_moves_to_past_claims(self):
        v = make_volume()
        v.claim(claim_for("a1"))
        rel = claim_for("a1", mode=csi.CLAIM_RELEASE)
        v.claim(rel)
        assert not v.write_claims
        assert "a1" in v.past_claims
        done = claim_for("a1", mode=csi.CLAIM_RELEASE)
        done.state = csi.CLAIM_STATE_READY_TO_FREE
        v.claim(done)
        assert not v.past_claims

    def test_unschedulable_volume(self):
        v = make_volume(schedulable=False)
        assert not v.claimable(csi.CLAIM_WRITE)
        assert not v.claimable(csi.CLAIM_READ)

    def test_validate(self):
        with pytest.raises(ValueError):
            csi.CSIVolume(id="v", plugin_id="p").validate()


class TestStateStore:
    # state_store CSIVolume table semantics

    def test_register_claim_deregister(self):
        server = Server(ServerConfig(num_workers=0))
        server.start()
        try:
            server.csi_volume_register([make_volume()])
            vol = server.state.csi_volume_by_id("default", "vol-1")
            assert vol is not None and vol.create_index > 0

            server.csi_volume_claim("default", "vol-1", claim_for("a1"))
            vol = server.state.csi_volume_by_id("default", "vol-1")
            assert "a1" in vol.write_claims

            # in-use deregister rejected without force
            with pytest.raises(ValueError):
                server.csi_volume_deregister("default", "vol-1")
            server.csi_volume_deregister("default", "vol-1", force=True)
            assert server.state.csi_volume_by_id("default", "vol-1") is None
        finally:
            server.shutdown()

    def test_reregister_keeps_claims(self):
        server = Server(ServerConfig(num_workers=0))
        server.start()
        try:
            server.csi_volume_register([make_volume()])
            server.csi_volume_claim("default", "vol-1", claim_for("a1"))
            server.csi_volume_register([make_volume()])
            vol = server.state.csi_volume_by_id("default", "vol-1")
            assert "a1" in vol.write_claims
        finally:
            server.shutdown()

    def test_snapshot_restore_roundtrip(self):
        server = Server(ServerConfig(num_workers=0))
        server.start()
        try:
            server.csi_volume_register([make_volume()])
            data = server.state.to_snapshot_bytes()
            server2 = Server(ServerConfig(num_workers=0))
            server2.state.restore_from_bytes(data)
            assert server2.state.csi_volume_by_id("default", "vol-1") is not None
        finally:
            server.shutdown()


class TestPluginsView:
    def test_plugins_from_nodes(self):
        n1 = mock.node()
        n1.csi_node_plugins = {"plug-1": {"healthy": True}}
        n2 = mock.node()
        n2.csi_node_plugins = {"plug-1": {"healthy": False}}
        n2.csi_controller_plugins = {"plug-1": {"healthy": True}}
        plugins = csi.plugins_from_nodes([n1, n2])
        p = plugins["plug-1"]
        assert p.nodes_healthy == 1
        assert len(p.nodes) == 2
        assert p.controller_required
        assert p.controllers_healthy == 1


class TestVolumeWatcher:
    # volumes_watcher_test.go: terminal alloc -> claims reaped

    def test_reaps_terminal_alloc_claims(self):
        server = Server(ServerConfig(num_workers=0))
        server.start()
        try:
            fake = FakeCSIClient()
            server.csi_clients["plug-1"] = fake
            node = mock.node()
            node.csi_node_plugins = {"plug-1": {"healthy": True}}
            node.csi_controller_plugins = {"plug-1": {"healthy": True}}
            server.node_register(node)

            server.csi_volume_register([make_volume()])
            job = mock.job()
            alloc = mock.alloc(job=job, node_id=node.id)
            server.state.upsert_allocs([alloc])
            server.csi_volume_claim(
                "default", "vol-1", claim_for(alloc.id, node_id=node.id)
            )
            # controller-publish happened on claim
            assert ("ext-vol-1", node.id) in fake.controller_published

            # alloc goes terminal -> watcher releases and unpublishes
            term = alloc.copy()
            term.client_status = consts.ALLOC_CLIENT_COMPLETE
            term.desired_status = consts.ALLOC_DESIRED_STOP
            server.state.upsert_allocs([term])

            def freed():
                vol = server.state.csi_volume_by_id("default", "vol-1")
                return not vol.in_use() and not vol.past_claims
            wait_for(freed, msg="claims freed")
            assert not fake.controller_published
        finally:
            server.shutdown()

    def test_node_unpublish_error_retries(self):
        server = Server(ServerConfig(num_workers=0))
        server.start()
        try:
            fake = FakeCSIClient()
            fake.fail["node_unpublish_volume"] = "socket gone"
            server.csi_clients["plug-1"] = fake
            server.csi_volume_register([make_volume()])
            c = claim_for("a1")
            c.target_path = "/data/csi/per-alloc/a1/vol-1"
            server.csi_volume_claim("default", "vol-1", c)
            # alloc a1 does not exist -> treated terminal -> release
            wait_for(
                lambda: server.state.csi_volume_by_id(
                    "default", "vol-1").past_claims,
                msg="claim released",
            )
            # stuck in taken because node unpublish keeps failing
            time.sleep(0.3)
            vol = server.state.csi_volume_by_id("default", "vol-1")
            assert vol.past_claims["a1"].state == csi.CLAIM_STATE_TAKEN
            # plugin recovers -> watcher finishes the pipeline
            del fake.fail["node_unpublish_volume"]
            wait_for(
                lambda: not server.state.csi_volume_by_id(
                    "default", "vol-1").past_claims,
                msg="claim freed after recovery",
            )
        finally:
            server.shutdown()


class TestFeasibility:
    # feasible_test.go TestCSIVolumeChecker

    def _snap_with_volume(self, server, access):
        server.csi_volume_register([make_volume(access=access)])
        return server.state.snapshot()

    def test_node_without_plugin_infeasible(self):
        from nomad_tpu.scheduler.feasible import csi_ok

        server = Server(ServerConfig(num_workers=0))
        server.start()
        try:
            snap = self._snap_with_volume(
                server, csi.ACCESS_MODE_SINGLE_NODE_WRITER
            )
            tg = structs.TaskGroup(name="web", volumes={
                "v": structs.VolumeRequest(name="v", type="csi",
                                           source="vol-1"),
            })
            n_plug = mock.node()
            n_plug.csi_node_plugins = {"plug-1": {"healthy": True}}
            n_unhealthy = mock.node()
            n_unhealthy.csi_node_plugins = {"plug-1": {"healthy": False}}
            n_none = mock.node()
            assert csi_ok(n_plug, tg, snap, "default")
            assert not csi_ok(n_unhealthy, tg, snap, "default")
            assert not csi_ok(n_none, tg, snap, "default")
        finally:
            server.shutdown()

    def test_claimed_single_writer_infeasible(self):
        from nomad_tpu.scheduler.feasible import csi_ok

        server = Server(ServerConfig(num_workers=0))
        server.start()
        try:
            snap = self._snap_with_volume(
                server, csi.ACCESS_MODE_SINGLE_NODE_WRITER
            )
            server.csi_volume_claim("default", "vol-1", claim_for("other"))
            snap = server.state.snapshot()
            tg = structs.TaskGroup(name="web", volumes={
                "v": structs.VolumeRequest(name="v", type="csi",
                                           source="vol-1"),
            })
            node = mock.node()
            node.csi_node_plugins = {"plug-1": {"healthy": True}}
            assert not csi_ok(node, tg, snap, "default")
            # read-only ask on the same volume also fails (single-node
            # writer volume with an active writer has no free reads)
            tg.volumes["v"].read_only = True
            assert not csi_ok(node, tg, snap, "default")
        finally:
            server.shutdown()


class TestHTTP:
    def _agent(self):
        from nomad_tpu.api.agent import Agent, AgentConfig

        agent = Agent(AgentConfig(num_schedulers=0))
        agent.start()
        return agent

    def test_volume_lifecycle_over_http(self):
        from nomad_tpu.api.client import APIClient, APIError

        agent = self._agent()
        try:
            api = APIClient(agent.http.addr)
            api.csi_volumes.register({
                "ID": "vol-http", "Name": "vol-http", "PluginID": "plug-1",
                "ExternalID": "ext-1",
                "RequestedCapabilities": [{
                    "AccessMode": csi.ACCESS_MODE_MULTI_NODE_READER,
                    "AttachmentMode": csi.ATTACHMENT_MODE_FS,
                }],
            })
            vols = api.csi_volumes.list()
            assert [v["ID"] for v in vols] == ["vol-http"]
            info = api.csi_volumes.info("vol-http")
            assert info["PluginID"] == "plug-1"
            assert api.csi_volumes.list(plugin_id="nope") == []
            assert len(api.csi_volumes.list(plugin_id="plug-1")) == 1
            api.csi_volumes.deregister("vol-http")
            with pytest.raises(APIError):
                api.csi_volumes.info("vol-http")
        finally:
            agent.shutdown()

    def test_volume_get_redacts_secrets(self):
        from nomad_tpu.api.client import APIClient

        agent = self._agent()
        try:
            vol = make_volume("vol-sec")
            vol.secrets = {"password": "hunter2"}
            agent.server.csi_volume_register([vol])
            api = APIClient(agent.http.addr)
            info = api.csi_volumes.info("vol-sec")
            assert info["Secrets"] == {"password": "[REDACTED]"}
            # the stored volume keeps the real secret
            assert agent.server.state.csi_volume_by_id(
                "default", "vol-sec").secrets["password"] == "hunter2"
        finally:
            agent.shutdown()

    def test_volume_register_requires_capability(self):
        from nomad_tpu.api.client import APIClient, APIError

        agent = self._agent()
        try:
            api = APIClient(agent.http.addr)
            with pytest.raises(APIError):
                api.csi_volumes.register({"ID": "bad", "PluginID": "p"})
        finally:
            agent.shutdown()

    def test_plugins_view_over_http(self):
        from nomad_tpu.api.client import APIClient

        agent = self._agent()
        try:
            node = mock.node()
            node.csi_node_plugins = {"plug-9": {"healthy": True}}
            agent.server.node_register(node)
            api = APIClient(agent.http.addr)
            plugins = api.csi_plugins.list()
            assert [p["ID"] for p in plugins] == ["plug-9"]
            assert api.csi_plugins.info("plug-9")["NodesHealthy"] == 1
        finally:
            agent.shutdown()

    def test_detach_releases_claims(self):
        from nomad_tpu.api.client import APIClient

        agent = self._agent()
        try:
            server = agent.server
            server.csi_volume_register([make_volume()])
            server.csi_volume_claim(
                "default", "vol-1", claim_for("a1", node_id="n-9")
            )
            api = APIClient(agent.http.addr)
            api.csi_volumes.detach("vol-1", node_id="n-9")
            wait_for(
                lambda: not server.state.csi_volume_by_id(
                    "default", "vol-1").in_use(),
                msg="detached",
            )
        finally:
            agent.shutdown()


class TestEndToEnd:
    def test_job_with_csi_volume_mounts_and_releases(self):
        """Full slice: volume registered, job placed only on the node
        with the plugin, client stages+publishes, stop releases."""
        server = Server(ServerConfig(heartbeat_ttl=60.0))
        server.start()
        fake = FakeCSIClient()
        server.csi_clients["plug-1"] = fake
        client = None
        try:
            server.csi_volume_register([make_volume()])
            client = Client(
                InProcessRPC(server),
                ClientConfig(data_dir="/tmp/nomad-tpu-test-csi"),
                csi_clients={"plug-1": fake},
            )
            client.start()
            wait_for(
                lambda: any(n.ready() for n in server.state.snapshot().nodes()),
                msg="node ready",
            )

            job = mock.job()
            job.task_groups[0].count = 1
            job.task_groups[0].volumes = {
                "data": structs.VolumeRequest(
                    name="data", type="csi", source="vol-1",
                    access_mode=csi.ACCESS_MODE_SINGLE_NODE_WRITER,
                    attachment_mode=csi.ATTACHMENT_MODE_FS,
                ),
            }
            job.task_groups[0].tasks[0].driver = "mock_driver"
            job.task_groups[0].tasks[0].config = {"run_for": 30}
            server.job_register(job)

            def claimed():
                vol = server.state.csi_volume_by_id("default", "vol-1")
                return vol.in_use()
            wait_for(claimed, msg="volume claimed")
            assert fake.node_staged and fake.node_published
            # the claim carries the node's real publish paths so the
            # server-side unpublish can replay them
            vol = server.state.csi_volume_by_id("default", "vol-1")
            claim = next(iter(vol.write_claims.values()))
            assert claim.target_path.endswith("/vol-1")
            # tasks see the mount path via env
            ar = next(iter(client.allocs.values()))
            tr = next(iter(ar.task_runners.values()))
            assert tr.extra_env.get("NOMAD_ALLOC_VOLUME_DATA") == \
                claim.target_path

            # stop the job: alloc terminal -> watcher frees the claim
            server.job_deregister("default", job.id)

            def freed():
                vol = server.state.csi_volume_by_id("default", "vol-1")
                return not vol.in_use() and not vol.past_claims
            wait_for(freed, msg="volume freed")
            # the watcher unpublished the node's actual target path
            wait_for(lambda: not fake.node_published,
                     msg="node target unpublished")
        finally:
            if client is not None:
                client.shutdown()
            server.shutdown()
