"""Pallas placement kernel parity tests.

Golden parity against the XLA lean kernel (ops/kernel.py) on identical
inputs: same chosen nodes, same scores, same sequential-deduction
semantics. Runs the pallas kernel in interpret mode (tests force CPU).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from nomad_tpu.ops.kernel import LEAN_FEATURES, build_kernel_in
from nomad_tpu.ops.pallas_kernel import (
    make_schedule_apply_step_pallas,
    pallas_place_batch,
)
from nomad_tpu.parallel.batching import (
    device_put_shared,
    make_schedule_apply_step,
)
from nomad_tpu.parallel.synthetic import synthetic_cluster, synthetic_eval

N_NODES = 200        # pads to a lane-aligned bucket
K = 5
B = 8
LEAN = LEAN_FEATURES


@pytest.fixture(scope="module")
def shared():
    cluster = synthetic_cluster(N_NODES, cpu=2000.0, mem=4096.0,
                                disk=50000.0, seed=3)
    ev = synthetic_eval(cluster, desired_count=K)
    kin = device_put_shared(build_kernel_in(cluster, ev, K))
    assert kin.cap_cpu.shape[0] % 128 == 0
    return kin


def _batch_inputs(seed=0):
    rng = np.random.default_rng(seed)
    ask_cpu = jnp.asarray(
        rng.choice([100.0, 250.0, 500.0], B).astype(np.float32))
    ask_mem = jnp.asarray(
        rng.choice([64.0, 128.0, 256.0], B).astype(np.float32))
    n_steps = jnp.asarray(np.full(B, K, np.int32))
    return ask_cpu, ask_mem, n_steps


class TestParity:
    def test_matches_xla_lean_kernel(self, shared):
        npad = shared.cap_cpu.shape[0]
        rng = np.random.default_rng(1)
        used = np.zeros(npad, np.float32)
        used[:N_NODES] = 2000.0 * 0.5 * rng.random(N_NODES,
                                                   dtype=np.float32)
        usedm = np.zeros(npad, np.float32)
        usedm[:N_NODES] = 4096.0 * 0.5 * rng.random(N_NODES,
                                                    dtype=np.float32)
        ask_cpu, ask_mem, n_steps = _batch_inputs()

        xla_step = make_schedule_apply_step(K, LEAN)
        out_x, uc_x, um_x = xla_step(
            shared, jnp.asarray(used), jnp.asarray(usedm),
            ask_cpu, ask_mem, n_steps)

        pl_step = make_schedule_apply_step_pallas(K, interpret=True)
        out_p, uc_p, um_p = pl_step(
            shared, jnp.asarray(used), jnp.asarray(usedm),
            ask_cpu, ask_mem, n_steps)

        np.testing.assert_array_equal(np.asarray(out_x.chosen),
                                      np.asarray(out_p.chosen))
        np.testing.assert_array_equal(np.asarray(out_x.found),
                                      np.asarray(out_p.found))
        np.testing.assert_allclose(np.asarray(out_x.scores),
                                   np.asarray(out_p.scores),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(uc_x), np.asarray(uc_p),
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(um_x), np.asarray(um_p),
                                   rtol=1e-6)

    def test_sequential_deduction_within_eval(self, shared):
        """The K placements of one eval must spread across nodes when
        one node can't hold them all (in-kernel deduction works)."""
        npad = shared.cap_cpu.shape[0]
        used = jnp.zeros(npad, jnp.float32)
        # ask so large each node fits exactly one
        ask_cpu = jnp.full(1, 1200.0, jnp.float32)
        ask_mem = jnp.full(1, 64.0, jnp.float32)
        out = pallas_place_batch(
            shared.cap_cpu, shared.cap_mem, shared.cap_disk,
            used, used, shared.used_disk,
            shared.base_mask, shared.job_tg_count, shared.penalty,
            shared.aff_score,
            ask_cpu, ask_mem, shared.ask_disk,
            jnp.full(1, K, jnp.int32), shared.desired_count,
            shared.algorithm_spread, k_steps=K, interpret=True)
        chosen = np.asarray(out.chosen[0])
        assert np.asarray(out.found[0]).all()
        assert len(set(chosen.tolist())) == K, chosen

    def test_infeasible_returns_not_found(self, shared):
        npad = shared.cap_cpu.shape[0]
        used = jnp.zeros(npad, jnp.float32)
        ask_cpu = jnp.full(1, 1e9, jnp.float32)   # impossible ask
        ask_mem = jnp.full(1, 64.0, jnp.float32)
        out = pallas_place_batch(
            shared.cap_cpu, shared.cap_mem, shared.cap_disk,
            used, used, shared.used_disk,
            shared.base_mask, shared.job_tg_count, shared.penalty,
            shared.aff_score,
            ask_cpu, ask_mem, shared.ask_disk,
            jnp.full(1, K, jnp.int32), shared.desired_count,
            shared.algorithm_spread, k_steps=K, interpret=True)
        assert not np.asarray(out.found).any()
        assert (np.asarray(out.chosen) == -1).all()


class TestCandidateScanParity:
    """The fused candidate scan (pallas_topk_place_batch): XLA
    full-width pass + approx_max_k + ONE pallas program for the K-step
    deduction scan. Whenever `valid` holds, results must be identical
    to the full-width XLA kernel."""

    def _run(self, shared, used, usedm, ask_cpu, ask_mem, n_steps):
        from nomad_tpu.ops.pallas_kernel import pallas_topk_place_batch

        return pallas_topk_place_batch(
            shared.cap_cpu, shared.cap_mem, shared.cap_disk,
            jnp.asarray(used), jnp.asarray(usedm), shared.used_disk,
            shared.base_mask, shared.job_tg_count, shared.penalty,
            shared.aff_score,
            ask_cpu, ask_mem, shared.ask_disk,
            n_steps, shared.desired_count, shared.algorithm_spread,
            k_steps=K, interpret=True)

    def test_matches_full_width_kernel_when_valid(self, shared):
        from nomad_tpu.ops.kernel import place_taskgroup

        npad = shared.cap_cpu.shape[0]
        rng = np.random.default_rng(5)
        used = np.zeros(npad, np.float32)
        used[:N_NODES] = 2000.0 * 0.6 * rng.random(N_NODES, np.float32)
        usedm = np.zeros(npad, np.float32)
        usedm[:N_NODES] = 4096.0 * 0.6 * rng.random(N_NODES, np.float32)
        ask_cpu, ask_mem, n_steps = _batch_inputs(seed=5)

        chosen, scores, found, valid = self._run(
            shared, used, usedm, ask_cpu, ask_mem, n_steps)
        assert np.asarray(valid).any(), "calibration workload all invalid"
        for b in range(B):
            if not bool(valid[b]):
                continue
            kin = shared._replace(
                used_cpu=jnp.asarray(used), used_mem=jnp.asarray(usedm),
                ask_cpu=ask_cpu[b], ask_mem=ask_mem[b],
                n_steps=jnp.asarray(K, jnp.int32))
            ref = place_taskgroup(kin, K, LEAN)
            np.testing.assert_array_equal(np.asarray(ref.chosen),
                                          np.asarray(chosen[b]))
            np.testing.assert_array_equal(np.asarray(ref.found),
                                          np.asarray(found[b]))
            np.testing.assert_allclose(np.asarray(ref.scores),
                                       np.asarray(scores[b]),
                                       rtol=1e-5, atol=1e-6)

    def test_loop_backend_matches_xla_topk(self, shared):
        from nomad_tpu.parallel.batching import make_schedule_apply_loop

        npad = shared.cap_cpu.shape[0]
        rng = np.random.default_rng(9)
        used = np.zeros(npad, np.float32)
        used[:N_NODES] = 2000.0 * 0.5 * rng.random(N_NODES, np.float32)
        usedm = np.zeros(npad, np.float32)
        usedm[:N_NODES] = 4096.0 * 0.5 * rng.random(N_NODES, np.float32)
        T = 3
        asks_cpu = jnp.asarray(
            rng.choice([100.0, 250.0, 500.0], (T, B)).astype(np.float32))
        asks_mem = jnp.asarray(
            rng.choice([64.0, 128.0, 256.0], (T, B)).astype(np.float32))
        n_steps = jnp.asarray(np.full(B, K, np.int32))

        xla = make_schedule_apply_loop(K, LEAN, topk=True)
        pls = make_schedule_apply_loop(K, LEAN, topk=True,
                                       backend="pallas_topk",
                                       interpret=True)
        sx = xla(shared, jnp.asarray(used), jnp.asarray(usedm),
                 asks_cpu, asks_mem, n_steps)
        sp = pls(shared, jnp.asarray(used), jnp.asarray(usedm),
                 asks_cpu, asks_mem, n_steps)
        # same placements committed -> same final utilization planes,
        # same totals (invalid counts may differ: different k_cand)
        assert int(sx[1]) > 0
        np.testing.assert_allclose(float(sx[0]), float(sp[0]), rtol=1e-5)
        assert int(sx[1]) == int(sp[1])
        np.testing.assert_allclose(np.asarray(sx[3]), np.asarray(sp[3]),
                                   rtol=1e-5)
        np.testing.assert_allclose(np.asarray(sx[4]), np.asarray(sp[4]),
                                   rtol=1e-5)

    def test_invalid_members_excluded(self, shared):
        """An eval that cannot place all K steps on the candidate set
        while the wider cluster could must come back valid=False."""
        npad = shared.cap_cpu.shape[0]
        used = np.zeros(npad, np.float32)
        usedm = np.zeros(npad, np.float32)
        # ask sized so each node fits exactly one placement and K
        # placements exceed the candidate count is impossible here
        # (k_cand >= K), so instead starve: only K-1 nodes feasible
        # via base_mask is not reachable from this seam — use a huge
        # ask that fits nowhere: found=False everywhere, which is a
        # VALID outcome (rest_max is -inf too)
        ask_cpu = jnp.full(1, 1e9, jnp.float32)
        ask_mem = jnp.full(1, 64.0, jnp.float32)
        chosen, scores, found, valid = self._run(
            shared, used, usedm, ask_cpu, ask_mem,
            jnp.full(1, K, jnp.int32))
        assert not np.asarray(found).any()
        assert bool(valid[0])


class TestDonationDiscipline:
    """BENCH_r05 grew a "Some donated buffers were not usable:
    float32[16384]" tail: ``make_schedule_apply_step_pallas`` jitted
    with raw ``donate_argnums`` over caller-owned ``jnp.asarray``
    planes. conftest promotes that warning to an error, so simply
    driving the step twice through the wrapper proves the fix — and
    the caller's planes must survive untouched."""

    def test_donated_step_clean_and_caller_planes_survive(self, shared):
        npad = shared.cap_cpu.shape[0]
        rng = np.random.default_rng(7)
        used = np.zeros(npad, np.float32)
        used[:N_NODES] = 2000.0 * 0.4 * rng.random(N_NODES,
                                                   dtype=np.float32)
        usedm = np.zeros(npad, np.float32)
        usedm[:N_NODES] = 4096.0 * 0.4 * rng.random(N_NODES,
                                                    dtype=np.float32)
        used0, usedm0 = used.copy(), usedm.copy()
        ask_cpu, ask_mem, n_steps = _batch_inputs(seed=2)

        step = make_schedule_apply_step_pallas(K, interpret=True)
        uc, um = jnp.asarray(used), jnp.asarray(usedm)
        for _ in range(2):          # second call reuses the jit cache
            out, uc2, um2 = step(shared, uc, um,
                                 ask_cpu, ask_mem, n_steps)
        # the wrapper copies before donating: caller arrays intact
        np.testing.assert_array_equal(np.asarray(uc), used0)
        np.testing.assert_array_equal(np.asarray(um), usedm0)
        assert np.asarray(out.found).any()
