"""Preemption tests.

Modeled on reference scheduler/preemption_test.go: eligibility delta,
greedy distance-minimizing victim selection, superset filtering, the
generic scheduler's preemption second pass, and the system scheduler's
per-node preemption branch.
"""

from nomad_tpu import mock, structs
from nomad_tpu.scheduler.preemption import (
    PRIORITY_DELTA,
    Preemptor,
    basic_resource_distance,
    filter_and_group_preemptible,
    net_priority,
    preemption_score,
)
from nomad_tpu.scheduler.testing import Harness
from nomad_tpu.structs import consts
from nomad_tpu.structs.resources import ComparableResources


def _alloc_on(node, cpu, mem, priority, job_type=consts.JOB_TYPE_SERVICE,
              disk=10):
    j = mock.job()
    j.priority = priority
    j.type = job_type
    a = mock.alloc(job=j)
    a.job_id = j.id
    a.node_id = node.id
    a.client_status = consts.ALLOC_CLIENT_RUNNING
    tr = a.allocated_resources.tasks["web"]
    tr.cpu.cpu_shares = cpu
    tr.memory.memory_mb = mem
    a.allocated_resources.shared.disk_mb = disk
    return a


class TestPreemptionScoring:
    def test_basic_resource_distance_exact_match_is_zero(self):
        ask = ComparableResources(cpu_shares=100, memory_mb=256, disk_mb=10)
        assert basic_resource_distance(ask, ask) == 0.0

    def test_distance_prefers_closer_alloc(self):
        ask = ComparableResources(cpu_shares=1000, memory_mb=1000, disk_mb=0)
        close = ComparableResources(cpu_shares=900, memory_mb=900, disk_mb=0)
        far = ComparableResources(cpu_shares=100, memory_mb=100, disk_mb=0)
        assert basic_resource_distance(ask, close) < basic_resource_distance(ask, far)

    def test_preemption_score_logistic(self):
        # inflection at 2048; low net priority scores near 1
        assert preemption_score(2048.0) == 0.5
        assert preemption_score(0.0) > 0.99
        assert preemption_score(10000.0) < 0.01

    def test_net_priority_penalizes_many_allocs(self):
        j_lo = mock.job(); j_lo.priority = 20
        a1 = mock.alloc(job=j_lo)
        several = [mock.alloc(job=j_lo) for _ in range(5)]
        assert net_priority(several) > net_priority([a1])


class TestEligibility:
    def test_delta_filter(self):
        jobs = []
        for pri in (10, 40, 41, 45, 50):
            j = mock.job()
            j.priority = pri
            jobs.append(mock.alloc(job=j))
        groups = filter_and_group_preemptible(50, jobs)
        # only priority 10 and 40 qualify (50 - p >= 10)
        flat_pris = [pri for pri, _ in groups]
        assert flat_pris == [10, 40]
        # lowest priority group first
        assert groups[0][0] == 10


class TestPreemptor:
    def test_picks_minimal_victim_set(self):
        node = mock.node()  # 4000 cpu (3900 usable), 8192 mem
        lo1 = _alloc_on(node, 3000, 6000, priority=10)
        lo2 = _alloc_on(node, 500, 512, priority=10)
        p = Preemptor(50, "default", "new-job")
        p.set_node(node)
        p.set_candidates([lo1, lo2])
        # ask fits once lo1 is gone; lo2 need not die
        victims = p.preempt_for_task_group(
            ComparableResources(cpu_shares=2000, memory_mb=4000, disk_mb=10)
        )
        assert [v.id for v in victims] == [lo1.id]

    def test_no_preemption_when_insufficient(self):
        node = mock.node()
        lo = _alloc_on(node, 500, 512, priority=10)
        hi = _alloc_on(node, 3000, 7000, priority=48)  # delta < 10: protected
        p = Preemptor(50, "default", "new-job")
        p.set_node(node)
        p.set_candidates([lo, hi])
        victims = p.preempt_for_task_group(
            ComparableResources(cpu_shares=3500, memory_mb=7000, disk_mb=10)
        )
        assert victims == []

    def test_lowest_priority_evicted_first(self):
        node = mock.node()
        lo = _alloc_on(node, 1500, 3000, priority=5)
        mid = _alloc_on(node, 1500, 3000, priority=30)
        p = Preemptor(50, "default", "new-job")
        p.set_node(node)
        p.set_candidates([mid, lo])
        victims = p.preempt_for_task_group(
            ComparableResources(cpu_shares=1200, memory_mb=2500, disk_mb=10)
        )
        assert [v.id for v in victims] == [lo.id]

    def test_own_job_never_preempted(self):
        node = mock.node()
        j = mock.job()
        j.priority = 10
        own = mock.alloc(job=j)
        own.node_id = node.id
        own.client_status = consts.ALLOC_CLIENT_RUNNING
        p = Preemptor(50, own.namespace, own.job_id)
        p.set_node(node)
        p.set_candidates([own])
        assert p._current_allocs == []


def _packed_cluster(h, n_nodes, fill_priority=10):
    """Nodes each fully packed by one low-priority alloc."""
    nodes = [mock.node() for _ in range(n_nodes)]
    for n in nodes:
        h.state.upsert_node(n)
    fillers = []
    for n in nodes:
        a = _alloc_on(n, 3500, 7000, priority=fill_priority)
        fillers.append(a)
    h.state.upsert_allocs(fillers)
    return nodes, fillers


class TestSchedulerPreemption:
    def test_service_preempts_when_enabled(self):
        h = Harness()
        h.state.scheduler_config.preemption_service_enabled = True
        nodes, fillers = _packed_cluster(h, 3)

        job = mock.simple_job()
        job.priority = 100
        job.task_groups[0].count = 1
        job.task_groups[0].tasks[0].resources.cpu = 2000
        job.task_groups[0].tasks[0].resources.memory_mb = 4000
        h.state.upsert_job(job)
        ev = mock.eval(job_id=job.id, namespace=job.namespace,
                       type=job.type, priority=job.priority,
                       triggered_by=consts.EVAL_TRIGGER_JOB_REGISTER)
        h.state.upsert_evals([ev])
        h.process(job.type, ev)

        placed = h.placed_allocs()
        assert len(placed) == 1
        assert placed[0].preempted_allocations
        # a preemption landed in the plan
        plan = h.plans[-1]
        victims = [a for allocs in plan.node_preemptions.values() for a in allocs]
        assert len(victims) >= 1
        assert victims[0].desired_status == consts.ALLOC_DESIRED_EVICT
        assert victims[0].preempted_by_allocation == placed[0].id
        # eviction and placement agree on the node
        assert victims[0].node_id == placed[0].node_id

    def test_service_no_preempt_when_disabled(self):
        h = Harness()
        h.state.scheduler_config.preemption_service_enabled = False
        _packed_cluster(h, 3)
        job = mock.simple_job()
        job.priority = 100
        job.task_groups[0].count = 1
        job.task_groups[0].tasks[0].resources.cpu = 2000
        job.task_groups[0].tasks[0].resources.memory_mb = 4000
        h.state.upsert_job(job)
        ev = mock.eval(job_id=job.id, namespace=job.namespace,
                       type=job.type, priority=job.priority,
                       triggered_by=consts.EVAL_TRIGGER_JOB_REGISTER)
        h.state.upsert_evals([ev])
        h.process(job.type, ev)
        assert len(h.placed_allocs()) == 0

    def test_low_priority_job_cannot_preempt(self):
        h = Harness()
        h.state.scheduler_config.preemption_service_enabled = True
        _packed_cluster(h, 2, fill_priority=50)
        job = mock.simple_job()
        job.priority = 55  # delta < 10
        job.task_groups[0].count = 1
        job.task_groups[0].tasks[0].resources.cpu = 2000
        job.task_groups[0].tasks[0].resources.memory_mb = 4000
        h.state.upsert_job(job)
        ev = mock.eval(job_id=job.id, namespace=job.namespace,
                       type=job.type, priority=job.priority,
                       triggered_by=consts.EVAL_TRIGGER_JOB_REGISTER)
        h.state.upsert_evals([ev])
        h.process(job.type, ev)
        assert len(h.placed_allocs()) == 0

    def test_system_job_preempts(self):
        h = Harness()
        # system preemption defaults on
        nodes, fillers = _packed_cluster(h, 2)
        job = mock.system_job()
        job.priority = 100
        job.task_groups[0].tasks[0].resources.cpu = 2000
        job.task_groups[0].tasks[0].resources.memory_mb = 4000
        h.state.upsert_job(job)
        ev = mock.eval(job_id=job.id, namespace=job.namespace,
                       type=job.type, priority=job.priority,
                       triggered_by=consts.EVAL_TRIGGER_JOB_REGISTER)
        h.state.upsert_evals([ev])
        h.process(job.type, ev)
        placed = h.placed_allocs()
        assert len(placed) == 2  # one per node, both via preemption
        for a in placed:
            assert a.preempted_allocations
        plan = h.plans[-1]
        victims = [a for allocs in plan.node_preemptions.values() for a in allocs]
        assert len(victims) == 2

    def test_preempted_allocs_freed_in_state(self):
        """Plan apply must upsert preempted allocs as evicted."""
        h = Harness()
        h.state.scheduler_config.preemption_service_enabled = True
        nodes, fillers = _packed_cluster(h, 1)
        job = mock.simple_job()
        job.priority = 100
        job.task_groups[0].count = 1
        job.task_groups[0].tasks[0].resources.cpu = 2000
        job.task_groups[0].tasks[0].resources.memory_mb = 4000
        h.state.upsert_job(job)
        ev = mock.eval(job_id=job.id, namespace=job.namespace,
                       type=job.type, priority=job.priority,
                       triggered_by=consts.EVAL_TRIGGER_JOB_REGISTER)
        h.state.upsert_evals([ev])
        h.process(job.type, ev)
        snap = h.state.snapshot()
        evicted = snap.alloc_by_id(fillers[0].id)
        assert evicted is not None
        assert evicted.desired_status == consts.ALLOC_DESIRED_EVICT
