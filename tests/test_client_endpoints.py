"""Client HTTP endpoint tests: fs, logs, exec, restart, signal.

Modeled on reference client/fs_endpoint_test.go,
client/alloc_endpoint_test.go, and the server->node pass-through
(nomad/client_fs_endpoint.go forwarding).
"""

import time

import pytest

from nomad_tpu import mock, structs
from nomad_tpu.api.agent import Agent, AgentConfig
from nomad_tpu.api.client import APIClient, APIError


def wait_for(fn, timeout=15.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        out = fn()
        if out:
            return out
        time.sleep(0.05)
    raise AssertionError(f"timeout waiting for {msg}")


def run_job(agent, api, run_for=30, driver="mock_driver", config=None):
    job = mock.job()
    job.task_groups[0].count = 1
    task = job.task_groups[0].tasks[0]
    task.driver = driver
    task.config = config if config is not None else {"run_for": run_for}
    agent.server.job_register(job)
    allocs = wait_for(
        lambda: [a for a in api.jobs.allocations(job.id)
                 if a["ClientStatus"] == "running"],
        msg="alloc running",
    )
    return job, allocs[0]


class TestFS:
    def setup_method(self):
        self.agent = Agent(AgentConfig.dev())
        self.agent.start()
        self.api = APIClient(self.agent.http_addr)

    def teardown_method(self):
        self.agent.shutdown()

    def test_logs_ls_stat_cat(self):
        # raw_exec task that writes to stdout then sleeps
        job, alloc = run_job(
            self.agent, self.api, driver="raw_exec",
            config={"command": "/bin/sh",
                    "args": ["-c", "echo hello-from-task; sleep 30"]},
        )
        aid = alloc["ID"]
        wait_for(lambda: "hello-from-task" in
                 self.api.allocations.logs(aid, "web"), msg="stdout logged")

        entries = self.api.allocations.fs_ls(aid, "/")
        names = {e["Name"] for e in entries}
        assert "alloc" in names and "web" in names

        st = self.api.allocations.fs_stat(aid, "alloc/logs")
        assert st["IsDir"]

        data = self.api.allocations.fs_cat(aid, "alloc/logs/web.stdout.0")
        assert "hello-from-task" in data

    def test_path_escape_rejected(self):
        job, alloc = run_job(self.agent, self.api)
        with pytest.raises(APIError) as e:
            self.api.allocations.fs_cat(alloc["ID"], "../../../etc/passwd")
        assert e.value.status in (403, 404)

    def test_secrets_dir_denied(self):
        job, alloc = run_job(self.agent, self.api)
        with pytest.raises(APIError) as e:
            self.api.allocations.fs_ls(alloc["ID"], "web/secrets")
        assert e.value.status == 403

    def test_restart_unknown_task_404(self):
        job, alloc = run_job(self.agent, self.api)
        with pytest.raises(APIError) as e:
            self.api.allocations.restart(alloc["ID"], "nope")
        assert e.value.status == 404

    def test_exec(self):
        job, alloc = run_job(
            self.agent, self.api, driver="raw_exec",
            config={"command": "/bin/sleep", "args": ["30"]},
        )
        out = self.api.allocations.exec(alloc["ID"], "web",
                                        ["/bin/echo", "exec-ok"])
        assert "exec-ok" in out["stdout"]
        assert out["exit_code"] == 0

    def test_restart_bounces_task(self):
        job, alloc = run_job(
            self.agent, self.api, driver="raw_exec",
            config={"command": "/bin/sleep", "args": ["30"]},
        )
        aid = alloc["ID"]
        self.api.allocations.restart(aid)

        def restarted():
            info = self.api.allocations.info(aid)
            events = info["TaskStates"]["web"]["Events"]
            types = [e["Type"] for e in events]
            return types.count("Started") >= 2 and \
                info["ClientStatus"] == "running"
        wait_for(restarted, msg="task restarted")

    def test_signal_kills_process(self):
        job, alloc = run_job(
            self.agent, self.api, driver="raw_exec",
            config={"command": "/bin/sleep", "args": ["30"]},
        )
        self.api.allocations.signal(alloc["ID"], "SIGKILL")

        def saw_exit():
            info = self.api.allocations.info(alloc["ID"])
            events = info["TaskStates"]["web"]["Events"]
            return any(e["Type"] in ("Terminated", "Restarting")
                       for e in events)
        wait_for(saw_exit, msg="task terminated by signal")


class TestPassThrough:
    def test_server_only_agent_proxies_to_node(self):
        dev = Agent(AgentConfig.dev())
        dev.start()
        srv = Agent(AgentConfig(name="hub", num_schedulers=0))
        srv.start()
        try:
            api = APIClient(dev.http_addr)
            job, alloc = run_job(
                dev, api, driver="raw_exec",
                config={"command": "/bin/sh",
                        "args": ["-c", "echo proxied-log; sleep 30"]},
            )
            # teach the hub about the node + alloc (in a full multi-host
            # deployment registration would do this)
            node = dev.client.node
            srv.server.state.upsert_node(node.copy())
            full = dev.server.state.snapshot().alloc_by_id(alloc["ID"])
            srv.server.state.upsert_allocs([full.copy_skip_job()])

            hub_api = APIClient(srv.http_addr)
            log = wait_for(
                lambda: hub_api.allocations.logs(alloc["ID"], "web"),
                msg="proxied logs",
            )
            assert "proxied-log" in log
            with pytest.raises(APIError):
                hub_api.allocations.logs("nonexistent-alloc", "web")
        finally:
            dev.shutdown()
            srv.shutdown()


class TestInteractiveExec:
    """Streaming exec over the websocket (api/allocations_exec.go,
    driver.proto:79 ExecTaskStreaming): stdin and stdout both ways."""

    def setup_method(self):
        self.agent = Agent(AgentConfig.dev())
        self.agent.start()
        self.api = APIClient(self.agent.http_addr)

    def teardown_method(self):
        self.agent.shutdown()

    def _running_alloc(self):
        job, alloc = run_job(
            self.agent, self.api, driver="raw_exec",
            config={"command": "/bin/sh", "args": ["-c", "sleep 30"]},
        )
        return alloc["ID"]

    def test_bidirectional_stream(self):
        aid = self._running_alloc()
        session = self.api.allocations.exec_stream(aid, "web", ["cat"])
        session.send_stdin(b"ping-1\n")
        session.send_stdin(b"ping-2\n")
        session.close_stdin()
        got = b""
        for frame in session.events():
            blob = frame.get("stdout") or {}
            if blob.get("bytes"):
                got += blob["bytes"]
        assert b"ping-1" in got and b"ping-2" in got
        assert session.exit_code == 0

    def test_exit_code_propagates(self):
        aid = self._running_alloc()
        session = self.api.allocations.exec_stream(
            aid, "web", ["/bin/sh", "-c", "echo out; echo err >&2; exit 7"])
        out, err = b"", b""
        for frame in session.events():
            if (frame.get("stdout") or {}).get("bytes"):
                out += frame["stdout"]["bytes"]
            if (frame.get("stderr") or {}).get("bytes"):
                err += frame["stderr"]["bytes"]
        assert b"out" in out
        assert b"err" in err
        assert session.exit_code == 7

    def test_tty_session(self):
        aid = self._running_alloc()
        session = self.api.allocations.exec_stream(
            aid, "web", ["/bin/sh"], tty=True)
        session.resize(24, 80)
        session.send_stdin(b"echo tty-$((40+2))\n")
        session.send_stdin(b"exit\n")
        got = b""
        for frame in session.events():
            blob = frame.get("stdout") or {}
            if blob.get("bytes"):
                got += blob["bytes"]
        assert b"tty-42" in got
        assert session.exit_code == 0

    def test_server_forwards_exec_to_node(self):
        """A server-only agent tunnels the exec websocket to the node
        running the alloc (rpc.go:708 NodeStreamingRpc analog)."""
        dev = Agent(AgentConfig.dev())
        dev.start()
        srv = Agent(AgentConfig(name="hub", num_schedulers=0))
        srv.start()
        try:
            api_dev = APIClient(dev.http_addr)
            job, alloc = run_job(
                dev, api_dev, driver="raw_exec",
                config={"command": "/bin/sh", "args": ["-c", "sleep 30"]},
            )
            # teach the hub about the node + alloc (multi-host
            # registration would do this in a real deployment)
            srv.server.state.upsert_node(dev.client.node.copy())
            full = dev.server.state.snapshot().alloc_by_id(alloc["ID"])
            srv.server.state.upsert_allocs([full.copy_skip_job()])

            hub_api = APIClient(srv.http_addr)
            session = hub_api.allocations.exec_stream(
                alloc["ID"], "web", ["cat"])
            session.send_stdin(b"through-the-tunnel\n")
            session.close_stdin()
            got = b""
            for frame in session.events():
                blob = frame.get("stdout") or {}
                if blob.get("bytes"):
                    got += blob["bytes"]
            assert b"through-the-tunnel" in got
            assert session.exit_code == 0
        finally:
            srv.shutdown()
            dev.shutdown()
