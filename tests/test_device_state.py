"""Device-resident cluster state (ISSUE 3 tentpole): the dirty-row
scatter advance must be bit-identical to a fresh ``ClusterTensors.
build`` + full device upload after any sequence of alloc transitions
and node mutations — the device mirror of tests/test_cluster_delta.py
— including the eviction/miss and structure_version-fork fallback
paths, and the host-identity registry the wave launcher's resident
substitution rides on.
"""

import numpy as np
import numpy.testing as npt
import pytest

jax = pytest.importorskip("jax")

from nomad_tpu import mock  # noqa: E402
from nomad_tpu.state.store import StateStore  # noqa: E402
from nomad_tpu.tensors.device_state import DeviceClusterState  # noqa: E402
from nomad_tpu.tensors.schema import (  # noqa: E402
    ClusterTensors,
    IncrementalClusterCache,
)


def assert_resident_matches_fresh(ds: DeviceClusterState, snap) -> None:
    """The resident generation for ``snap`` must be bit-identical to a
    fresh build of its node table uploaded whole."""
    u = snap.usage
    fresh = ClusterTensors.build(snap.nodes())
    want = fresh.wave_shared_planes(u)
    gen = ds._gens[(u.uid, u.structure_version)]
    for f, host in want.items():
        got = np.asarray(gen.planes[f])
        assert got.dtype == host.dtype, f
        npt.assert_array_equal(got, host, err_msg=f)


@pytest.fixture()
def store():
    s = StateStore()
    for _ in range(24):
        s.upsert_node(mock.node())
    return s


def _ensure(ds, cache, store):
    snap = store.snapshot()
    ds.ensure(cache.get(snap), snap.usage)
    return snap


class TestDeviceDeltaParity:
    def test_alloc_churn_advances_by_scatter(self, store):
        ds = DeviceClusterState()
        cache = IncrementalClusterCache()
        _ensure(ds, cache, store)
        nodes = store.snapshot().nodes()
        store.upsert_allocs(
            [mock.alloc(node_id=nodes[i % 8].id) for i in range(20)])
        snap = _ensure(ds, cache, store)
        assert ds.delta_advances == 1
        assert ds.full_uploads == 1          # only the initial build
        assert ds.rows_uploaded > 0
        assert_resident_matches_fresh(ds, snap)

    def test_structural_update_is_fork_delta(self, store):
        ds = DeviceClusterState()
        cache = IncrementalClusterCache()
        _ensure(ds, cache, store)
        node = store.snapshot().nodes()[5].copy()
        node.node_resources.cpu.cpu_shares = 12345
        store.upsert_node(node)
        snap = _ensure(ds, cache, store)
        assert ds.fork_deltas == 1
        assert_resident_matches_fresh(ds, snap)

    def test_delete_permutes_rows_and_falls_back_to_full(self, store):
        ds = DeviceClusterState()
        cache = IncrementalClusterCache()
        _ensure(ds, cache, store)
        store.delete_node(store.snapshot().nodes()[0].id)
        snap = _ensure(ds, cache, store)
        # compaction moved surviving rows: no device-side gather, so
        # this MUST be a full upload — and still bit-identical
        assert ds.fork_deltas == 0
        assert ds.full_uploads == 2
        assert_resident_matches_fresh(ds, snap)

    def test_random_scatter_sequences(self, store):
        """Property-style: random interleavings of alloc transitions,
        node adds/updates/drains/deletes; device-vs-fresh parity after
        every round."""
        rng = np.random.default_rng(23)
        ds = DeviceClusterState()
        cache = IncrementalClusterCache()
        _ensure(ds, cache, store)
        live_allocs = []
        for _round in range(8):
            for _ in range(int(rng.integers(1, 5))):
                nodes = store.snapshot().nodes()
                pick = nodes[int(rng.integers(0, len(nodes)))]
                op = rng.integers(0, 6)
                if op == 0:
                    a = mock.alloc(node_id=pick.id)
                    live_allocs.append(a)
                    store.upsert_allocs([a])
                elif op == 1 and live_allocs:
                    a = live_allocs.pop(
                        int(rng.integers(0, len(live_allocs))))
                    store.stop_alloc(a.id, [])
                elif op == 2:
                    store.upsert_node(mock.node())
                elif op == 3:
                    n = pick.copy()
                    n.node_resources.cpu.cpu_shares = int(
                        rng.integers(1000, 9000))
                    store.upsert_node(n)
                elif op == 4:
                    store.update_node_drain(pick.id,
                                            bool(rng.integers(0, 2)))
                elif len(nodes) > 4:
                    store.delete_node(pick.id)
            snap = _ensure(ds, cache, store)
            assert_resident_matches_fresh(ds, snap)
        # the scatter paths actually ran (not everything fell back)
        assert ds.delta_advances + ds.fork_deltas >= 2

    def test_trimmed_row_log_falls_back_to_full_usage_upload(self, store):
        from nomad_tpu.state import usage as usage_mod

        ds = DeviceClusterState()
        cache = IncrementalClusterCache()
        _ensure(ds, cache, store)
        nodes = store.snapshot().nodes()
        for i in range(usage_mod.ROW_LOG_MAX + 8):
            store.upsert_allocs(
                [mock.alloc(node_id=nodes[i % 8].id)])
        snap = _ensure(ds, cache, store)
        assert ds.usage_full_uploads == 1
        assert ds.delta_advances == 0
        assert_resident_matches_fresh(ds, snap)


class TestGenerationCache:
    def test_same_version_is_hit(self, store):
        ds = DeviceClusterState()
        cache = IncrementalClusterCache()
        snap = store.snapshot()
        g1 = ds.ensure(cache.get(snap), snap.usage)
        g2 = ds.ensure(cache.get(store.snapshot()), snap.usage)
        assert g1 is g2
        assert ds.hits == 1

    def test_structure_fork_keeps_both_generations(self, store):
        """An in-flight wave still executing against the OLD structure
        version must keep its resident planes while the new version's
        generation advances — the double-buffer contract."""
        ds = DeviceClusterState()
        cache = IncrementalClusterCache()
        old_snap = store.snapshot()
        old_cluster = cache.get(old_snap)
        ds.ensure(old_cluster, old_snap.usage)
        old_host = old_cluster.wave_shared_planes(old_snap.usage)
        old_dev = {f: ds.lookup(h) for f, h in old_host.items()}
        store.upsert_node(mock.node())
        new_snap = _ensure(ds, cache, store)
        assert_resident_matches_fresh(ds, new_snap)
        # the old generation's arrays are still resident and untouched
        for f, host in old_host.items():
            dev = ds.lookup(host)
            assert dev is old_dev[f], f
            npt.assert_array_equal(np.asarray(dev), host, err_msg=f)

    def test_older_snapshot_does_not_demote_generation(self, store):
        """A pipelined eval still on an older usage snapshot must MISS
        (its wave ships host planes) without demoting the advanced
        generation — demotion would full-upload per interleave and
        ping-pong the registry between versions."""
        ds = DeviceClusterState()
        cache = IncrementalClusterCache()
        old_snap = store.snapshot()
        cluster = cache.get(old_snap)
        ds.ensure(cluster, old_snap.usage)
        store.upsert_allocs(
            [mock.alloc(node_id=store.snapshot().nodes()[0].id)])
        new_snap = _ensure(ds, cache, store)
        uploads = ds.full_uploads + ds.usage_full_uploads
        gen_new = ds._gens[(new_snap.usage.uid,
                            new_snap.usage.structure_version)]
        assert ds.ensure(cache.get(old_snap), old_snap.usage) is None
        assert ds.full_uploads + ds.usage_full_uploads == uploads
        assert ds._gens[(new_snap.usage.uid,
                         new_snap.usage.structure_version)] is gen_new
        assert gen_new.version == new_snap.usage.version
        # the stale snapshot's read-only gathered planes must not
        # sneak in through the frozen-singleton path either
        stale_used = cluster.gathered_usage(old_snap.usage)[0]
        assert ds.lookup(stale_used, frozen_ok=False) is None
        assert len(ds._frozen) == 0

    def test_eviction_unregisters_and_miss_rebuilds(self, store):
        ds = DeviceClusterState(max_generations=2)
        cache = IncrementalClusterCache()
        first = store.snapshot()
        first_cluster = cache.get(first)
        ds.ensure(first_cluster, first.usage)
        first_host = first_cluster.wave_shared_planes(first.usage)
        for _ in range(3):
            store.upsert_node(mock.node())
            _ensure(ds, cache, store)
        assert len(ds._gens) == 2
        # the first generation was evicted: its host arrays no longer
        # resolve (mutable arrays need a live registration)
        assert ds.lookup(first_host["cap_cpu"]) is None
        # an ensure for the evicted version is a miss -> full upload,
        # bit-identical by construction
        full_before = ds.full_uploads
        ds.ensure(first_cluster, first.usage)
        assert ds.full_uploads == full_before + 1
        gen = ds._gens[(first.usage.uid, first.usage.structure_version)]
        for f, host in first_host.items():
            npt.assert_array_equal(np.asarray(gen.planes[f]), host,
                                   err_msg=f)


class TestRegistry:
    def test_frozen_singletons_become_resident(self):
        from nomad_tpu.ops.kernel import neutral_planes

        ds = DeviceClusterState()
        host = neutral_planes(64).zeros_f32
        dev1 = ds.lookup(host)
        dev2 = ds.lookup(host)
        assert dev1 is not None and dev1 is dev2
        npt.assert_array_equal(np.asarray(dev1), host)

    def test_mutable_unregistered_array_is_not_resident(self):
        ds = DeviceClusterState()
        assert ds.lookup(np.zeros(8, np.float32)) is None
        assert ds.lookup(3.5) is None
