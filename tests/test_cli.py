"""CLI tests — drive the verb tree against a live in-process agent.

Modeled on the reference's command/*_test.go pattern (testagent + CLI
Run() with captured output).
"""

import json

import pytest

from nomad_tpu import mock
from nomad_tpu.api.agent import Agent, AgentConfig
from nomad_tpu.api.client import APIClient
from nomad_tpu.api.codec import encode
from nomad_tpu.cli.main import main

JOB_HCL = """
job "cli-example" {
  datacenters = ["dc1"]
  type = "service"

  group "web" {
    count = 2

    task "frontend" {
      driver = "mock_driver"
      config {
        run_for = "10s"
      }
      resources {
        cpu    = 500
        memory = 256
      }
    }
  }
}
"""


@pytest.fixture(scope="module")
def agent():
    a = Agent(AgentConfig(name="cli-agent", num_schedulers=1))
    a.start()
    for _ in range(4):
        a.server.node_register(mock.node())
    yield a
    a.shutdown()


@pytest.fixture(scope="module")
def addr(agent):
    return agent.http_addr


@pytest.fixture()
def jobfile(tmp_path):
    p = tmp_path / "example.hcl"
    p.write_text(JOB_HCL)
    return str(p)


def run_cli(addr, *argv):
    return main(["-address", addr, *argv])


class TestJobCommands:
    def test_run_and_status(self, addr, jobfile, capsys):
        rc = run_cli(addr, "job", "run", jobfile)
        out = capsys.readouterr().out
        assert rc == 0, out
        assert 'status "complete"' in out
        assert out.count("Allocation") == 2

        rc = run_cli(addr, "job", "status", "cli-example")
        out = capsys.readouterr().out
        assert rc == 0
        assert "cli-example" in out
        assert "Summary" in out
        assert "Allocations" in out

    def test_job_list(self, addr, capsys):
        rc = run_cli(addr, "job", "status")
        out = capsys.readouterr().out
        assert rc == 0
        assert "cli-example" in out

    def test_plan_detects_change(self, addr, jobfile, capsys):
        # same job, higher count => diff => exit code 1
        changed = JOB_HCL.replace("count = 2", "count = 4")
        p = jobfile + ".changed"
        with open(p, "w") as f:
            f.write(changed)
        rc = run_cli(addr, "job", "plan", p)
        capsys.readouterr()
        assert rc == 1  # non-empty diff

    def test_inspect(self, addr, capsys):
        rc = run_cli(addr, "job", "inspect", "cli-example")
        out = capsys.readouterr().out
        assert rc == 0
        parsed = json.loads(out)
        assert parsed["Job"]["ID"] == "cli-example"

    def test_top_level_run_alias(self, addr, jobfile, capsys):
        rc = run_cli(addr, "run", "-detach", jobfile)
        out = capsys.readouterr().out
        assert rc == 0
        assert "registration successful" in out

    def test_stop(self, addr, capsys):
        rc = run_cli(addr, "job", "stop", "-detach", "-purge", "cli-example")
        capsys.readouterr()
        assert rc == 0
        rc = run_cli(addr, "job", "status", "cli-example")
        err = capsys.readouterr().err
        assert rc == 1
        assert "no jobs match" in err


class TestNodeCommands:
    def test_node_status_list(self, addr, capsys):
        rc = run_cli(addr, "node", "status")
        out = capsys.readouterr().out
        assert rc == 0
        assert "foobar-" in out
        assert "ready" in out

    def test_node_status_one_by_prefix(self, agent, addr, capsys):
        node_id = agent.server.state.snapshot().nodes()[0].id
        rc = run_cli(addr, "node", "status", node_id[:8])
        out = capsys.readouterr().out
        assert rc == 0
        assert node_id in out

    def test_node_eligibility(self, agent, addr, capsys):
        node_id = agent.server.state.snapshot().nodes()[0].id
        rc = run_cli(addr, "node", "eligibility", "-disable", node_id)
        out = capsys.readouterr().out
        assert rc == 0
        assert "ineligible" in out
        rc = run_cli(addr, "node", "eligibility", "-enable", node_id)
        out = capsys.readouterr().out
        assert rc == 0


class TestAllocEvalCommands:
    def test_alloc_and_eval_status(self, agent, addr, jobfile, capsys):
        rc = run_cli(addr, "job", "run", jobfile)
        capsys.readouterr()
        assert rc == 0
        api = APIClient(addr)
        allocs = api.jobs.allocations("cli-example")
        assert allocs
        rc = run_cli(addr, "alloc", "status", allocs[0]["ID"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "cli-example" in out

        rc = run_cli(addr, "eval", "list")
        out = capsys.readouterr().out
        assert rc == 0
        assert "cli-example" in out

        ev_id = allocs[0]["EvalID"]
        rc = run_cli(addr, "eval", "status", ev_id)
        out = capsys.readouterr().out
        assert rc == 0
        assert "complete" in out

    def test_generic_status_resolves_alloc(self, addr, capsys):
        api = APIClient(addr)
        allocs = api.jobs.allocations("cli-example")
        rc = run_cli(addr, "status", allocs[0]["ID"][:8])
        out = capsys.readouterr().out
        assert rc == 0
        assert "Client Status" in out


class TestOperatorCommands:
    def test_scheduler_config(self, addr, capsys):
        rc = run_cli(addr, "operator", "scheduler", "get-config")
        out = capsys.readouterr().out
        assert rc == 0
        assert "binpack" in out
        rc = run_cli(addr, "operator", "scheduler", "set-config",
                     "-scheduler-algorithm", "spread")
        capsys.readouterr()
        assert rc == 0
        rc = run_cli(addr, "operator", "scheduler", "get-config")
        out = capsys.readouterr().out
        assert "spread" in out
        run_cli(addr, "operator", "scheduler", "set-config",
                "-scheduler-algorithm", "binpack")
        capsys.readouterr()

    def test_snapshot_roundtrip(self, addr, tmp_path, capsys):
        snap = str(tmp_path / "state.snap")
        rc = run_cli(addr, "operator", "snapshot", "save", snap)
        out = capsys.readouterr().out
        assert rc == 0
        assert "written" in out
        rc = run_cli(addr, "operator", "snapshot", "restore", snap)
        out = capsys.readouterr().out
        assert rc == 0

    def test_server_members(self, addr, capsys):
        rc = run_cli(addr, "server", "members")
        out = capsys.readouterr().out
        assert rc == 0
        assert "cli-agent" in out


class TestMiscCommands:
    def test_namespace_lifecycle(self, addr, capsys):
        rc = run_cli(addr, "namespace", "apply", "ns-test",
                     "-description", "x")
        capsys.readouterr()
        assert rc == 0
        rc = run_cli(addr, "namespace", "list")
        out = capsys.readouterr().out
        assert "ns-test" in out
        rc = run_cli(addr, "namespace", "delete", "ns-test")
        capsys.readouterr()
        assert rc == 0

    def test_system_gc(self, addr, capsys):
        assert run_cli(addr, "system", "gc") == 0
        capsys.readouterr()

    def test_version(self, addr, capsys):
        assert run_cli(addr, "version") == 0
        assert "nomad-tpu" in capsys.readouterr().out

    def test_dispatch(self, agent, addr, capsys):
        from nomad_tpu.structs.job import ParameterizedJobConfig

        job = mock.simple_job()
        job.parameterized = ParameterizedJobConfig(meta_required=["input"])
        api = APIClient(addr)
        api.jobs.register(encode(job))
        rc = run_cli(addr, "job", "dispatch", "-detach",
                     "-meta", "input=x", job.id)
        out = capsys.readouterr().out
        assert rc == 0
        assert "Dispatched Job ID" in out
