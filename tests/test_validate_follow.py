"""Job validation + follow-mode log streaming tests.

Modeled on reference nomad/job_endpoint_test.go Validate coverage and
client fs_endpoint follow-logs tests.
"""

import threading
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.api.agent import Agent, AgentConfig
from nomad_tpu.api.client import APIClient
from nomad_tpu.api.codec import encode
from nomad_tpu.structs import consts


@pytest.fixture(scope="module")
def agent(tmp_path_factory):
    a = Agent(AgentConfig(name="vf-agent", num_schedulers=1,
                          client_enabled=True))
    a.client.config.data_dir = str(tmp_path_factory.mktemp("client"))
    a.start()
    yield a
    a.shutdown()


@pytest.fixture(scope="module")
def api(agent):
    return APIClient(agent.http_addr)


class TestJobValidate:
    def test_struct_validate(self):
        job = mock.job()
        assert job.validate() == []
        job.priority = 0
        job.task_groups[0].name = ""
        errs = job.validate()
        assert any("priority" in e for e in errs)
        assert any("missing name" in e for e in errs)

    def test_duplicate_groups_and_tasks(self):
        job = mock.job()
        job.task_groups.append(job.task_groups[0].copy())
        errs = job.validate()
        assert any("duplicate task group" in e for e in errs)

    def test_validate_null_fields_report_not_crash(self, api):
        """Arbitrary payloads must produce validation results, not
        500s (null Resources / TaskGroups)."""
        res = api.put("/v1/validate/job", {"Job": {
            "ID": "x", "Name": "x", "Datacenters": ["dc1"],
            "TaskGroups": [{"Name": "g", "Tasks": [
                {"Name": "t", "Driver": "exec", "Resources": None}]}],
        }})
        assert res["ValidationErrors"] == []
        res2 = api.put("/v1/validate/job", {"Job": {
            "ID": "x", "Name": "x", "TaskGroups": None}})
        assert any("task groups" in e for e in res2["ValidationErrors"])

    def test_validate_endpoint(self, api):
        res = api.put("/v1/validate/job", {"Job": encode(mock.job())})
        assert res["ValidationErrors"] == []
        bad = mock.job()
        bad.type = "cron"
        res = api.put("/v1/validate/job", {"Job": encode(bad)})
        assert any("invalid job type" in e
                   for e in res["ValidationErrors"])
        assert res["Error"]

    def test_register_rejects_invalid(self, api):
        bad = mock.job()
        bad.datacenters = []
        from nomad_tpu.api.client import APIError
        with pytest.raises(APIError):
            api.jobs.register(encode(bad))


class TestFollowLogs:
    def test_follow_streams_live_output(self, agent, api):
        job = mock.job()
        job.type = consts.JOB_TYPE_BATCH
        job.task_groups[0].count = 1
        task = job.task_groups[0].tasks[0]
        task.driver = "raw_exec"
        task.config = {
            "command": "/bin/sh",
            "args": ["-c",
                     "echo first; sleep 1.2; echo second; sleep 0.3"],
        }
        api.jobs.register(encode(job))
        deadline = time.time() + 60
        alloc_id = ""
        while time.time() < deadline and not alloc_id:
            allocs = api.get(f"/v1/job/{job.id}/allocations")
            running = [a for a in allocs
                       if a["ClientStatus"] in ("running", "complete")]
            if running:
                alloc_id = running[0]["ID"]
            time.sleep(0.2)
        assert alloc_id, "alloc never started"

        chunks = []
        got_first = threading.Event()

        def consume():
            for chunk in api.allocations.logs_follow(
                    alloc_id, task.name, "stdout", timeout=60):
                chunks.append((time.time(), chunk.decode()))
                if b"first" in chunk:
                    got_first.set()

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        assert got_first.wait(timeout=30), "first line never streamed"
        t.join(timeout=30)
        assert not t.is_alive(), "follow stream didn't end with the task"
        text = "".join(c for _, c in chunks)
        assert "first" in text and "second" in text
        # 'second' must have arrived in a later chunk than 'first'
        # (live tail, not one buffered read)
        first_t = next(ts for ts, c in chunks if "first" in c)
        second_t = next(ts for ts, c in chunks if "second" in c)
        assert second_t > first_t
