"""Native service-discovery tests.

Modeled on reference nomad/service_registration_endpoint_test.go,
state_store_service_registration_test.go, and the client
serviceregistration wrapper tests (client/serviceregistration/nsd).
"""

import time

import pytest

from nomad_tpu import mock, structs
from nomad_tpu.client import Client, ClientConfig, InProcessRPC
from nomad_tpu.server.server import Server, ServerConfig
from nomad_tpu.structs import consts
from nomad_tpu.structs.job import Service
from nomad_tpu.structs.services import ServiceRegistration, registration_id


def wait_for(fn, timeout=15.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return
        time.sleep(0.02)
    raise AssertionError(f"timeout waiting for {msg}")


def make_reg(reg_id="r1", name="web", alloc_id="a1", node_id="n1", **kw):
    return ServiceRegistration(
        id=reg_id, service_name=name, alloc_id=alloc_id, node_id=node_id,
        job_id=kw.pop("job_id", "j1"), address=kw.pop("address", "10.0.0.1"),
        port=kw.pop("port", 8080), **kw,
    )


class TestStateStore:
    def test_upsert_list_delete(self):
        server = Server(ServerConfig(num_workers=0))
        server.start()
        try:
            server.service_register([make_reg(), make_reg("r2", "db")])
            assert len(server.state.service_registrations()) == 2
            assert [r.id for r in
                    server.state.service_registrations_by_name(
                        "default", "web")] == ["r1"]
            server.service_deregister("r1")
            assert len(server.state.service_registrations()) == 1
            with pytest.raises(ValueError):
                server.service_deregister("r1")
        finally:
            server.shutdown()

    def test_delete_by_alloc_and_node(self):
        server = Server(ServerConfig(num_workers=0))
        server.start()
        try:
            server.service_register([
                make_reg("r1", alloc_id="a1", node_id="n1"),
                make_reg("r2", alloc_id="a2", node_id="n1"),
                make_reg("r3", alloc_id="a3", node_id="n2"),
            ])
            server.service_deregister_by_alloc(["a1"])
            assert {r.id for r in server.state.service_registrations()} == \
                {"r2", "r3"}
            server.state.delete_service_registrations_by_node("n1")
            assert {r.id for r in server.state.service_registrations()} == \
                {"r3"}
        finally:
            server.shutdown()

    def test_alloc_gc_reaps_registrations(self):
        server = Server(ServerConfig(num_workers=0))
        server.start()
        try:
            server.service_register([make_reg("r1", alloc_id="a1")])
            server.state.delete_allocs(["a1"])
            assert server.state.service_registrations() == []
        finally:
            server.shutdown()

    def test_validation(self):
        with pytest.raises(ValueError):
            ServiceRegistration(id="x", service_name="web").validate()

    def test_registration_id_stable(self):
        assert registration_id("web", "a1", "t1") == \
            registration_id("web", "a1", "t1")
        assert registration_id("web", "a1", "t1") != \
            registration_id("web", "a2", "t1")
        # same service name on one task, two port labels -> distinct ids
        assert registration_id("web", "a1", "t1", "http") != \
            registration_id("web", "a1", "t1", "metrics")


class TestNodeDownReaping:
    def test_node_down_removes_its_services(self):
        server = Server(ServerConfig(num_workers=0))
        server.start()
        try:
            node = mock.node()
            server.node_register(node)
            server.service_register([
                make_reg("r1", node_id=node.id),
                make_reg("r2", node_id="other-node"),
            ])
            server.node_update_status(node.id, consts.NODE_STATUS_DOWN)
            assert {r.id for r in server.state.service_registrations()} == \
                {"r2"}
        finally:
            server.shutdown()


class TestEndToEnd:
    def test_service_registered_while_task_runs(self):
        server = Server(ServerConfig(heartbeat_ttl=60.0))
        server.start()
        client = None
        try:
            client = Client(
                InProcessRPC(server),
                ClientConfig(data_dir="/tmp/nomad-tpu-test-svc"),
            )
            client.start()
            wait_for(
                lambda: any(n.ready() for n in server.state.snapshot().nodes()),
                msg="node ready",
            )

            job = mock.job()
            job.task_groups[0].count = 1
            task = job.task_groups[0].tasks[0]
            task.driver = "mock_driver"
            task.config = {"run_for": 30}
            task.services = [Service(name="web-svc", provider="builtin",
                                     tags=["prod", "http"])]
            server.job_register(job)

            wait_for(
                lambda: server.state.service_registrations_by_name(
                    "default", "web-svc"),
                msg="service registered",
            )
            regs = server.state.service_registrations_by_name(
                "default", "web-svc"
            )
            assert regs[0].job_id == job.id
            assert regs[0].address
            assert regs[0].tags == ["prod", "http"]

            # stop -> task dead -> client deregisters
            server.job_deregister("default", job.id)
            wait_for(
                lambda: not server.state.service_registrations_by_name(
                    "default", "web-svc"),
                msg="service deregistered",
            )
        finally:
            if client is not None:
                client.shutdown()
            server.shutdown()


class TestHTTP:
    def test_services_over_http(self):
        from nomad_tpu.api.agent import Agent, AgentConfig
        from nomad_tpu.api.client import APIClient

        agent = Agent(AgentConfig(num_schedulers=0))
        agent.start()
        try:
            agent.server.service_register([
                make_reg("r1", "web", tags=["a"]),
                make_reg("r2", "web", alloc_id="a2", tags=["b"]),
                make_reg("r3", "db"),
            ])
            api = APIClient(agent.http.addr)
            listing = api.services.list()
            assert listing[0]["Namespace"] == "default"
            names = {s["ServiceName"] for s in listing[0]["Services"]}
            assert names == {"web", "db"}
            web = next(s for s in listing[0]["Services"]
                       if s["ServiceName"] == "web")
            assert web["Tags"] == ["a", "b"]

            regs = api.services.get("web")
            assert [r["ID"] for r in regs] == ["r1", "r2"]
            assert regs[0]["Port"] == 8080

            # delete is scoped by service name + namespace
            from nomad_tpu.api.client import APIError
            with pytest.raises(APIError):
                api.services.delete("db", "r1")     # wrong name
            api.services.delete("web", "r1")
            assert [r["ID"] for r in api.services.get("web")] == ["r2"]
        finally:
            agent.shutdown()
