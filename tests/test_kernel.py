"""Kernel golden-parity tests.

An independent pure-Python oracle reproduces the Go iterator semantics
(feasible.go / rank.go / spread.go / select.go MaxScore) with float64
math; the JAX kernel must match its choices exactly and its scores to
float32 tolerance. This is the port of the reference's scheduler unit
tests' role (rank_test.go, spread_test.go) onto the batched formulation.
"""

import math

import numpy as np
import pytest

from nomad_tpu.ops.kernel import KernelOut, build_kernel_in, pad_steps, place_taskgroup_jit
from nomad_tpu.tensors.schema import (
    MAX_DEV_REQS,
    SPREAD_BUCKETS,
    AskTensor,
    ClusterTensors,
    EvalTensors,
    SpreadTensor,
    pad_bucket,
)


# ---------------------------------------------------------------------------
# Helpers to build small synthetic clusters without full structs
# ---------------------------------------------------------------------------


def make_cluster(caps):
    """caps: list of (cpu, mem) tuples."""
    n = len(caps)
    npad = pad_bucket(n)
    c = ClusterTensors(
        n_real=n,
        n_pad=npad,
        node_ids=[f"node-{i}" for i in range(n)],
        index={f"node-{i}": i for i in range(n)},
        cap_cpu=np.zeros(npad, np.float32),
        cap_mem=np.zeros(npad, np.float32),
        cap_disk=np.full(npad, 1 << 20, np.float32),
        ready=np.zeros(npad, bool),
        port_words=np.zeros((npad, 2048), np.uint32),
        free_dyn=np.full(npad, 12001, np.int32),
        free_cores=np.full(npad, 8, np.int32),
        shares_per_core=np.full(npad, 1000.0, np.float32),
        datacenters=["dc1"] * n,
        node_classes=[""] * n,
        computed_classes=["c0"] * n,
        node_pools=["default"] * n,
    )
    for i, (cpu, mem) in enumerate(caps):
        c.cap_cpu[i] = cpu
        c.cap_mem[i] = mem
        c.ready[i] = True
    return c


def make_eval(cluster, ask=None, **kw):
    n = cluster.n_pad
    base = np.zeros(n, bool)
    base[: cluster.n_real] = True
    ev = EvalTensors(
        base_mask=kw.get("base_mask", base),
        used_cpu=kw.get("used_cpu", np.zeros(n, np.float32)),
        used_mem=kw.get("used_mem", np.zeros(n, np.float32)),
        used_disk=np.zeros(n, np.float32),
        used_mbits=np.zeros(n, np.int32),
        avail_mbits=np.full(n, 1000, np.int32),
        used_cores=np.zeros(n, np.int32),
        port_conflict_words=np.zeros((n, 2048), np.uint32),
        free_dyn_delta=np.zeros(n, np.int32),
        dev_free=kw.get("dev_free", np.zeros((n, MAX_DEV_REQS), np.float32)),
        dev_aff_score=kw.get("dev_aff_score", np.zeros(n, np.float32)),
        has_dev_affinity=kw.get("has_dev_affinity", False),
        job_tg_count=kw.get("job_tg_count", np.zeros(n, np.int32)),
        job_any_count=kw.get("job_any_count", np.zeros(n, np.int32)),
        distinct_hosts_job=kw.get("distinct_hosts_job", False),
        distinct_hosts_tg=kw.get("distinct_hosts_tg", False),
        penalty=kw.get("penalty", np.zeros(n, bool)),
        aff_score=kw.get("aff_score", np.zeros(n, np.float32)),
        has_affinities=bool(np.any(kw.get("aff_score", np.zeros(1)) != 0)),
        spreads=kw.get("spreads", []),
        ask=ask or AskTensor.build_from_simple(),
        desired_count=kw.get("desired_count", 1),
        algorithm=kw.get("algorithm", "binpack"),
    )
    return ev


def simple_ask(cpu=500, mem=256, disk=0, dyn=0, dev=None):
    a = AskTensor()
    a.cpu, a.mem, a.disk = float(cpu), float(mem), float(disk)
    a.n_dyn_ports = dyn
    a.reserved_ports = []
    a.port_mask = np.zeros(2048, np.uint32)
    a.dev_counts = np.zeros(MAX_DEV_REQS, np.int32)
    if dev:
        for i, d in enumerate(dev):
            a.dev_counts[i] = d
    return a


AskTensor.build_from_simple = staticmethod(simple_ask)


def run_kernel(cluster, ev, k):
    kin = build_kernel_in(cluster, ev, k)
    out = place_taskgroup_jit(kin, pad_steps(k))
    return KernelOut(*[np.asarray(x) for x in out])


# ---------------------------------------------------------------------------
# The float64 oracle (Go semantics)
# ---------------------------------------------------------------------------


def oracle_place(cluster, ev, k):
    """Sequential max-score placement with Go's scoring rules."""
    n = cluster.n_real
    used_cpu = ev.used_cpu.astype(np.float64).copy()
    used_mem = ev.used_mem.astype(np.float64).copy()
    job_cnt = ev.job_tg_count.astype(np.int64).copy()
    dev_free = ev.dev_free.astype(np.float64).copy()
    free_dyn = (cluster.free_dyn - ev.free_dyn_delta).astype(np.int64).copy()
    sp_counts = [s.counts.astype(np.float64).copy() for s in ev.spreads]
    results = []
    ask = ev.ask
    for _ in range(k):
        best_i, best_s = -1, None
        for i in range(n):
            if not ev.base_mask[i]:
                continue
            cap_c, cap_m = cluster.cap_cpu[i], cluster.cap_mem[i]
            if cap_c - used_cpu[i] < ask.cpu or cap_m - used_mem[i] < ask.mem:
                continue
            if free_dyn[i] < ask.n_dyn_ports:
                continue
            if np.any(dev_free[i] < ask.dev_counts):
                continue
            util_c, util_m = used_cpu[i] + ask.cpu, used_mem[i] + ask.mem
            fc = 1 - util_c / cap_c if cap_c > 0 else 0.0
            fm = 1 - util_m / cap_m if cap_m > 0 else 0.0
            total = 10.0 ** fc + 10.0 ** fm
            if ev.algorithm == "spread":
                raw = min(max(total - 2.0, 0.0), 18.0)
            else:
                raw = min(max(20.0 - total, 0.0), 18.0)
            scores = [raw / 18.0]
            if ev.has_dev_affinity:
                scores.append(float(ev.dev_aff_score[i]))
            col = int(job_cnt[i])
            if col > 0:
                scores.append(-(col + 1) / max(ev.desired_count, 1))
            if ev.penalty[i]:
                scores.append(-1.0)
            if ev.aff_score[i] != 0.0:
                scores.append(float(ev.aff_score[i]))
            sp_total = 0.0
            for s_i, sp in enumerate(ev.spreads):
                b = int(sp.bucket_id[i])
                if b < 0:
                    sp_total += -1.0
                    continue
                cnt = sp_counts[s_i][b]
                if sp.even:
                    counts = sp_counts[s_i]
                    present = counts > 0
                    if not present.any():
                        continue
                    minc = counts[present].min()
                    maxc = counts[present].max()
                    if cnt != minc:
                        sp_total += (minc - cnt) / minc if minc > 0 else -1.0
                    elif minc == maxc:
                        sp_total += -1.0
                    elif minc == 0:
                        sp_total += 1.0
                    else:
                        sp_total += (maxc - minc) / minc
                else:
                    des = sp.desired[b]
                    if des > 0:
                        sp_total += ((des - (cnt + 1)) / des) * sp.weight_frac
                    else:
                        sp_total += -1.0
            if sp_total != 0.0:
                scores.append(sp_total)
            final = sum(scores) / len(scores)
            if best_s is None or final > best_s:
                best_i, best_s = i, final
        if best_i < 0:
            results.append((-1, 0.0))
            continue
        results.append((best_i, best_s))
        used_cpu[best_i] += ask.cpu
        used_mem[best_i] += ask.mem
        job_cnt[best_i] += 1
        dev_free[best_i] -= ask.dev_counts
        free_dyn[best_i] -= ask.n_dyn_ports
        for s_i, sp in enumerate(ev.spreads):
            b = int(sp.bucket_id[best_i])
            if b >= 0:
                sp_counts[s_i][b] += 1
    return results


def assert_parity(cluster, ev, k):
    out = run_kernel(cluster, ev, k)
    want = oracle_place(cluster, ev, k)
    for step, (wi, ws) in enumerate(want):
        assert out.chosen[step] == wi, (
            f"step {step}: kernel chose {out.chosen[step]}, oracle {wi} "
            f"(kernel score {out.scores[step]}, oracle {ws})"
        )
        if wi >= 0:
            assert out.scores[step] == pytest.approx(ws, abs=2e-5)
    return out


# ---------------------------------------------------------------------------
# Tests
# ---------------------------------------------------------------------------


class TestBinpackScoring:
    def test_picks_most_packed_feasible(self):
        # binpack prefers the node that ends up most utilized
        cluster = make_cluster([(4000, 8192), (4000, 8192), (4000, 8192)])
        used = np.zeros(cluster.n_pad, np.float32)
        used[1] = 2000  # node 1 is half full on cpu
        ev = make_eval(cluster, ask=simple_ask(), used_cpu=used)
        out = assert_parity(cluster, ev, 1)
        assert out.chosen[0] == 1

    def test_score_matches_structs_math(self):
        from nomad_tpu import structs, mock

        cluster = make_cluster([(4000, 8192)])
        ev = make_eval(cluster, ask=simple_ask(cpu=2000, mem=4096))
        out = run_kernel(cluster, ev, 1)
        node = mock.node()
        node.node_resources.cpu.cpu_shares = 4000
        node.node_resources.memory.memory_mb = 8192
        node.reserved_resources = structs.NodeReservedResources()
        want = structs.score_fit_binpack(
            node, structs.ComparableResources(cpu_shares=2000, memory_mb=4096)
        ) / 18.0
        assert out.scores[0] == pytest.approx(want, abs=2e-5)  # f32 pow

    def test_spread_algorithm_flips_score(self):
        cluster = make_cluster([(4000, 8192), (4000, 8192)])
        used = np.zeros(cluster.n_pad, np.float32)
        used[0] = 2000
        ev = make_eval(cluster, ask=simple_ask(), used_cpu=used, algorithm="spread")
        out = assert_parity(cluster, ev, 1)
        assert out.chosen[0] == 1  # worst-fit prefers the empty node

    def test_infeasible_all(self):
        cluster = make_cluster([(400, 512)])
        ev = make_eval(cluster, ask=simple_ask(cpu=500, mem=256))
        out = run_kernel(cluster, ev, 1)
        assert out.chosen[0] == -1
        assert not out.found[0]
        assert out.exhausted_cpu == 1


class TestSequentialDeduction:
    def test_resources_deducted_between_placements(self):
        # one node fits exactly two asks; third placement must go elsewhere
        cluster = make_cluster([(1000, 1024), (4000, 8192)])
        used = np.zeros(cluster.n_pad, np.float32)
        used[1] = 3000  # node 1 more packed -> preferred until full
        ev = make_eval(cluster, ask=simple_ask(cpu=500, mem=256), used_cpu=used)
        assert_parity(cluster, ev, 5)

    def test_exhaustion_mid_sequence(self):
        cluster = make_cluster([(1000, 512), (1000, 512)])
        ev = make_eval(cluster, ask=simple_ask(cpu=400, mem=200))
        out = assert_parity(cluster, ev, 5)
        # 2 per node fit (400*2=800<1000, 200*2=400<512), 5th fails
        assert list(out.found[:5]) == [True, True, True, True, False]


class TestAntiAffinity:
    def test_collision_penalty(self):
        cluster = make_cluster([(4000, 8192), (4000, 8192)])
        cnt = np.zeros(cluster.n_pad, np.int32)
        cnt[0] = 2  # node 0 already has 2 allocs of this job/tg
        ev = make_eval(
            cluster, ask=simple_ask(), job_tg_count=cnt, desired_count=10
        )
        out = assert_parity(cluster, ev, 1)
        assert out.chosen[0] == 1

    def test_spreads_across_nodes(self):
        # with anti-affinity via job_tg_count updates, placements alternate
        cluster = make_cluster([(8000, 16384), (8000, 16384)])
        ev = make_eval(cluster, ask=simple_ask(), desired_count=4)
        out = assert_parity(cluster, ev, 4)
        assert sorted(np.bincount(out.chosen[:4], minlength=2)[:2].tolist()) == [2, 2]


class TestPenaltyAndAffinity:
    def test_reschedule_penalty(self):
        cluster = make_cluster([(4000, 8192), (4000, 8192)])
        pen = np.zeros(cluster.n_pad, bool)
        pen[0] = True
        ev = make_eval(cluster, ask=simple_ask(), penalty=pen)
        out = assert_parity(cluster, ev, 1)
        assert out.chosen[0] == 1

    def test_node_affinity_attracts(self):
        cluster = make_cluster([(4000, 8192), (4000, 8192)])
        aff = np.zeros(cluster.n_pad, np.float32)
        aff[0] = 0.8
        ev = make_eval(cluster, ask=simple_ask(), aff_score=aff)
        out = assert_parity(cluster, ev, 1)
        assert out.chosen[0] == 0

    def test_negative_affinity_repels(self):
        cluster = make_cluster([(4000, 8192), (4000, 8192)])
        aff = np.zeros(cluster.n_pad, np.float32)
        aff[0] = -0.5
        ev = make_eval(cluster, ask=simple_ask(), aff_score=aff)
        out = assert_parity(cluster, ev, 1)
        assert out.chosen[0] == 1


class TestSpreadStanza:
    def _spread(self, cluster, buckets, counts, desired, weight=1.0, even=False):
        b = np.full(cluster.n_pad, -1, np.int32)
        b[: len(buckets)] = buckets
        c = np.zeros(SPREAD_BUCKETS, np.float32)
        c[: len(counts)] = counts
        d = np.full(SPREAD_BUCKETS, -1.0, np.float32)
        if desired is not None:
            d[: len(desired)] = desired
        return SpreadTensor(
            bucket_id=b, counts=c, desired=d if desired is not None else np.full(SPREAD_BUCKETS, -1.0, np.float32),
            weight_frac=weight, even=even,
        )

    def test_desired_count_spread(self):
        # 4 nodes: dc0,dc0,dc1,dc1; desire 3 in dc0, 1 in dc1 (count 4)
        cluster = make_cluster([(4000, 8192)] * 4)
        sp = self._spread(
            cluster, buckets=[0, 0, 1, 1], counts=[0, 0], desired=[3.0, 1.0]
        )
        ev = make_eval(cluster, ask=simple_ask(), spreads=[sp], desired_count=4)
        out = assert_parity(cluster, ev, 4)
        placed = out.chosen[:4]
        dc0 = sum(1 for i in placed if i in (0, 1))
        assert dc0 == 3  # 3 of 4 land in dc0

    def test_even_spread(self):
        cluster = make_cluster([(8000, 16384)] * 4)
        sp = self._spread(
            cluster, buckets=[0, 0, 1, 1], counts=[2, 0], desired=None, even=True
        )
        ev = make_eval(cluster, ask=simple_ask(), spreads=[sp], desired_count=2)
        out = assert_parity(cluster, ev, 2)
        # bucket 1 has fewer allocs -> both placements favor nodes 2,3
        assert set(out.chosen[:2].tolist()) == {2, 3}

    def test_missing_attribute_penalized(self):
        cluster = make_cluster([(4000, 8192), (4000, 8192)])
        b = np.full(cluster.n_pad, -1, np.int32)
        b[0] = 0  # node 1 lacks the attribute
        sp = SpreadTensor(
            bucket_id=b,
            counts=np.zeros(SPREAD_BUCKETS, np.float32),
            desired=np.full(SPREAD_BUCKETS, -1.0, np.float32),
            weight_frac=1.0,
            even=True,
        )
        ev = make_eval(cluster, ask=simple_ask(), spreads=[sp])
        out = assert_parity(cluster, ev, 1)
        assert out.chosen[0] == 0


class TestPortsAndDevices:
    def test_reserved_port_conflict(self):
        cluster = make_cluster([(4000, 8192), (4000, 8192)])
        # node 0 has port 8080 in use
        cluster.port_words[0, 8080 // 32] |= np.uint32(1 << (8080 % 32))
        ask = simple_ask()
        ask.reserved_ports.append(8080)
        ask.port_mask[8080 // 32] |= np.uint32(1 << (8080 % 32))
        ev = make_eval(cluster, ask=ask)
        out = run_kernel(cluster, ev, 2)
        assert out.chosen[0] == 1
        # second placement of same group also needs 8080 -> node 1 now
        # conflicts with itself -> no placement
        assert out.chosen[1] == -1
        assert out.exhausted_ports >= 1

    def test_dynamic_port_exhaustion(self):
        cluster = make_cluster([(4000, 8192)])
        cluster.free_dyn[0] = 1
        ev = make_eval(cluster, ask=simple_ask(dyn=2))
        out = run_kernel(cluster, ev, 1)
        assert out.chosen[0] == -1

    def test_device_fit_and_deduction(self):
        cluster = make_cluster([(4000, 8192), (4000, 8192)])
        dev = np.zeros((cluster.n_pad, MAX_DEV_REQS), np.float32)
        dev[0, 0] = 2  # node 0 has 2 GPUs free
        dev[1, 0] = 1
        ev = make_eval(cluster, ask=simple_ask(dev=[1]), dev_free=dev)
        out = assert_parity(cluster, ev, 3)
        # 3 placements: two on node 0, one on node 1 (order per scoring)
        assert sorted(out.chosen[:3].tolist()) == [0, 0, 1]
        assert bool(out.found[2])

    def test_device_affinity_plane(self):
        cluster = make_cluster([(4000, 8192), (4000, 8192)])
        dev = np.ones((cluster.n_pad, MAX_DEV_REQS), np.float32)
        daff = np.zeros(cluster.n_pad, np.float32)
        daff[1] = 0.9
        ev = make_eval(
            cluster, ask=simple_ask(dev=[1]), dev_free=dev,
            dev_aff_score=daff, has_dev_affinity=True,
        )
        out = assert_parity(cluster, ev, 1)
        assert out.chosen[0] == 1


class TestMetrics:
    def test_counts(self):
        cluster = make_cluster([(4000, 8192), (400, 128), (4000, 8192)])
        base = np.zeros(cluster.n_pad, bool)
        base[:3] = True
        base[2] = False  # class-filtered
        ev = make_eval(cluster, ask=simple_ask(), base_mask=base)
        out = run_kernel(cluster, ev, 1)
        assert out.nodes_evaluated == 2
        assert out.nodes_feasible == 1
        assert out.exhausted_cpu == 1
        assert out.exhausted_mem == 1


class TestStepPadding:
    def test_padded_steps_inactive(self):
        cluster = make_cluster([(8000, 16384)])
        ev = make_eval(cluster, ask=simple_ask())
        kin = build_kernel_in(cluster, ev, 3)
        out = place_taskgroup_jit(kin, pad_steps(3))  # pads to 4
        out = KernelOut(*[np.asarray(x) for x in out])
        assert list(out.found[:3]) == [True, True, True]
        assert not out.found[3]  # padded step places nothing

    def test_pad_steps_buckets(self):
        assert pad_steps(1) == 1
        assert pad_steps(3) == 4
        assert pad_steps(100) == 128
        assert pad_steps(5000) == 8192


class TestKernelFeatures:
    """Static specialization must not change semantics when the
    disabled features' inputs are neutral."""

    def test_lean_matches_full(self):
        import numpy as np

        from nomad_tpu.ops.kernel import (
            FULL_FEATURES,
            KernelFeatures,
            KernelOut,
            place_taskgroup_jit,
        )
        from nomad_tpu.parallel.synthetic import synthetic_kernel_in

        kin = synthetic_kernel_in(n_nodes=100, n_steps=8, used_frac=0.5)
        lean = KernelFeatures(
            n_spreads=0, with_topk=False, with_devices=False,
            with_ports=False, with_cores=False, with_network=False,
            with_distinct=False, with_step_penalties=False,
            with_preferred=False,
        )
        full = KernelOut(*[np.asarray(x) for x in place_taskgroup_jit(kin, 8, FULL_FEATURES)])
        got = KernelOut(*[np.asarray(x) for x in place_taskgroup_jit(kin, 8, lean)])
        np.testing.assert_array_equal(got.chosen, full.chosen)
        np.testing.assert_array_equal(got.found, full.found)
        np.testing.assert_allclose(got.scores, full.scores, rtol=1e-6)

    def test_spread_specialization(self):
        import numpy as np

        from nomad_tpu.ops.kernel import (
            FULL_FEATURES,
            KernelOut,
            infer_features,
            place_taskgroup_jit,
        )
        from nomad_tpu.ops.kernel import build_kernel_in
        from nomad_tpu.parallel.synthetic import synthetic_cluster, synthetic_eval

        cluster = synthetic_cluster(100, seed=3)
        ev = synthetic_eval(cluster, with_spread=True, used_frac=0.3, seed=3)
        kin = build_kernel_in(cluster, ev, 8)
        feats = infer_features(ev)
        assert feats.n_spreads == 1
        full = KernelOut(*[np.asarray(x) for x in place_taskgroup_jit(kin, 8, FULL_FEATURES)])
        got = KernelOut(*[np.asarray(x) for x in place_taskgroup_jit(kin, 8, feats)])
        np.testing.assert_array_equal(got.chosen, full.chosen)
        np.testing.assert_allclose(got.scores, full.scores, rtol=1e-6)


class TestCandidateKernel:
    """place_taskgroup_topk: candidate-set placement must be exact.

    The bound argument: every score-mutating plane moves non-chosen
    nodes down or not at all (no spreads), so the max over
    non-candidates is a standing upper bound; the kernel flags
    ``valid=False`` whenever a step's choice falls below it.
    """

    def _kin(self, rng, n, with_extras=False):
        import numpy as np

        from nomad_tpu.ops.kernel import build_kernel_in
        from nomad_tpu.parallel.synthetic import (
            synthetic_cluster, synthetic_eval,
        )

        cluster = synthetic_cluster(
            n, cpu=3900.0, mem=7936.0, disk=98304.0,
            seed=int(rng.integers(0, 99)))
        ev = synthetic_eval(cluster, desired_count=10)
        kwargs = {}
        if with_extras:
            pen = np.full((16, 4), -1, np.int32)
            pen[0, 0] = rng.integers(0, n)
            pref = np.full(16, -1, np.int32)
            pref[2] = rng.integers(0, n)
            kwargs = dict(
                step_penalty=pen, step_preferred=pref,
                node_perm=rng.permutation(cluster.n_pad).astype(np.int32),
            )
        kin = build_kernel_in(cluster, ev, 10, **kwargs)
        uc = (3900 * 0.7 * rng.random(cluster.n_pad)).astype(np.float32)
        um = (7936 * 0.7 * rng.random(cluster.n_pad)).astype(np.float32)
        return kin._replace(
            used_cpu=uc, used_mem=um,
            ask_cpu=np.float32(rng.choice([250, 500, 900])),
            ask_mem=np.float32(rng.choice([128, 256, 700])),
        )

    def test_matches_full_kernel(self):
        import numpy as np

        from nomad_tpu.ops.kernel import (
            LEAN_FEATURES, pad_steps, place_taskgroup_jit,
            place_taskgroup_topk_jit,
        )

        rng = np.random.default_rng(17)
        feats_variants = [
            (LEAN_FEATURES, False),
            (LEAN_FEATURES._replace(with_topk=True, with_distinct=True),
             False),
            (LEAN_FEATURES._replace(
                with_step_penalties=True, with_preferred=True,
                with_shuffle=True), True),
        ]
        k = pad_steps(10)
        for trial in range(6):
            feats, extras = feats_variants[trial % 3]
            kin = self._kin(rng, int(rng.choice([60, 400])), extras)
            full = place_taskgroup_jit(kin, k, feats)
            topk, ok = place_taskgroup_topk_jit(kin, k, feats)
            if not bool(ok):
                continue  # bound breached: caller re-runs full kernel
            assert np.array_equal(
                np.asarray(full.chosen), np.asarray(topk.chosen)), trial
            assert np.array_equal(
                np.asarray(full.found), np.asarray(topk.found)), trial
            assert np.allclose(
                np.asarray(full.scores), np.asarray(topk.scores),
                atol=1e-6), trial

    def test_invalid_flag_on_tiny_feasible_set(self):
        """When the cluster nearly saturates, candidates can exhaust;
        the kernel must flag it rather than silently fail placements
        the wider cluster could serve."""
        import numpy as np

        from nomad_tpu.ops.kernel import (
            LEAN_FEATURES, pad_steps, place_taskgroup_jit,
            place_taskgroup_topk_jit,
        )

        rng = np.random.default_rng(3)
        kin = self._kin(rng, 400)
        # leave only a sliver of cpu on every node: ask barely fits
        kin = kin._replace(
            used_cpu=np.full_like(kin.used_cpu, 3900.0 - 510.0),
            ask_cpu=np.float32(500.0),
        )
        k = pad_steps(10)
        full = place_taskgroup_jit(kin, k, LEAN_FEATURES)
        topk, ok = place_taskgroup_topk_jit(kin, k, LEAN_FEATURES)
        if bool(ok):
            assert np.array_equal(
                np.asarray(full.chosen), np.asarray(topk.chosen))
        else:
            # fallback path: full kernel remains the source of truth
            assert np.asarray(full.found).sum() >= np.asarray(topk.found).sum()
