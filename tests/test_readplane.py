"""The follower read plane: consistency-mode routing (ISSUE 20).

Reference behavior: Nomad's QueryOptions consistency knobs
(api/api.go AllowStale / nomad/rpc.go blockingOptions) — ``?stale`` +
``max_stale=<dur>`` route reads to any server with
``X-Nomad-LastContact`` / ``X-Nomad-KnownLeader`` attribution, the
default mode is leader-preferred, and linearizable reads are
leader-only (raft §6.4 ReadIndex fences follower default reads).

Tier-1 coverage: query-param parsing at the HTTP boundary, the three
modes over real HTTP against a REAL 3-server cluster (stale serves on
followers with bounded attribution and rejects loudly over the bound;
default forwards the read fence; linearizable 503s off-leader with a
leader hint), ACL parity on followers (anonymous/weak tokens get the
same 403s a leader hands out), and the pinned-seed mini smoke
(bench/trace_report.py run_readplane_smoke: stale + forwarded default
+ lease-lapse demotion on a durable cluster).
"""

import json
import os
import sys
import time
import urllib.error
import urllib.request
from types import SimpleNamespace

import pytest

from nomad_tpu.api.http import HTTPAgent, HTTPError, Request
from nomad_tpu.server.readplane import ReadStats, read_stats
from nomad_tpu.server.testing import make_cluster, wait_for_leader, wait_until


# -- query-param parsing (parseConsistency) ------------------------------

def _req(query):
    q = {k: v if isinstance(v, list) else [v] for k, v in query.items()}
    return Request("GET", "/v1/jobs", {}, q, None, "", None)


class TestConsistencyParams:
    def test_no_params_is_default(self):
        assert _req({}).consistency_params() == ("default", None)

    def test_stale_flag(self):
        assert _req({"stale": "true"}).consistency_params() == ("stale", None)
        assert _req({"stale": "1"}).consistency_params() == ("stale", None)

    def test_stale_false_stays_default(self):
        assert _req({"stale": "false"}).consistency_params()[0] == "default"

    def test_max_stale_implies_stale(self):
        assert _req({"max_stale": "30s"}).consistency_params() == \
            ("stale", 30.0)
        assert _req({"max_stale": "500ms"}).consistency_params() == \
            ("stale", 0.5)
        assert _req({"max_stale": "1m"}).consistency_params() == \
            ("stale", 60.0)

    def test_bad_max_stale_is_400(self):
        with pytest.raises(HTTPError) as e:
            _req({"max_stale": "banana"}).consistency_params()
        assert e.value.status == 400

    def test_unknown_mode_is_400(self):
        with pytest.raises(HTTPError) as e:
            _req({"consistency": "quorum"}).consistency_params()
        assert e.value.status == 400

    def test_explicit_mode_wins_over_stale_flag(self):
        mode, _ = _req({"consistency": "linearizable",
                        "stale": "true"}).consistency_params()
        assert mode == "linearizable"


class TestReadStats:
    def test_follower_share_and_reset(self):
        rs = ReadStats()
        rs.note_request("stale")
        rs.note_served("follower", 0.01)
        rs.note_served("follower", 0.02)
        rs.note_served("leader", 0.0)
        snap = rs.snapshot()
        assert snap["served"] == {"leader": 1, "follower": 2}
        assert snap["modes"]["stale"] == 1
        assert snap["follower_share"] == round(2 / 3, 4)
        rs.reset_stats()
        empty = rs.snapshot()
        assert empty["served"] == {"leader": 0, "follower": 0}
        assert empty["follower_share"] == 0.0


# -- HTTP over a real cluster --------------------------------------------

class _ShimAgent:
    """Just enough of api/agent.Agent for HTTPAgent to route against
    one cluster Server. The real Agent always constructs its own
    single-node Server; these tests need HTTP listeners on REAL
    cluster followers."""

    def __init__(self, server):
        self.server = server
        self.client = None
        self.config = SimpleNamespace(region="global",
                                      name=server.config.name)
        self.acl_resolver = None


def _get(addr, path, token=""):
    """GET -> (status, headers, decoded-json body); 4xx/5xx bodies
    decode too (the error payload + hint headers are the contract)."""
    r = urllib.request.Request(addr + path)
    if token:
        r.add_header("X-Nomad-Token", token)
    try:
        with urllib.request.urlopen(r, timeout=30) as resp:
            raw = resp.read().decode()
            return resp.status, dict(resp.headers), \
                json.loads(raw) if raw else None
    except urllib.error.HTTPError as e:
        raw = e.read().decode()
        try:
            body = json.loads(raw) if raw else None
        except json.JSONDecodeError:
            body = raw
        return e.code, dict(e.headers), body


@pytest.fixture()
def cluster():
    servers, registry = make_cluster(3)
    https = []
    try:
        wait_for_leader(servers)
        for s in servers:
            h = HTTPAgent(_ShimAgent(s), port=0)
            h.start()
            https.append(h)
        yield servers, registry, https
    finally:
        registry.heal()
        for h in https:
            h.shutdown()
        for s in servers:
            s.shutdown()


def _follower_idx(servers, leader):
    return next(i for i, s in enumerate(servers) if s is not leader)


class TestReadPlaneHTTP:
    def test_stale_read_on_follower_stamps_attribution(self, cluster):
        servers, _, https = cluster
        leader = wait_for_leader(servers)
        fidx = _follower_idx(servers, leader)
        before = read_stats.snapshot()
        status, headers, body = _get(https[fidx].addr, "/v1/jobs?stale=true")
        assert status == 200
        assert body == []
        # attribution: how stale, and where to go for fresh
        assert float(headers["X-Nomad-Last-Contact"]) >= 0.0
        assert headers["X-Nomad-Known-Leader"] == leader.raft.id
        after = read_stats.snapshot()
        assert after["served"]["follower"] >= \
            before["served"]["follower"] + 1
        assert after["modes"]["stale"] >= before["modes"]["stale"] + 1

    def test_default_read_on_follower_forwards_fence(self, cluster):
        servers, _, https = cluster
        leader = wait_for_leader(servers)
        fidx = _follower_idx(servers, leader)
        before = read_stats.snapshot()
        status, headers, _ = _get(https[fidx].addr, "/v1/jobs")
        assert status == 200
        # the fence crossed the wire (one read_index RPC), the data
        # came off the follower's own root
        after = read_stats.snapshot()
        assert after["forwards"] >= before["forwards"] + 1
        assert after["served"]["follower"] >= \
            before["served"]["follower"] + 1
        assert headers["X-Nomad-Known-Leader"] == leader.raft.id

    def test_linearizable_is_leader_only(self, cluster):
        servers, _, https = cluster
        leader = wait_for_leader(servers)
        lidx = servers.index(leader)
        fidx = _follower_idx(servers, leader)
        # follower: loud 503 + leader hint, never an answer
        status, headers, body = _get(
            https[fidx].addr, "/v1/jobs?consistency=linearizable")
        assert status == 503
        assert headers["X-Nomad-Known-Leader"] == leader.raft.id
        assert "leader-only" in (body or {}).get("error", "")
        # leader at steady state: the lease fast path serves
        before = read_stats.snapshot()
        status, headers, _ = _get(
            https[lidx].addr, "/v1/jobs?consistency=linearizable")
        assert status == 200
        assert float(headers["X-Nomad-Last-Contact"]) == 0.0
        after = read_stats.snapshot()
        assert after["lease_fast"] >= before["lease_fast"] + 1

    def test_stale_read_rejected_over_max_stale(self, cluster):
        servers, registry, https = cluster
        leader = wait_for_leader(servers)
        fidx = _follower_idx(servers, leader)
        follower = servers[fidx]
        # cut the follower from both peers: its leader-contact age
        # grows unbounded while the other two keep a quorum
        for s in servers:
            if s is not follower:
                registry.partition(follower.raft.id, s.raft.id)
        try:
            # < election_timeout_min (0.30s): the follower ages past
            # the bound but does not start an election
            time.sleep(0.2)
            before = read_stats.snapshot()
            status, headers, body = _get(
                https[fidx].addr, "/v1/jobs?max_stale=50ms")
            assert status == 503
            assert "stale" in (body or {}).get("error", "")
            after = read_stats.snapshot()
            assert after["stale_rejects"] >= before["stale_rejects"] + 1
            # a generous bound still serves, staleness stamped
            status, headers, _ = _get(
                https[fidx].addr, "/v1/jobs?max_stale=1h")
            assert status == 200
            assert float(headers["X-Nomad-Last-Contact"]) > 50.0
        finally:
            registry.heal()
            wait_for_leader(servers)

    def test_follower_acl_parity(self, cluster):
        """ISSUE 20 satellite: a follower hands anonymous/weak tokens
        exactly the 403s the leader does — reads routed to followers
        must not become an ACL bypass."""
        from nomad_tpu.acl.policy import ACLPolicy, ACLToken
        from nomad_tpu.acl.resolver import TokenResolver
        from nomad_tpu.server import fsm as fsm_msgs

        servers, _, https = cluster
        leader = wait_for_leader(servers)
        lidx = servers.index(leader)
        fidx = _follower_idx(servers, leader)
        policy = ACLPolicy(name="default-read",
                           rules='namespace "default" { policy = "read" }')
        leader.raft_apply(fsm_msgs.ACL_POLICY_UPSERT, {"policies": [policy]})
        tok = ACLToken.create(name="weak", type="client",
                              policies=["default-read"])
        leader.raft_apply(fsm_msgs.ACL_TOKEN_UPSERT, {"tokens": [tok]})
        wait_until(lambda: servers[fidx].state.acl_tokens(),
                   msg="token replication to follower")
        for h in https:
            h.agent.acl_resolver = TokenResolver(h.agent.server)
        try:
            for idx in (lidx, fidx):
                # anonymous: 403 in every mode, follower or leader
                for q in ("?stale=true", "", "?consistency=linearizable"):
                    status, _, _ = _get(https[idx].addr, "/v1/jobs" + q)
                    assert status == 403, (idx, q, status)
                # weak token outside its namespace: same 403
                status, _, _ = _get(
                    https[idx].addr,
                    "/v1/jobs?stale=true&namespace=secret",
                    token=tok.secret_id)
                assert status == 403, idx
            # inside its namespace the weak token reads from the
            # follower, attribution intact
            status, headers, _ = _get(https[fidx].addr,
                                      "/v1/jobs?stale=true",
                                      token=tok.secret_id)
            assert status == 200
            assert "X-Nomad-Last-Contact" in headers
        finally:
            for h in https:
                h.agent.acl_resolver = None


# -- pinned-seed mini smoke ----------------------------------------------

class TestReadPlaneSmoke:
    def test_readplane_smoke_three_server_cluster(self):
        """ISSUE 20 satellite: the ~10s pinned-seed smoke on a durable
        3-server cluster — a stale read lands on a follower with
        bounded last-contact, a default read forwards across one
        injected step-down, and a linearizable read demotes to the
        quorum barrier under a lease lapse."""
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "bench"))
        import trace_report

        r = trace_report.run_readplane_smoke()
        assert r["stale_ok"], r
        assert r["default_ok"], r
        assert r["demote_ok"], r
        assert r["ok"], r
