"""HTTP API + SDK tests.

Modeled on reference command/agent/*_test.go and api/ SDK tests
(testagent.go pattern: full agent + HTTP on an ephemeral port).
"""

import threading
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.api.agent import Agent, AgentConfig
from nomad_tpu.api.client import APIClient, APIError, QueryOptions
from nomad_tpu.api.codec import decode, encode, wire_name
from nomad_tpu.structs import consts
from nomad_tpu.structs.job import Job


@pytest.fixture(scope="module")
def agent():
    a = Agent(AgentConfig(name="test-agent", num_schedulers=1))
    a.start()
    # register some nodes straight into state (no client data plane here)
    for _ in range(4):
        a.server.node_register(mock.node())
    yield a
    a.shutdown()


@pytest.fixture(scope="module")
def api(agent):
    return APIClient(agent.http_addr)


def wait_until(fn, timeout=10.0, every=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(every)
    return False


class TestCodec:
    def test_wire_name(self):
        assert wire_name("job_id") == "JobID"
        assert wire_name("cpu_shares") == "CPUShares"
        assert wire_name("memory_mb") == "MemoryMB"
        assert wire_name("task_groups") == "TaskGroups"

    def test_roundtrip_job(self):
        job = mock.simple_job()
        wire = encode(job)
        assert wire["ID"] == job.id
        assert wire["TaskGroups"][0]["Tasks"][0]["Resources"]["CPU"] == 500
        back = decode(wire, Job)
        assert back.id == job.id
        assert back.task_groups[0].tasks[0].resources.cpu == 500
        assert back.task_groups[0].count == job.task_groups[0].count

    def test_decode_ignores_unknown_keys(self):
        job = decode({"ID": "x", "Bogus": 1}, Job)
        assert job.id == "x"


class TestJobsAPI:
    def test_register_and_run(self, agent, api):
        job = encode(mock.simple_job())
        res = api.jobs.register(job)
        assert res["EvalID"]
        # scheduler places all 10 allocs
        assert wait_until(
            lambda: len(api.jobs.allocations(job["ID"])) == 10
        ), "allocations never appeared"
        info = api.jobs.info(job["ID"])
        assert info["ID"] == job["ID"]
        listed = api.jobs.list()
        assert any(j["ID"] == job["ID"] for j in listed)
        summ = api.jobs.summary(job["ID"])
        assert sum(v for v in summ["Summary"]["web"].values()) == 10
        evals = api.jobs.evaluations(job["ID"])
        assert evals and evals[0]["JobID"] == job["ID"]

    def test_job_plan_dry_run(self, agent, api):
        job = encode(mock.simple_job())
        res = api.jobs.plan(job, diff=True)
        assert res["Diff"]["Type"] == "Added"
        # dry run must not register the job
        with pytest.raises(APIError) as e:
            api.jobs.info(job["ID"])
        assert e.value.status == 404

    def test_deregister(self, agent, api):
        job = encode(mock.simple_job())
        api.jobs.register(job)
        api.jobs.deregister(job["ID"], purge=True)
        with pytest.raises(APIError):
            api.jobs.info(job["ID"])

    def test_blocking_query_unblocks_on_register(self, agent, api):
        start_jobs = api.jobs.list()
        index = agent.server.state.latest_index()
        got = {}

        def blocked():
            got["jobs"] = api.jobs.list(QueryOptions(wait_index=index,
                                                     wait_time_s=5.0))

        t = threading.Thread(target=blocked)
        t.start()
        time.sleep(0.1)
        job = encode(mock.simple_job())
        api.jobs.register(job)
        t.join(timeout=10)
        assert not t.is_alive()
        assert len(got["jobs"]) >= len(start_jobs)

    def test_versions_and_revert(self, agent, api):
        job = mock.simple_job()
        wire = encode(job)
        api.jobs.register(wire)
        wire2 = encode(job)
        wire2["TaskGroups"][0]["Count"] = 3
        api.jobs.register(wire2)
        versions = api.jobs.versions(job.id)["Versions"]
        assert len(versions) >= 2
        api.jobs.revert(job.id, 0)
        info = api.jobs.info(job.id)
        assert info["Version"] >= 2  # revert re-registers as a new version

    def test_scale(self, agent, api):
        job = mock.simple_job()
        api.jobs.register(encode(job))
        api.jobs.scale(job.id, "web", 5, message="scale test")
        status = api.jobs.scale_status(job.id)
        assert status["TaskGroups"]["web"]["Desired"] == 5
        assert status["TaskGroups"]["web"]["Events"]

    def test_dispatch_parameterized(self, agent, api):
        from nomad_tpu.structs.job import ParameterizedJobConfig

        job = mock.simple_job()
        job.parameterized = ParameterizedJobConfig(meta_required=["input"])
        api.jobs.register(encode(job))
        res = api.jobs.dispatch(job.id, meta={"input": "x"})
        assert res["DispatchedJobID"].startswith(f"{job.id}/dispatch-")
        # dispatched IDs contain '/': the SDK must escape them in paths
        child = res["DispatchedJobID"]
        assert api.jobs.info(child)["ID"] == child
        api.jobs.deregister(child, purge=True)
        with pytest.raises(APIError):
            api.jobs.info(child)
        with pytest.raises(APIError):
            api.jobs.dispatch(job.id, meta={})  # missing required meta


class TestNodesAPI:
    def test_list_and_info(self, agent, api):
        nodes = api.nodes.list()
        assert len(nodes) >= 4
        info = api.nodes.info(nodes[0]["ID"])
        assert info["ID"] == nodes[0]["ID"]

    def test_drain_and_eligibility(self, agent, api):
        node = api.nodes.list()[0]
        api.nodes.drain(node["ID"], enable=True, deadline_s=1.0)
        info = api.nodes.info(node["ID"])
        assert info["DrainStrategy"] or info["SchedulingEligibility"] == "ineligible"
        api.nodes.drain(node["ID"], enable=False)
        api.nodes.eligibility(node["ID"], eligible=True)
        info = api.nodes.info(node["ID"])
        assert info["SchedulingEligibility"] == "eligible"


class TestOperatorAPI:
    def test_scheduler_config_roundtrip(self, agent, api):
        cfg = api.operator.scheduler_config()["SchedulerConfig"]
        assert cfg["SchedulerAlgorithm"] == "binpack"
        cfg["SchedulerAlgorithm"] = "spread"
        api.operator.set_scheduler_config(cfg)
        cfg2 = api.operator.scheduler_config()["SchedulerConfig"]
        assert cfg2["SchedulerAlgorithm"] == "spread"
        cfg2["SchedulerAlgorithm"] = "binpack"
        api.operator.set_scheduler_config(cfg2)

    def test_snapshot_save_restore(self, agent, api):
        job = mock.simple_job()
        api.jobs.register(encode(job))
        snap = api.operator.snapshot_save()
        assert len(snap) > 100
        api.jobs.deregister(job.id, purge=True)
        with pytest.raises(APIError):
            api.jobs.info(job.id)
        api.operator.snapshot_restore(snap)
        assert api.jobs.info(job.id)["ID"] == job.id


class TestSearchAPI:
    def test_prefix_search(self, agent, api):
        job = mock.simple_job()
        api.jobs.register(encode(job))
        res = api.search.prefix(job.id[:5], "jobs")
        assert job.id in res["Matches"]["jobs"]

    def test_fuzzy_search(self, agent, api):
        nodes = api.nodes.list()
        name = nodes[0]["Name"]
        res = api.search.fuzzy(name[:4], "nodes")
        assert any(name in m["ID"] for m in res["Matches"]["nodes"])


class TestNamespacesAPI:
    def test_crud(self, agent, api):
        api.namespaces.register("apps", "application namespace")
        names = {n["Name"] for n in api.namespaces.list()}
        assert {"default", "apps"} <= names
        info = api.namespaces.info("apps")
        assert info["Description"] == "application namespace"
        api.namespaces.delete("apps")
        names = {n["Name"] for n in api.namespaces.list()}
        assert "apps" not in names


class TestAgentAPI:
    def test_self_and_health(self, agent, api):
        self_info = api.agent.self()
        assert self_info["Config"]["Name"] == "test-agent"
        assert self_info["Config"]["Server"] is True
        health = api.agent.health()
        assert health["server"]["ok"]

    def test_members(self, agent, api):
        members = api.agent.members()
        assert members["Members"][0]["Name"] == "test-agent"

    def test_metrics(self, agent, api):
        from nomad_tpu.utils.metrics import global_registry

        global_registry.incr_counter("nomad.test.counter", 2)
        res = api.agent.metrics()
        assert any(c["Name"] == "nomad.test.counter" for c in res["Counters"])


class TestEventStream:
    def test_stream_delivers_job_events(self, agent, api):
        got = []

        def consume():
            try:
                for batch in api.events.stream(topics={"Job": ["*"]},
                                               timeout=10.0):
                    got.extend(batch.get("Events", []))
                    if got:
                        return
            except Exception:
                pass

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        time.sleep(0.2)
        api.jobs.register(encode(mock.simple_job()))
        t.join(timeout=10)
        assert got, "no events received"
        assert got[0]["Topic"] == "Job"


class TestAllocAPI:
    def test_alloc_lifecycle(self, agent, api):
        # earlier module-scoped tests leave jobs (some blocked on capacity)
        # behind; purge them and add fresh nodes so this job always places
        for j in api.jobs.list():
            api.jobs.deregister(j["ID"], purge=True)
        for _ in range(2):
            agent.server.node_register(mock.node())
        job = encode(mock.simple_job())
        api.jobs.register(job)
        assert wait_until(lambda: api.jobs.allocations(job["ID"]))
        allocs = api.jobs.allocations(job["ID"])
        info = api.allocations.info(allocs[0]["ID"])
        assert info["JobID"] == job["ID"]
        res = api.allocations.stop(allocs[0]["ID"])
        assert res["EvalID"]
        listed = api.allocations.list()
        assert any(a["ID"] == allocs[0]["ID"] for a in listed)
