"""Pipelined replication, leader leases, and the batched-apply wakeup
audit (ISSUE 18).

The property at the center: with ``max_in_flight > 1`` the per-peer
replicator keeps a window of AppendEntries batches in flight, but the
COMMITTED LOG must be indistinguishable from the synchronous path —
every acked apply lands exactly once, every replica converges to the
identical sequence, and usage planes rebuilt from that sequence are
bit-identical (``usage_rebuild_diff`` stays empty). Randomized fault
schedules (drops, latency, partitions-then-heal, mid-stream term
changes, mid-window leader kills) exercise the drain/fallback seams.

Leader leases: a quorum of append acks within
``election_timeout_min * lease_fraction`` of their SEND time lets the
leader serve linearizable reads without a barrier round-trip. The
safety half: the lease window is strictly shorter than the minimum
election timeout, so by the time any new leader CAN exist, a deposed
leader's lease has already lapsed — it must fall back to the barrier
path (which fails), never serve a stale fast read.
"""

import random
import threading
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.raft.node import NotLeaderError, RaftConfig, RaftNode
from nomad_tpu.raft.observe import raft_observer
from nomad_tpu.raft.transport import InmemTransport, TransportRegistry
from nomad_tpu.server import fsm as fsm_mod
from nomad_tpu.server.fsm import NomadFSM
from nomad_tpu.server.testing import make_cluster, wait_for_leader, wait_until
from nomad_tpu.state.store import StateStore, watch_stats
from nomad_tpu.state.usage import usage_rebuild_diff
from nomad_tpu.utils import faultpoints


def make_pipe_cluster(n, max_in_flight=8):
    """N bare RaftNodes with the pipelined-replication window sized by
    ``max_in_flight`` (1 = the synchronous path, bit-for-bit)."""
    cfg = RaftConfig(
        heartbeat_interval=0.02,
        election_timeout_min=0.06,
        election_timeout_max=0.12,
        max_in_flight=max_in_flight,
    )
    registry = TransportRegistry()
    addrs = [f"n{i}" for i in range(n)]
    nodes, logs = [], []
    for addr in addrs:
        applied = []
        logs.append(applied)
        nodes.append(RaftNode(
            node_id=addr,
            peers=addrs,
            transport=InmemTransport(addr, registry),
            fsm_apply=(lambda a: lambda t, r: a.append((t, r)) or len(a))(applied),
            config=cfg,
        ))
    for node in nodes:
        node.start()
    return nodes, logs, registry


def leader_of(nodes, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        leaders = [n for n in nodes if n.is_leader()]
        if len(leaders) == 1:
            return leaders[0]
        time.sleep(0.01)
    raise TimeoutError("no single leader")


def shutdown_all(nodes):
    for n in nodes:
        n.shutdown()


#: one fault family per seed residue; every family crosses the
#: pipelined window's drain/fallback seams a different way
SCENARIOS = ("drops", "latency", "conflict", "term_change", "leader_kill")


def _run_seed(seed, max_in_flight, n_ops=12):
    """One randomized run: returns the converged log's op ids (in
    order) after asserting the exactly-once / identical-replica
    property. Op ids are deterministic per (seed, op, attempt), so a
    disturbance-free run's log is a pure function of the seed — the
    cross-arm bit-identity hook."""
    rng = random.Random(seed)
    scenario = SCENARIOS[seed % len(SCENARIOS)]
    nodes, logs, registry = make_pipe_cluster(3, max_in_flight)
    acked, killed = [], []
    try:
        try:
            leader = leader_of(nodes)
            disturb_at = rng.randrange(2, n_ops - 2)
            if scenario == "drops":
                faultpoints.arm(
                    {"raft.replicate.send": {"kind": "error", "p": 0.2}},
                    seed=seed)
            elif scenario == "latency":
                faultpoints.arm(
                    {"raft.replicate.send": {
                        "kind": "latency", "p": 0.5,
                        "sleep_s": 0.001 + rng.random() * 0.004}},
                    seed=seed)
            i, attempt = 0, 0
            while i < n_ops:
                if i == disturb_at and attempt == 0:
                    if scenario == "conflict":
                        f = next(n for n in nodes
                                 if n not in killed and not n.is_leader())
                        registry.partition(leader.id, f.id)
                    elif scenario == "term_change":
                        leader.step_down()
                    elif scenario == "leader_kill":
                        # mid-window: earlier applies may still be in
                        # flight in the pipelined window when it dies
                        leader.shutdown()
                        killed.append(leader)
                op_id = f"s{seed}-op{i}-a{attempt}"
                try:
                    live = [n for n in nodes if n not in killed]
                    leader = leader_of(live, timeout=5.0)
                    leader.apply("set", {"id": op_id}, timeout=5.0)
                except Exception:
                    attempt += 1
                    assert attempt <= 8, (seed, scenario, op_id)
                    continue
                acked.append(op_id)
                attempt = 0
                i += 1
        finally:
            faultpoints.reset()
            registry.heal()
        live_idx = [k for k, nd in enumerate(nodes) if nd not in killed]

        def converged():
            ls = [logs[k] for k in live_idx]
            if not all(ls[0] == other for other in ls[1:]):
                return False
            ids = [r["id"] for _, r in ls[0]]
            return all(a in ids for a in acked)

        deadline = time.time() + 10.0
        while time.time() < deadline and not converged():
            time.sleep(0.01)
        assert converged(), (seed, scenario, acked,
                             [len(logs[k]) for k in live_idx])
        ids = [r["id"] for _, r in logs[live_idx[0]]]
        # exactly-once: acked ops appear once; an unacked attempt that
        # committed after its client timed out appears at most once
        assert len(ids) == len(set(ids)), (seed, scenario)
        for a in acked:
            assert ids.count(a) == 1, (seed, scenario, a)
        return ids, acked, scenario
    finally:
        shutdown_all(n for n in nodes if n not in killed)


class TestPipelinedLogEquivalence:
    def _sweep(self, seeds):
        for seed in seeds:
            ids, acked, scenario = _run_seed(seed, max_in_flight=8)
            if scenario == "latency":
                # disturbance-free arm: the log IS the acked sequence,
                # so the synchronous arm must produce the identical
                # bytes — pipelining changed nothing observable
                assert ids == acked, (seed, ids, acked)
                sync_ids, sync_acked, _ = _run_seed(seed, max_in_flight=1)
                assert sync_ids == ids, (seed, sync_ids, ids)

    def test_property_pipelined_log_equivalent_25_seeds(self):
        self._sweep(range(25))

    @pytest.mark.slow
    def test_property_pipelined_log_equivalent_200_seeds(self):
        self._sweep(range(25, 225))

    def test_max_in_flight_1_never_arms_pipeline(self):
        """The dispatcher must route ``max_in_flight=1`` through the
        original synchronous replicator — zero pipeline batches, zero
        armed peers — so today's path stays bit-identical."""
        nodes, logs, _ = make_pipe_cluster(3, max_in_flight=1)
        try:
            leader = leader_of(nodes)
            for i in range(8):
                leader.apply("set", {"id": i})
            wait_until(lambda: all(len(l) == 8 for l in logs),
                       msg="all replicas applied")
            assert logs[0] == logs[1] == logs[2]
            g = leader.observe_gauges()
            assert g["pipeline_batches"] == 0, g
            assert g["pipeline_armed"] == 0, g
            assert g["pipeline_drains"] == 0, g
        finally:
            shutdown_all(nodes)

    def test_pipelined_path_actually_pipelines(self):
        """Sanity for the property above: at ``max_in_flight=8`` the
        window really is taken (batches counted, no drains on a clean
        wire) — otherwise the equivalence sweep proves nothing."""
        nodes, logs, _ = make_pipe_cluster(3, max_in_flight=8)
        try:
            leader = leader_of(nodes)
            for i in range(20):
                leader.apply("set", {"id": i})
            wait_until(lambda: all(len(l) == 20 for l in logs),
                       msg="all replicas applied")
            g = leader.observe_gauges()
            assert g["pipeline_batches"] > 0, g
        finally:
            shutdown_all(nodes)


class TestServerPipelinedUsageParity:
    def test_usage_rebuild_diff_empty_under_pipelined_replication(self):
        """Server-backed variant of the equivalence property: schedule
        real allocs through a pipelined cluster and require the
        incremental usage planes on EVERY replica to match a from-
        scratch rebuild bit-for-bit."""
        servers, _ = make_cluster(3)
        try:
            leader = wait_for_leader(servers)
            # clusters run the pipelined window by default now
            assert leader.raft.config.max_in_flight > 1
            for _ in range(3):
                leader.node_register(mock.node())
            job = mock.job()
            leader.job_register(job)
            wait_until(
                lambda: all(
                    len(s.state.snapshot().allocs_by_job(
                        job.namespace, job.id)) == 10
                    for s in servers),
                timeout=30,
                msg="allocs replicated to all servers",
            )
            for s in servers:
                assert usage_rebuild_diff(s.state) == [], s.config.name
        finally:
            for s in servers:
                s.shutdown()


class TestLeaderLease:
    def test_lease_held_on_steady_leader_not_on_follower(self):
        nodes, _, _ = make_pipe_cluster(3)
        try:
            leader = leader_of(nodes)
            leader.apply("set", {"id": "x"})
            wait_until(lambda: leader.lease_valid(),
                       msg="lease established from append acks")
            for f in (n for n in nodes if n is not leader):
                assert not f.lease_valid()
        finally:
            shutdown_all(nodes)

    def test_deposed_leader_lease_lapses_before_new_leader_commits(self):
        """The safety argument, executed: lease window
        (election_timeout_min * lease_fraction) < election_timeout_min,
        so when the partitioned-away majority elects a successor, the
        old leader — still believing it leads — must already be
        reporting its lease invalid. A fast read there would be stale;
        the lease forbids it."""
        nodes, _, registry = make_pipe_cluster(3)
        try:
            old = leader_of(nodes)
            old.apply("set", {"id": "pre"})
            wait_until(lambda: old.lease_valid(), msg="lease held")
            followers = [n for n in nodes if n is not old]
            for f in followers:
                registry.partition(old.id, f.id)
            new = leader_of(followers, timeout=5.0)
            # the instant a successor exists, the old lease is gone
            assert not old.lease_valid()
            assert old.is_leader()      # ...though it doesn't know yet
            new.apply("set", {"id": "post"})
            assert not old.lease_valid()
        finally:
            registry.heal()
            shutdown_all(nodes)

    def test_lease_read_counters_and_expiry_event(self):
        nodes, _, _ = make_pipe_cluster(3)
        try:
            leader = leader_of(nodes)
            leader.apply("set", {"id": "x"})
            wait_until(lambda: leader.lease_valid(), msg="lease held")
            t0 = time.monotonic()
            leader.note_lease_read(True)
            g = leader.observe_gauges()
            assert g["lease_reads_fast"] == 1, g
            # fast -> barrier edge emits ONE lease_expired event
            leader.note_lease_read(False)
            leader.note_lease_read(False)
            g = leader.observe_gauges()
            assert g["lease_reads_barrier"] == 2, g
            evs = [e for e in raft_observer.events(since_mono=t0)
                   if e["kind"] == "lease_expired"
                   and e["server"] == leader.id]
            assert len(evs) == 1, evs
        finally:
            shutdown_all(nodes)

    def test_server_linearizable_read_paths(self):
        servers, _ = make_cluster(3)
        try:
            leader = wait_for_leader(servers)
            wait_until(lambda: leader.raft.lease_valid(),
                       msg="leader lease held")
            leader.linearizable_read()      # fast path, no barrier
            assert leader.raft.observe_gauges()["lease_reads_fast"] >= 1
            follower = next(s for s in servers if s is not leader)
            with pytest.raises(NotLeaderError):
                follower.linearizable_read()
        finally:
            for s in servers:
                s.shutdown()


class TestBatchedApplyWakeupAudit:
    def test_one_wakeup_one_publish_stamp_per_batch(self):
        """A committed run applied as one batch must cost ONE watcher
        wakeup (carrying the batch's newest index) and ONE event-stream
        publish stamp — the PR 16 spurious-wakeup counter stays flat."""
        store = StateStore()
        pubs = []

        class _RecordingBroker:
            def publish(self, events, stamp=None):
                pubs.append((list(events), stamp))

        f = NomadFSM(store, event_broker=_RecordingBroker())
        jobs = [mock.job() for _ in range(5)]
        base = store.table_index(["jobs"])
        base_held = watch_stats.snapshot()["held_watchers"]
        got = []
        th = threading.Thread(
            target=lambda: got.append(
                store.block_until(["jobs"], base, timeout=10.0)))
        th.start()
        wait_until(
            lambda: watch_stats.snapshot()["held_watchers"] > base_held,
            msg="watcher parked")
        watch_stats.reset_stats()
        results = f.apply_batch(
            [(fsm_mod.JOB_REGISTER, {"job": j}) for j in jobs])
        th.join(5.0)
        assert not th.is_alive()
        assert all(err is None for _, err in results), results
        idxs = [i for i, _ in results]
        newest = max(idxs)
        assert got == [newest], (got, newest)
        snap = watch_stats.snapshot()
        assert snap["wakeups"] == 1, snap
        assert snap["spurious_wakeups"] == 0, snap
        # one stamp for the whole batch; per-entry commit indexes ride
        # the events so consumers still see each entry's index
        assert len(pubs) == 1, [len(p[0]) for p in pubs]
        events, stamp = pubs[0]
        assert isinstance(stamp, float)
        assert sorted({e.index for e in events}) == sorted(set(idxs))
        assert max(e.index for e in events) == newest

    def test_per_entry_apply_still_publishes_per_entry(self):
        """Containment check for the audit above: the single-entry
        path keeps its one-stamp-per-apply behavior (the batch path is
        an optimization, not a semantics change)."""
        store = StateStore()
        pubs = []

        class _RecordingBroker:
            def publish(self, events, stamp=None):
                pubs.append(stamp)

        f = NomadFSM(store, event_broker=_RecordingBroker())
        for _ in range(3):
            f.apply(fsm_mod.JOB_REGISTER, {"job": mock.job()})
        assert len(pubs) == 3
