"""Eval-batching worker tests (the TPU-idiomatic throughput path).

SURVEY.md §7 step 5: workers dequeue BATCHES of compatible evals and
amortize kernel dispatch. Covers dequeue_batch semantics and a live
server running with batch_size > 1.
"""

import time

import pytest

from nomad_tpu import mock
from nomad_tpu.server.eval_broker import EvalBroker
from nomad_tpu.server.server import Server, ServerConfig
from nomad_tpu.structs import consts
from nomad_tpu.structs.eval_plan import Evaluation


def _eval(job_id: str, sched: str = "service") -> Evaluation:
    return Evaluation(
        namespace="default", job_id=job_id, type=sched,
        priority=50, status=consts.EVAL_STATUS_PENDING,
        triggered_by=consts.EVAL_TRIGGER_JOB_REGISTER,
    )


class TestDequeueBatch:
    def test_drains_up_to_batch(self):
        b = EvalBroker()
        b.set_enabled(True)
        for i in range(5):
            b.enqueue(_eval(f"job-{i}"))
        batch = b.dequeue_batch(["service"], batch=3, timeout=0)
        assert len(batch) == 3
        # every dequeued eval has its own ack token
        for ev, token in batch:
            b.ack(ev.id, token)
        rest = b.dequeue_batch(["service"], batch=10, timeout=0)
        assert len(rest) == 2

    def test_single_available_returns_one(self):
        b = EvalBroker()
        b.set_enabled(True)
        b.enqueue(_eval("only"))
        batch = b.dequeue_batch(["service"], batch=8, timeout=0)
        assert len(batch) == 1

    def test_empty_returns_empty(self):
        b = EvalBroker()
        b.set_enabled(True)
        assert b.dequeue_batch(["service"], batch=4, timeout=0) == []

    def test_nack_of_batch_member_requeues(self):
        # zero nack delay: the default 1s delayed-requeue would race
        # the dequeue deadline
        b = EvalBroker(nack_timeout=60, initial_nack_delay=0)
        b.set_enabled(True)
        for i in range(2):
            b.enqueue(_eval(f"j{i}"))
        batch = b.dequeue_batch(["service"], batch=2, timeout=0)
        ev0, tok0 = batch[0]
        ev1, tok1 = batch[1]
        b.ack(ev0.id, tok0)
        b.nack(ev1.id, tok1)
        redo = b.dequeue_batch(["service"], batch=2, timeout=5.0)
        assert [e.id for e, _ in redo] == [ev1.id]


class TestLiveBatchedWorkers:
    def test_burst_of_jobs_all_schedule(self):
        """A server whose single worker processes 8-eval batches must
        place a burst of concurrently registered jobs correctly — and
        do it through COALESCED device launches (one joint kernel call
        per wave of concurrently scheduled evals), not one launch per
        eval."""
        server = Server(ServerConfig(num_workers=1, worker_batch_size=8))
        server.start()
        try:
            for _ in range(4):
                server.node_register(mock.node())
            jobs = []
            for i in range(12):
                job = mock.job()
                job.task_groups[0].count = 2
                jobs.append(job)
                server.job_register(job)
            # generous: a cold CPU compile of the joint wave variant
            # under full-suite load can take tens of seconds (warm runs
            # finish in ~3s via the persistent compile cache)
            deadline = time.time() + 150
            def placed():
                snap = server.state.snapshot()
                return all(
                    len(snap.allocs_by_job(j.namespace, j.id)) == 2
                    for j in jobs)
            while time.time() < deadline and not placed():
                time.sleep(0.2)
            assert placed(), {
                j.id: len(server.state.snapshot().allocs_by_job(
                    j.namespace, j.id)) for j in jobs}
            # every alloc landed on a real node row
            snap = server.state.snapshot()
            for j in jobs:
                for a in snap.allocs_by_job(j.namespace, j.id):
                    assert snap.node_by_id(a.node_id) is not None
            # the batching claim itself: kernel requests served by far
            # fewer joint launches, with a real multi-eval wave. (An
            # eval that lands in a 1-eval batch dispatches directly and
            # isn't coalescer-counted, so allow a little slack.)
            w = server.workers[0]
            assert w.batch_requests >= 10
            assert w.batch_launches < w.batch_requests
            assert w.max_wave >= 4
            # the batch fan-out rode the PERSISTENT eval pool (one
            # executor for the worker's lifetime, not a thread spawn
            # per eval per batch) and survives across batches
            assert w._pool is not None
            pool = w._pool
            job = mock.job()
            job.task_groups[0].count = 2
            server.job_register(job)
            deadline = time.time() + 60
            while time.time() < deadline and len(
                    server.state.snapshot().allocs_by_job(
                        job.namespace, job.id)) < 2:
                time.sleep(0.2)
            assert w._pool is pool
        finally:
            server.shutdown()
        # stop() retires the pool
        assert w._pool is None


class TestLaunchCoalescer:
    def test_joint_wave_members_see_each_others_placements(self):
        """The joint kernel runs wave members over a SHARED capacity
        carry (the plan applier's serialization, on device): a later
        member must not over-subscribe a node an earlier member filled."""
        import numpy as np

        from nomad_tpu.ops.kernel import (
            build_kernel_in, infer_features, pad_steps,
        )
        from nomad_tpu.parallel.coalesce import launch_wave
        from nomad_tpu.scheduler.context import EvalContext
        from nomad_tpu.scheduler.stack import XLAGenericStack
        from nomad_tpu.scheduler.testing import Harness
        from nomad_tpu.structs.eval_plan import Plan
        from nomad_tpu.tensors.schema import ClusterTensors

        h = Harness()
        # one node, capacity for exactly 2 allocs of the big ask
        node = mock.node()
        h.state.upsert_node(node)
        job = mock.simple_job()
        job.task_groups[0].tasks[0].resources.cpu = 1500
        h.state.upsert_job(job)
        snap = h.state.snapshot()
        c = ClusterTensors.build(snap.nodes())
        ctx = EvalContext(snap, Plan())
        st = XLAGenericStack(False, ctx, c)
        st.set_job(job)
        tg = job.task_groups[0]
        ev = st._build_eval_tensors(tg, np.zeros(c.n_pad, bool))
        kin = build_kernel_in(c, ev, 2)
        feats = infer_features(ev)
        kp = pad_steps(2)

        # three members, each asking 2 x 1500 MHz against one 3900 MHz
        # node: joint accounting admits only the first 2 placements
        outs = launch_wave([kin, kin, kin], [kp, kp, kp], [feats] * 3)
        found = [bool(o.found[i]) for o in outs for i in range(2)]
        assert sum(found) == 2, found
        # and they are the FIRST members' placements (applier order)
        assert outs[0].found[:2].all()
        assert not outs[1].found[:2].any()
        assert not outs[2].found[:2].any()

    def test_wave_output_matches_single_launch_for_lone_member(self):
        """A 1-member wave must equal the direct per-eval kernel."""
        import numpy as np

        from nomad_tpu.ops.kernel import (
            build_kernel_in, infer_features, pad_steps, place_taskgroup_jit,
        )
        from nomad_tpu.parallel.coalesce import launch_wave
        from nomad_tpu.scheduler.context import EvalContext
        from nomad_tpu.scheduler.stack import XLAGenericStack
        from nomad_tpu.scheduler.testing import Harness
        from nomad_tpu.structs.eval_plan import Plan
        from nomad_tpu.tensors.schema import ClusterTensors

        h = Harness()
        for _ in range(5):
            h.state.upsert_node(mock.node())
        job = mock.job()
        h.state.upsert_job(job)
        snap = h.state.snapshot()
        c = ClusterTensors.build(snap.nodes())
        ctx = EvalContext(snap, Plan())
        st = XLAGenericStack(False, ctx, c)
        st.set_job(job)
        tg = job.task_groups[0]
        ev = st._build_eval_tensors(tg, np.zeros(c.n_pad, bool))
        kin = build_kernel_in(c, ev, 3)
        feats = infer_features(ev)
        kp = pad_steps(3)
        direct = place_taskgroup_jit(kin, kp, feats)
        import numpy as np  # noqa: F811

        wave = launch_wave([kin], [kp], [feats])[0]
        assert (np.asarray(direct.chosen) == wave.chosen).all()
        assert (np.asarray(direct.found) == wave.found).all()
        assert np.allclose(np.asarray(direct.scores), wave.scores, atol=1e-6)
        assert int(direct.nodes_evaluated) == int(wave.nodes_evaluated)
