"""Eval-batching worker tests (the TPU-idiomatic throughput path).

SURVEY.md §7 step 5: workers dequeue BATCHES of compatible evals and
amortize kernel dispatch. Covers dequeue_batch semantics and a live
server running with batch_size > 1.
"""

import time

import pytest

from nomad_tpu import mock
from nomad_tpu.server.eval_broker import EvalBroker
from nomad_tpu.server.server import Server, ServerConfig
from nomad_tpu.structs import consts
from nomad_tpu.structs.eval_plan import Evaluation


def _eval(job_id: str, sched: str = "service") -> Evaluation:
    return Evaluation(
        namespace="default", job_id=job_id, type=sched,
        priority=50, status=consts.EVAL_STATUS_PENDING,
        triggered_by=consts.EVAL_TRIGGER_JOB_REGISTER,
    )


class TestDequeueBatch:
    def test_drains_up_to_batch(self):
        b = EvalBroker()
        b.set_enabled(True)
        for i in range(5):
            b.enqueue(_eval(f"job-{i}"))
        batch = b.dequeue_batch(["service"], batch=3, timeout=0)
        assert len(batch) == 3
        # every dequeued eval has its own ack token
        for ev, token in batch:
            b.ack(ev.id, token)
        rest = b.dequeue_batch(["service"], batch=10, timeout=0)
        assert len(rest) == 2

    def test_single_available_returns_one(self):
        b = EvalBroker()
        b.set_enabled(True)
        b.enqueue(_eval("only"))
        batch = b.dequeue_batch(["service"], batch=8, timeout=0)
        assert len(batch) == 1

    def test_empty_returns_empty(self):
        b = EvalBroker()
        b.set_enabled(True)
        assert b.dequeue_batch(["service"], batch=4, timeout=0) == []

    def test_nack_of_batch_member_requeues(self):
        # zero nack delay: the default 1s delayed-requeue would race
        # the dequeue deadline
        b = EvalBroker(nack_timeout=60, initial_nack_delay=0)
        b.set_enabled(True)
        for i in range(2):
            b.enqueue(_eval(f"j{i}"))
        batch = b.dequeue_batch(["service"], batch=2, timeout=0)
        ev0, tok0 = batch[0]
        ev1, tok1 = batch[1]
        b.ack(ev0.id, tok0)
        b.nack(ev1.id, tok1)
        redo = b.dequeue_batch(["service"], batch=2, timeout=5.0)
        assert [e.id for e, _ in redo] == [ev1.id]


class TestLiveBatchedWorkers:
    def test_burst_of_jobs_all_schedule(self):
        """A server whose single worker processes 8-eval batches must
        place a burst of concurrently registered jobs correctly."""
        server = Server(ServerConfig(num_workers=1, worker_batch_size=8))
        server.start()
        try:
            for _ in range(4):
                server.node_register(mock.node())
            jobs = []
            for i in range(12):
                job = mock.job()
                job.task_groups[0].count = 2
                jobs.append(job)
                server.job_register(job)
            deadline = time.time() + 60
            def placed():
                snap = server.state.snapshot()
                return all(
                    len(snap.allocs_by_job(j.namespace, j.id)) == 2
                    for j in jobs)
            while time.time() < deadline and not placed():
                time.sleep(0.2)
            assert placed(), {
                j.id: len(server.state.snapshot().allocs_by_job(
                    j.namespace, j.id)) for j in jobs}
            # every alloc landed on a real node row
            snap = server.state.snapshot()
            for j in jobs:
                for a in snap.allocs_by_job(j.namespace, j.id):
                    assert snap.node_by_id(a.node_id) is not None
        finally:
            server.shutdown()
