"""Live waves over the device mesh (VERDICT r2 missing #2).

The coalescer's joint wave kernel runs with its node axis sharded over
the mesh (parallel/sharded.make_joint_sharded): the SAME program, so
placements must be identical to single-device dispatch — per-step
argmax/top-k lower to per-shard reductions + cross-shard collectives
(SURVEY.md §2.10 node-axis-over-ICI mapping). Tests run on the
8-virtual-CPU mesh (conftest forces the device count).
"""

import numpy as np
import pytest

import jax

from nomad_tpu import mock
from nomad_tpu.parallel import coalesce


@pytest.fixture
def wave_mesh():
    from nomad_tpu.parallel.sharded import wave_mesh as make

    assert len(jax.devices()) >= 8, "conftest must force 8 CPU devices"
    return make(8)


class TestShardedWaveParity:
    def test_launch_wave_identical_to_single_device(self, wave_mesh):
        from nomad_tpu.ops.kernel import (
            LEAN_FEATURES,
            build_kernel_in,
            infer_features,
        )
        from nomad_tpu.parallel.synthetic import (
            synthetic_cluster,
            synthetic_eval,
        )

        cluster = synthetic_cluster(200, cpu=2000.0, mem=4096.0,
                                    disk=50000.0, seed=5)
        rng = np.random.default_rng(3)
        kins, steps, feats = [], [], []
        for i in range(5):
            ev = synthetic_eval(cluster, desired_count=4)
            kin = build_kernel_in(cluster, ev, 4)
            kin = kin._replace(
                ask_cpu=np.asarray(float(rng.choice([100, 300, 500])),
                                   np.float32))
            kins.append(kin)
            steps.append(4)
            feats.append(LEAN_FEATURES._replace(with_topk=True))

        coalesce.configure_wave_mesh(None)
        single = coalesce.launch_wave(kins, steps, feats)

        before = coalesce.sharded_wave_launches
        coalesce.configure_wave_mesh(wave_mesh)
        try:
            sharded = coalesce.launch_wave(kins, steps, feats)
        finally:
            coalesce.configure_wave_mesh(None)
        assert coalesce.sharded_wave_launches == before + 1

        for s, m in zip(single, sharded):
            np.testing.assert_array_equal(np.asarray(s.chosen),
                                          np.asarray(m.chosen))
            np.testing.assert_array_equal(np.asarray(s.found),
                                          np.asarray(m.found))
            np.testing.assert_allclose(np.asarray(s.scores),
                                       np.asarray(m.scores),
                                       rtol=1e-6, atol=1e-7)
        assert any(np.asarray(s.found).any() for s in single)


def _shared_layout_wave(n_nodes=200, members=4, k=3, seed=5):
    """B kins whose three sharing groups are ALL identity-shared (the
    live stack.py build's steady shape): wave-shared planes from one
    (cluster, usage) pair, neutral/job groups from frozen singletons."""
    from nomad_tpu.ops.kernel import (
        LEAN_FEATURES,
        build_kernel_in,
        neutral_planes,
    )
    from nomad_tpu.parallel.synthetic import synthetic_cluster, synthetic_eval

    cluster = synthetic_cluster(n_nodes, seed=seed)
    cluster.avail_mbits = np.zeros(cluster.n_pad, np.int32)
    cluster.avail_mbits[:n_nodes] = 1000

    class _U:
        pass

    u = _U()
    u.uid = "wave-test"
    u.version = 1
    u.structure_version = 0
    u.rows = {nid: i for i, nid in enumerate(cluster.node_ids)}
    u.n = cluster.n_real
    for f, dt in (("used_cpu", np.float32), ("used_mem", np.float32),
                  ("used_disk", np.float32), ("used_cores", np.int32),
                  ("used_mbits", np.int32)):
        setattr(u, f, np.zeros(cluster.n_real, dt))
    u.row_events = ()
    u.row_events_floor = 0
    u.node_events = ()

    shared = cluster.wave_shared_planes(u)
    neutral = neutral_planes(cluster.n_pad)
    base_mask = cluster.ready.copy()
    base_mask.setflags(write=False)
    ev = synthetic_eval(cluster, desired_count=k)
    kins, steps, feats = [], [], []
    for i in range(members):
        kin = build_kernel_in(cluster, ev, k)
        kin = kin._replace(
            ask_cpu=np.asarray(100.0 + 50 * i, np.float32),
            **{f: shared[f] for f in shared},
            port_conflict=neutral.zeros_bool,
            dev_free=neutral.zeros_dev,
            dev_aff_score=neutral.zeros_f32,
            job_tg_count=neutral.zeros_i32,
            job_any_count=neutral.zeros_i32,
            penalty=neutral.zeros_bool,
            aff_score=neutral.zeros_f32,
            base_mask=base_mask,
        )
        kins.append(kin)
        steps.append(k)
        feats.append(LEAN_FEATURES._replace(with_topk=True))
    return cluster, u, kins, steps, feats


class TestShardedSharedLayout:
    def test_shared_layout_parity_and_resident_h2d(self, wave_mesh):
        """The ISSUE 14 steady shape: identity-shared planes resident
        SHARDED via the device state — bit-identical to single-device
        dispatch, zero fallbacks, and the second sharded wave's h2d is
        just node_perm + scalars (the resident planes move nothing)."""
        from nomad_tpu import telemetry
        from nomad_tpu.telemetry.kernel_profile import profiler
        from nomad_tpu.tensors.device_state import default_device_state

        cluster, u, kins, steps, feats = _shared_layout_wave()
        prior = default_device_state.mesh
        telemetry.enable()
        telemetry.reset()
        try:
            default_device_state.configure_mesh(wave_mesh)
            default_device_state.ensure(cluster, u)
            sharded = coalesce.launch_wave(kins, steps, feats,
                                           mesh=wave_mesh)
            h2d_1 = profiler.summary()["TransferBytes"]["h2d"]
            coalesce.launch_wave(kins, steps, feats, mesh=wave_mesh)
            h2d_2 = profiler.summary()["TransferBytes"]["h2d"] - h2d_1
            single = coalesce.launch_wave(kins, steps, feats,
                                          mesh=None)
            for s, m in zip(single, sharded):
                np.testing.assert_array_equal(np.asarray(s.chosen),
                                              np.asarray(m.chosen))
                np.testing.assert_array_equal(np.asarray(s.found),
                                              np.asarray(m.found))
                np.testing.assert_allclose(np.asarray(s.scores),
                                           np.asarray(m.scores),
                                           rtol=1e-6, atol=1e-7)
            assert any(np.asarray(s.found).any() for s in single)
            stats = coalesce.sharded_wave_stats.snapshot()
            assert stats["launches"] == 2
            assert stats["fallbacks"] == 0
            assert stats["mesh_devices"] == 8
            # resident sharded planes upload NOTHING on the repeat
            # wave: node_perm ([B, N] i32) + step planes + scalars
            # only — far under one [N] f32 node plane per member
            assert h2d_2 < 40_000, h2d_2
        finally:
            default_device_state.configure_mesh(prior)
            telemetry.disable()
            telemetry.reset()

    def test_indivisible_mesh_falls_back_unsharded(self):
        """A 3-device mesh over a 256-row pad bucket cannot split the
        node axis: the wave must dispatch single-device, count a
        fallback, and still place identically."""
        from nomad_tpu.parallel.sharded import wave_mesh as make

        mesh3 = make(3)
        _, _, kins, steps, feats = _shared_layout_wave(seed=7)
        before = coalesce.sharded_wave_stats.snapshot()
        sharded_before = coalesce.sharded_wave_launches
        out_m = coalesce.launch_wave(kins, steps, feats, mesh=mesh3)
        out_s = coalesce.launch_wave(kins, steps, feats, mesh=None)
        after = coalesce.sharded_wave_stats.snapshot()
        assert coalesce.sharded_wave_launches == sharded_before
        assert after["fallbacks"] == before["fallbacks"] + 1
        for a, b in zip(out_m, out_s):
            np.testing.assert_array_equal(np.asarray(a.chosen),
                                          np.asarray(b.chosen))


class TestShardedWarmup:
    def test_warmup_populates_sharded_jit_signatures(self, wave_mesh):
        """ops/warmup learns the sharded joint programs: a manifest
        entry warmed with ``mesh`` makes the live sharded launch of
        that bucket shape a cache HIT (0 joint_sharded misses) — the
        steady-state-keeps-0-compiles contract, mesh edition."""
        from nomad_tpu import telemetry
        from nomad_tpu.ops import warmup as kernel_warmup
        from nomad_tpu.ops.kernel import LEAN_FEATURES, pad_steps
        from nomad_tpu.telemetry.kernel_profile import profiler

        _, _, kins, steps, feats = _shared_layout_wave(seed=11)
        n_pad = int(np.asarray(kins[0].cap_cpu).shape[0])
        b_pad = coalesce.pad_wave(len(kins))
        feat_union = coalesce.union_features(feats)
        entry = {
            "kernel": "joint", "wave": b_pad,
            "steps": pad_steps(b_pad * steps[0]), "nodes": n_pad,
            # the all-stacked layout (no residency installed here)
            "shared": False, "neutral_shared": False,
            "job_shared": False,
            "features": dict(feat_union._asdict()),
        }
        compiled, failed = kernel_warmup.warmup_entries(
            [entry], mesh=wave_mesh, mesh_only=True)
        assert compiled == 1 and failed == 0
        telemetry.enable()
        telemetry.reset()
        try:
            coalesce.launch_wave(kins, steps, feats, mesh=wave_mesh)
            assert profiler.misses_for("joint_sharded") == 0, \
                profiler.summary()["PerKey"]
        finally:
            telemetry.disable()
            telemetry.reset()

    def test_sharded_launch_keys_fold_into_manifest(self, wave_mesh):
        """A mesh server's manifest must not go empty just because
        every wave dispatched sharded: joint_sharded profiler keys
        fold into mesh-agnostic joint entries."""
        from nomad_tpu import telemetry
        from nomad_tpu.ops import warmup as kernel_warmup
        from nomad_tpu.telemetry.kernel_profile import profiler

        _, _, kins, steps, feats = _shared_layout_wave(seed=13)
        telemetry.enable()
        telemetry.reset()
        try:
            coalesce.launch_wave(kins, steps, feats, mesh=wave_mesh)
            entries = kernel_warmup.manifest_from_profiler(profiler)
        finally:
            telemetry.disable()
            telemetry.reset()
        joints = [e for e in entries if e["kernel"] == "joint"]
        assert joints, entries
        assert joints[0]["nodes"] == 256


class TestServerOverMesh:
    def test_server_places_through_sharded_waves(self, wave_mesh):
        """A live server with use_device_mesh=True places a batched
        job's allocations through shard_map-style sharded waves."""
        import time

        from nomad_tpu.server.server import Server, ServerConfig

        before = coalesce.sharded_wave_launches
        server = Server(ServerConfig(
            num_workers=1, worker_batch_size=8, heartbeat_ttl=3600.0,
            use_device_mesh=True,
        ))
        server.start()
        try:
            assert server.wave_mesh is not None
            for _ in range(30):
                server.node_register(mock.node())
            jobs = []
            for _ in range(8):
                job = mock.simple_job()
                job.task_groups[0].count = 3
                jobs.append(job)
                server.job_register(job)
            deadline = time.time() + 120
            placed = 0
            while time.time() < deadline:
                snap = server.state.snapshot()
                placed = sum(len(snap.allocs_by_job(j.namespace, j.id))
                             for j in jobs)
                if placed >= 24:
                    break
                time.sleep(0.1)
            assert placed >= 24, placed
            assert coalesce.sharded_wave_launches > before
            # placements are real: every alloc row maps to a node with
            # capacity accounting in the usage planes
            u = server.state.snapshot().usage
            assert float(u.used_cpu.sum()) >= 24 * 500
        finally:
            server.shutdown()


class TestMiniMeshSmoke:
    def test_steady_sharded_bursts_keep_zero_new_compiles(self):
        """Tier-1 mini-mesh smoke (ISSUE 14 satellite): a live mesh
        server places two bursts through sharded waves; the SECOND
        burst re-uses burst 1's compiled sharded programs (0 new
        joint_sharded misses), every wave dispatches sharded
        (fallbacks 0), and the resident cluster state advances by
        dirty-row scatter between waves."""
        import time

        from nomad_tpu import mock, telemetry
        from nomad_tpu.server.server import Server, ServerConfig
        from nomad_tpu.telemetry.kernel_profile import profiler
        from nomad_tpu.tensors.device_state import default_device_state

        server = Server(ServerConfig(
            num_workers=1, worker_batch_size=8, heartbeat_ttl=3600.0,
            use_device_mesh=True,
        ))
        telemetry.enable()
        telemetry.reset()
        server.start()
        try:
            assert server.wave_mesh is not None
            # the server adopted its mesh into the resident state
            assert default_device_state.mesh is server.wave_mesh
            for _ in range(30):
                server.node_register(mock.node())

            def burst(n_jobs: int) -> None:
                jobs = []
                for _ in range(n_jobs):
                    job = mock.simple_job()
                    job.task_groups[0].count = 3
                    jobs.append(job)
                    server.job_register(job)
                deadline = time.time() + 120
                while time.time() < deadline:
                    snap = server.state.snapshot()
                    placed = sum(
                        len(snap.allocs_by_job(j.namespace, j.id))
                        for j in jobs)
                    if placed >= 3 * n_jobs:
                        return
                    time.sleep(0.05)
                raise AssertionError("burst did not place in time")

            burst(8)
            stats1 = coalesce.sharded_wave_stats.snapshot()
            assert stats1["launches"] >= 1, stats1
            assert stats1["fallbacks"] == 0, stats1
            # the warmup-manifest flow, mesh edition: burst 1's
            # observed keys (sharded keys fold into joint entries)
            # expand over the bucket lattice and AOT-compile the
            # sharded signatures — burst 2 then cannot hit a tail
            # bucket cold (a deadline-fired partial wave lands on a
            # smaller, pre-warmed bucket)
            from nomad_tpu.ops import warmup as kernel_warmup

            entries = kernel_warmup.expand_lattice(
                kernel_warmup.manifest_from_profiler(profiler),
                max_wave=8)
            compiled, failed = kernel_warmup.warmup_entries(
                entries, mesh=server.wave_mesh, mesh_only=True)
            assert compiled >= 1 and failed == 0, (compiled, failed)
            misses1 = profiler.misses_for("joint_sharded")
            burst(8)
            stats2 = coalesce.sharded_wave_stats.snapshot()
            assert stats2["launches"] > stats1["launches"], stats2
            assert stats2["fallbacks"] == 0, stats2
            # steady state: burst 2's sharded waves are all cache hits
            assert profiler.misses_for("joint_sharded") == misses1, \
                profiler.summary()["PerKey"]
            # dirty-row advancement ran (the between-wave scatter)
            assert default_device_state.snapshot()["delta_advances"] \
                >= 1, default_device_state.snapshot()
        finally:
            server.shutdown()
            telemetry.disable()
            telemetry.reset()
            assert default_device_state.mesh is None
