"""Live waves over the device mesh (VERDICT r2 missing #2).

The coalescer's joint wave kernel runs with its node axis sharded over
the mesh (parallel/sharded.make_joint_sharded): the SAME program, so
placements must be identical to single-device dispatch — per-step
argmax/top-k lower to per-shard reductions + cross-shard collectives
(SURVEY.md §2.10 node-axis-over-ICI mapping). Tests run on the
8-virtual-CPU mesh (conftest forces the device count).
"""

import numpy as np
import pytest

import jax

from nomad_tpu import mock
from nomad_tpu.parallel import coalesce


@pytest.fixture
def wave_mesh():
    from nomad_tpu.parallel.sharded import wave_mesh as make

    assert len(jax.devices()) >= 8, "conftest must force 8 CPU devices"
    return make(8)


class TestShardedWaveParity:
    def test_launch_wave_identical_to_single_device(self, wave_mesh):
        from nomad_tpu.ops.kernel import (
            LEAN_FEATURES,
            build_kernel_in,
            infer_features,
        )
        from nomad_tpu.parallel.synthetic import (
            synthetic_cluster,
            synthetic_eval,
        )

        cluster = synthetic_cluster(200, cpu=2000.0, mem=4096.0,
                                    disk=50000.0, seed=5)
        rng = np.random.default_rng(3)
        kins, steps, feats = [], [], []
        for i in range(5):
            ev = synthetic_eval(cluster, desired_count=4)
            kin = build_kernel_in(cluster, ev, 4)
            kin = kin._replace(
                ask_cpu=np.asarray(float(rng.choice([100, 300, 500])),
                                   np.float32))
            kins.append(kin)
            steps.append(4)
            feats.append(LEAN_FEATURES._replace(with_topk=True))

        coalesce.configure_wave_mesh(None)
        single = coalesce.launch_wave(kins, steps, feats)

        before = coalesce.sharded_wave_launches
        coalesce.configure_wave_mesh(wave_mesh)
        try:
            sharded = coalesce.launch_wave(kins, steps, feats)
        finally:
            coalesce.configure_wave_mesh(None)
        assert coalesce.sharded_wave_launches == before + 1

        for s, m in zip(single, sharded):
            np.testing.assert_array_equal(np.asarray(s.chosen),
                                          np.asarray(m.chosen))
            np.testing.assert_array_equal(np.asarray(s.found),
                                          np.asarray(m.found))
            np.testing.assert_allclose(np.asarray(s.scores),
                                       np.asarray(m.scores),
                                       rtol=1e-6, atol=1e-7)
        assert any(np.asarray(s.found).any() for s in single)


class TestServerOverMesh:
    def test_server_places_through_sharded_waves(self, wave_mesh):
        """A live server with use_device_mesh=True places a batched
        job's allocations through shard_map-style sharded waves."""
        import time

        from nomad_tpu.server.server import Server, ServerConfig

        before = coalesce.sharded_wave_launches
        server = Server(ServerConfig(
            num_workers=1, worker_batch_size=8, heartbeat_ttl=3600.0,
            use_device_mesh=True,
        ))
        server.start()
        try:
            assert server.wave_mesh is not None
            for _ in range(30):
                server.node_register(mock.node())
            jobs = []
            for _ in range(8):
                job = mock.simple_job()
                job.task_groups[0].count = 3
                jobs.append(job)
                server.job_register(job)
            deadline = time.time() + 120
            placed = 0
            while time.time() < deadline:
                snap = server.state.snapshot()
                placed = sum(len(snap.allocs_by_job(j.namespace, j.id))
                             for j in jobs)
                if placed >= 24:
                    break
                time.sleep(0.1)
            assert placed >= 24, placed
            assert coalesce.sharded_wave_launches > before
            # placements are real: every alloc row maps to a node with
            # capacity accounting in the usage planes
            u = server.state.snapshot().usage
            assert float(u.used_cpu.sum()) >= 24 * 500
        finally:
            server.shutdown()
