"""Reconcile fast-path bit-identity: the fused single-pass classifier
(scheduler/reconcile.classify_group + the memoized per-(job, tg)
invariants) must produce ReconcileResults identical to the legacy
multi-pass composition (filter_by_tainted -> should_filter ->
filter_by_rescheduleable -> _update_by_reschedulable) over randomized
alloc populations — tainted/disconnected/canary/reschedule/drain mixes
— INCLUDING the order of every result list (stops, placements,
followup evals), which downstream plan construction observes.

Run ids (followup eval ids, new deployment ids) are generated fresh
per run, so fingerprints normalize them by order of first appearance;
everything else must match exactly.
"""

from __future__ import annotations

import random

import pytest

from nomad_tpu import mock
from nomad_tpu.scheduler.reconcile import (
    AllocReconciler,
    classify_group,
    filter_by_rescheduleable,
    filter_by_tainted,
    union,
)
from nomad_tpu.structs import consts
from nomad_tpu.structs.alloc import (
    AllocDeploymentStatus,
    DesiredTransition,
    RescheduleEvent,
    RescheduleTracker,
    TaskEvent,
    TaskState,
)
from nomad_tpu.structs.eval_plan import Deployment, DeploymentState
from nomad_tpu.structs.job import ReschedulePolicy

NOW = 1_700_000_000.0

CLIENT_STATUSES = (
    consts.ALLOC_CLIENT_PENDING, consts.ALLOC_CLIENT_RUNNING,
    consts.ALLOC_CLIENT_COMPLETE, consts.ALLOC_CLIENT_FAILED,
    consts.ALLOC_CLIENT_LOST, consts.ALLOC_CLIENT_UNKNOWN,
)
DESIRED_STATUSES = (
    consts.ALLOC_DESIRED_RUN, consts.ALLOC_DESIRED_STOP,
    consts.ALLOC_DESIRED_EVICT,
)


def _build_scenario(seed: int):
    """(reconciler_kwargs...) for one randomized population."""
    rng = random.Random(seed)
    is_batch = rng.random() < 0.3

    job = mock.job(id=f"recon-{seed}")
    if is_batch:
        job.type = consts.JOB_TYPE_BATCH
    tg = job.task_groups[0]
    tg.count = rng.randint(1, 8)
    # reschedule-policy mix: disabled / constant / unlimited / default
    roll = rng.random()
    if roll < 0.25:
        tg.reschedule_policy = ReschedulePolicy(attempts=0, interval_s=0)
    elif roll < 0.5:
        tg.reschedule_policy = ReschedulePolicy(
            attempts=2, interval_s=600, delay_s=5, delay_function="constant")
    elif roll < 0.75:
        tg.reschedule_policy = ReschedulePolicy(
            delay_s=5, delay_function="exponential", max_delay_s=300,
            unlimited=True)
    else:
        tg.reschedule_policy = None
    if rng.random() < 0.4:
        tg.max_client_disconnect_s = rng.choice([30.0, 600.0])
    if rng.random() < 0.3:
        tg.stop_after_client_disconnect_s = 60.0

    # older job version for the batch terminal filter
    old_job = mock.job(id=job.id)
    old_job.type = job.type
    old_job.version = 0
    old_job.create_index = 1
    job.version = rng.randint(0, 2)
    job.create_index = 42

    nodes = {}
    tainted = {}
    node_ids = []
    for i in range(6):
        status = rng.choice([
            consts.NODE_STATUS_READY, consts.NODE_STATUS_READY,
            consts.NODE_STATUS_DOWN, consts.NODE_STATUS_DISCONNECTED,
        ])
        drain = status == consts.NODE_STATUS_READY and rng.random() < 0.2
        n = mock.node(status=status, drain=drain)
        nodes[n.id] = n
        node_ids.append(n.id)
        if drain or status in (consts.NODE_STATUS_DOWN,
                               consts.NODE_STATUS_DISCONNECTED):
            tainted[n.id] = n
    missing_id = f"missing-node-{seed}"
    node_ids.append(missing_id)
    if rng.random() < 0.7:
        tainted[missing_id] = None

    deployment = None
    if rng.random() < 0.5:
        deployment = Deployment(
            id=f"dep-{seed}",
            job_id=job.id,
            job_version=job.version,
            job_create_index=job.create_index,
            status=rng.choice([
                consts.DEPLOYMENT_STATUS_RUNNING,
                consts.DEPLOYMENT_STATUS_PAUSED,
                consts.DEPLOYMENT_STATUS_FAILED,
                consts.DEPLOYMENT_STATUS_SUCCESSFUL,
            ]),
        )
        ds = DeploymentState(
            desired_total=tg.count,
            desired_canaries=rng.choice([0, 0, 2]),
            promoted=rng.random() < 0.3,
        )
        deployment.task_groups[tg.name] = ds

    allocs = []
    for i in range(rng.randint(0, 18)):
        a_job = old_job if (is_batch and rng.random() < 0.3) else job
        a = mock.alloc(
            id=f"alloc-{seed}-{i:02d}",
            job=a_job,
            job_id=job.id,
            task_group=tg.name,
            name=f"{job.id}.{tg.name}[{rng.randint(0, tg.count + 2)}]",
            node_id=rng.choice(node_ids),
            desired_status=rng.choice(DESIRED_STATUSES),
            client_status=rng.choice(CLIENT_STATUSES),
            job_version=a_job.version,
            modify_time_ns=int((NOW - rng.uniform(0, 1200)) * 1e9),
        )
        if rng.random() < 0.3:
            a.desired_transition = DesiredTransition(
                migrate=rng.random() < 0.5,
                reschedule=rng.random() < 0.3,
                force_reschedule=rng.random() < 0.2,
            )
        if rng.random() < 0.3:
            events = []
            t0 = int((NOW - rng.uniform(10, 900)) * 1e9)
            events.append(TaskEvent(type="Disconnected", time_ns=t0))
            if rng.random() < 0.6:
                events.append(TaskEvent(
                    type="Reconnected",
                    time_ns=t0 + int(rng.uniform(-5, 60) * 1e9)))
            a.task_states = {"web": TaskState(events=events)}
        if rng.random() < 0.25:
            a.reschedule_tracker = RescheduleTracker(events=[
                RescheduleEvent(
                    reschedule_time_ns=int((NOW - rng.uniform(0, 700)) * 1e9),
                    prev_alloc_id=f"prev-{i}", prev_node_id=rng.choice(node_ids))
                for _ in range(rng.randint(1, 3))
            ])
        if rng.random() < 0.15:
            a.follow_up_eval_id = f"eval-follow-{seed}"
        if rng.random() < 0.1:
            a.next_allocation = f"alloc-next-{i}"
        if deployment is not None and rng.random() < 0.4:
            a.deployment_id = deployment.id
            a.deployment_status = AllocDeploymentStatus(
                healthy=rng.choice([True, False, None]),
                canary=rng.random() < 0.3,
            )
            if a.deployment_status.canary:
                deployment.task_groups[tg.name].placed_canaries.append(a.id)
        allocs.append(a)

    update_rolls = {a.id: rng.random() for a in allocs}

    def update_fn(existing, new_job, new_tg):
        r = update_rolls.get(existing.id, 0.0)
        if r < 0.6:
            return True, False, None
        if r < 0.8:
            return False, True, None
        return False, False, existing.copy_skip_job()

    return {
        "alloc_update_fn": update_fn,
        "batch": is_batch,
        "job_id": job.id,
        "job": job,
        "deployment": deployment,
        "existing_allocs": allocs,
        "tainted_nodes": tainted,
        "eval_id": f"eval-{seed}",
        "eval_priority": 50,
        "now": NOW,
    }


def _fingerprint(results):
    """Order-preserving fingerprint with generated ids normalized by
    first appearance (followup eval ids, new deployment ids)."""
    norm = {}

    def nid(x):
        if not x:
            return ""
        return norm.setdefault(x, f"gen-{len(norm)}")

    place = [
        (p.name, getattr(p, "canary", False), p.previous_alloc.id
         if p.previous_alloc is not None else "",
         getattr(p, "reschedule", False), getattr(p, "lost", False),
         getattr(p, "downgrade_non_canary", False),
         getattr(p, "min_job_version", 0))
        for p in results.place
    ]
    destructive = [
        (d.place_name, d.stop_alloc.id if d.stop_alloc else "",
         d.stop_status_description)
        for d in results.destructive_update
    ]
    stop = [
        (s.alloc.id, s.client_status, s.status_description,
         nid(s.followup_eval_id))
        for s in results.stop
    ]
    inplace = [a.id for a in results.inplace_update]
    attr = {aid: nid(a.follow_up_eval_id)
            for aid, a in results.attribute_updates.items()}
    disco = {
        aid: (a.client_status, nid(a.follow_up_eval_id),
              tuple(sorted(
                  (name, tuple((e.type, e.time_ns) for e in ts.events))
                  for name, ts in a.task_states.items())))
        for aid, a in results.disconnect_updates.items()
    }
    reco = {aid: a.client_status
            for aid, a in results.reconnect_updates.items()}
    du = {
        g: (d.ignore, d.place, d.migrate, d.stop, d.in_place_update,
            d.destructive_update, d.canary, d.preemptions)
        for g, d in results.desired_tg_updates.items()
    }
    followups = {
        g: [(ev.triggered_by, round(ev.wait_until_s, 6), nid(ev.id))
            for ev in evs]
        for g, evs in results.desired_followup_evals.items()
    }
    dep = None
    if results.deployment is not None:
        d = results.deployment
        dep = (nid(d.id), d.status, d.status_description, sorted(
            (g, s.desired_total, s.desired_canaries, s.promoted,
             tuple(nid(c) if c in norm else c for c in s.placed_canaries))
            for g, s in d.task_groups.items()))
    dep_updates = [
        (nid(u["deployment_id"]) if u["deployment_id"] in norm
         else u["deployment_id"], u["status"])
        for u in results.deployment_updates
    ]
    return (place, destructive, stop, inplace, attr, disco, reco, du,
            followups, dep, dep_updates)


class TestReconcileFastBitIdentity:
    @pytest.mark.parametrize("seed", range(40))
    def test_fast_matches_legacy(self, seed):
        kwargs = _build_scenario(seed)
        legacy = AllocReconciler(use_legacy_filters=True, **kwargs).compute()
        fast = AllocReconciler(use_legacy_filters=False, **kwargs).compute()
        assert _fingerprint(legacy) == _fingerprint(fast), f"seed {seed}"

    @pytest.mark.parametrize("seed", range(25))
    def test_classify_group_matches_filter_pipeline(self, seed):
        """The fused classifier against the raw legacy pipeline,
        checking set MEMBERSHIP AND ORDER for every partition."""
        kwargs = _build_scenario(seed)
        allocs = {a.id: a for a in kwargs["existing_allocs"]}
        tainted = kwargs["tainted_nodes"]
        is_batch = kwargs["batch"]
        eval_id = kwargs["eval_id"]
        deployment = kwargs["deployment"]

        unt, mig, lost, disc, reco, ign = filter_by_tainted(
            allocs, tainted, True, NOW)
        unt2, res_now, res_later = filter_by_rescheduleable(
            unt, is_batch, False, NOW, eval_id, deployment)
        _, res_disc, _ = filter_by_rescheduleable(
            disc, is_batch, True, NOW, eval_id, deployment)
        res_all = union(res_now, res_disc)

        cls = classify_group(
            allocs, tainted, True, NOW, is_batch, eval_id, deployment)

        assert list(cls.untainted) == list(unt2), f"seed {seed}"
        assert list(cls.migrate) == list(mig)
        assert list(cls.lost) == list(lost)
        assert list(cls.disconnecting) == list(disc)
        assert list(cls.reconnecting) == list(reco)
        assert cls.ignore == len(ign)
        assert list(cls.reschedule_now) == list(res_all)
        assert [(i.alloc_id, i.reschedule_time_s)
                for i in cls.reschedule_later] == \
            [(i.alloc_id, i.reschedule_time_s) for i in res_later]
