"""HA server agents: raft over TCP between full agents.

Modeled on reference nomad/server_test.go multi-server tests
(TestJoin-style real 3-node raft clusters) — but through the agent +
HTTP layer: three agents with static raft peers elect a leader,
replicate writes submitted to any agent, and survive leader loss.
"""

import socket
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.api.agent import Agent, AgentConfig
from nomad_tpu.api.client import APIClient
from nomad_tpu.api.codec import encode
from nomad_tpu.client.client import Client, ClientConfig, InProcessRPC


def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _wait(fn, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            if fn():
                return True
        except Exception:                       # noqa: BLE001
            pass
        time.sleep(0.1)
    return False


@pytest.fixture()
def ha_cluster(tmp_path):
    ports = _free_ports(3)
    peers = [f"127.0.0.1:{p}" for p in ports]
    agents = [
        Agent(AgentConfig(name=f"srv-{i}", num_schedulers=1,
                          raft_port=ports[i], raft_peers=peers))
        for i in range(3)
    ]
    client = None
    try:
        for a in agents:
            a.start()
        assert _wait(lambda: any(a.server.is_leader() for a in agents)), \
            "no leader elected"
        # a real heartbeating client node (a bare mock node would be
        # marked down by the TTL timers), attached to a FOLLOWER so it
        # survives leader loss — its writes forward to the leader
        follower = next(a for a in agents if not a.server.is_leader())
        client = Client(InProcessRPC(follower.server),
                        ClientConfig(data_dir=str(tmp_path / "client")))
        client.start()
        assert _wait(lambda: all(
            a.server.state.snapshot().node_by_id(client.node_id)
            is not None for a in agents))
        yield agents, client
    finally:
        if client is not None:
            client.shutdown()
        for a in agents:
            try:
                a.shutdown()
            except Exception:                   # noqa: BLE001
                pass


def _leader(agents):
    return next((a for a in agents if a.server.is_leader()), None)


class TestHAAgents:
    def test_write_to_follower_replicates_everywhere(self, ha_cluster):
        agents, _client = ha_cluster
        follower = next(a for a in agents if not a.server.is_leader())
        api = APIClient(follower.http_addr)
        job = mock.job()
        job.task_groups[0].count = 4   # fits the single client node
        api.jobs.register(encode(job))         # HTTP to a follower
        assert _wait(lambda: all(
            a.server.state.snapshot().job_by_id(job.namespace, job.id)
            is not None for a in agents
        )), "job not replicated to every server"
        # scheduling happens on the leader; allocs replicate back
        assert _wait(lambda: all(
            len(a.server.state.snapshot().allocs_by_job(
                job.namespace, job.id)) == 4 for a in agents
        ), timeout=60), "allocs not replicated"

    def test_leader_loss_failover_keeps_scheduling(self, ha_cluster):
        agents, client = ha_cluster
        old_leader = _leader(agents)
        old_leader.shutdown()
        survivors = [a for a in agents if a is not old_leader]
        assert _wait(lambda: _leader(survivors) is not None, timeout=30), \
            "no new leader after failover"
        new_leader = _leader(survivors)
        api = APIClient(new_leader.http_addr)
        job = mock.job()
        job.task_groups[0].count = 4
        api.jobs.register(encode(job))
        assert _wait(lambda: len(
            new_leader.server.state.snapshot().allocs_by_job(
                job.namespace, job.id)) == 4, timeout=60), \
            "new leader stopped scheduling"
