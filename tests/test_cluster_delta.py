"""Incremental ClusterTensors (ISSUE 2 tentpole part 2): the
dirty-node delta path must be bit-identical to a fresh build after any
sequence of node add / drain / resource-change / status / delete, and
the cache must actually serve hits and deltas instead of full rebuilds.
"""

import numpy as np
import numpy.testing as npt
import pytest

from nomad_tpu import mock
from nomad_tpu.state.store import StateStore
from nomad_tpu.tensors.schema import (
    ClusterTensors,
    IncrementalClusterCache,
)


def assert_cluster_equal(got: ClusterTensors, want: ClusterTensors):
    assert got.n_real == want.n_real
    assert got.n_pad == want.n_pad
    for f in ClusterTensors._PLANE_FIELDS:
        npt.assert_array_equal(getattr(got, f), getattr(want, f),
                               err_msg=f)
    for f in ClusterTensors._RAGGED_FIELDS:
        assert getattr(got, f) == getattr(want, f), f
    assert got.index == want.index
    assert set(got.nodes_by_id) == set(want.nodes_by_id)


@pytest.fixture()
def store():
    s = StateStore()
    for _ in range(24):
        s.upsert_node(mock.node())
    return s


class TestDeltaParity:
    def test_resource_change_delta_matches_fresh_build(self, store):
        cache = IncrementalClusterCache()
        cache.get(store.snapshot())
        node = store.snapshot().nodes()[5].copy()
        node.node_resources.cpu.cpu_shares = 12345
        node.node_resources.memory.memory_mb = 4096
        store.upsert_node(node)
        snap = store.snapshot()
        got = cache.get(snap)
        assert cache.delta_builds == 1
        assert_cluster_equal(got, ClusterTensors.build(snap.nodes()))

    def test_drain_and_status_delta(self, store):
        cache = IncrementalClusterCache()
        cache.get(store.snapshot())
        nodes = store.snapshot().nodes()
        store.update_node_drain(nodes[2].id, True)
        store.update_node_status(nodes[9].id, "down")
        snap = store.snapshot()
        got = cache.get(snap)
        assert cache.delta_builds == 1
        fresh = ClusterTensors.build(snap.nodes())
        assert_cluster_equal(got, fresh)
        # the drained/down rows really flipped
        assert not got.ready[2]
        assert not got.ready[9]

    def test_add_and_delete_delta(self, store):
        cache = IncrementalClusterCache()
        cache.get(store.snapshot())
        nodes = store.snapshot().nodes()
        store.delete_node(nodes[7].id)
        store.upsert_node(mock.node())
        store.upsert_node(mock.node())
        snap = store.snapshot()
        got = cache.get(snap)
        assert cache.delta_builds == 1
        assert_cluster_equal(got, ClusterTensors.build(snap.nodes()))

    def test_random_mutation_sequences(self, store):
        """Property-style: random interleavings of add / drain /
        resource-change / status / delete, parity after every batch."""
        rng = np.random.default_rng(11)
        cache = IncrementalClusterCache()
        cache.get(store.snapshot())
        for _round in range(6):
            for _ in range(int(rng.integers(1, 4))):
                nodes = store.snapshot().nodes()
                op = rng.integers(0, 5)
                pick = nodes[int(rng.integers(0, len(nodes)))]
                if op == 0:
                    store.upsert_node(mock.node())
                elif op == 1 and len(nodes) > 4:
                    store.delete_node(pick.id)
                elif op == 2:
                    n = pick.copy()
                    n.node_resources.cpu.cpu_shares = int(
                        rng.integers(1000, 9000))
                    store.upsert_node(n)
                elif op == 3:
                    store.update_node_drain(pick.id,
                                            bool(rng.integers(0, 2)))
                else:
                    store.update_node_status(
                        pick.id, "down" if rng.integers(0, 2) else "ready")
            snap = store.snapshot()
            got = cache.get(snap)
            assert_cluster_equal(got, ClusterTensors.build(snap.nodes()))
        assert cache.delta_builds >= 4

    def test_empty_base_falls_back_to_full_build(self):
        """A cluster snapshotted before any node registers caches an
        empty build; the first nodes arriving must take the full-build
        path (there are no rows to gather from)."""
        s = StateStore()
        cache = IncrementalClusterCache()
        empty = cache.get(s.snapshot())
        assert empty.n_real == 0
        for _ in range(4):
            s.upsert_node(mock.node())
        snap = s.snapshot()
        got = cache.get(snap)
        assert got.n_real == 4
        assert_cluster_equal(got, ClusterTensors.build(snap.nodes()))

    def test_pad_bucket_growth_falls_back_to_full_build(self):
        s = StateStore()
        for _ in range(60):
            s.upsert_node(mock.node())
        cache = IncrementalClusterCache()
        cache.get(s.snapshot())        # n_pad 64
        for _ in range(10):            # crosses into the 128 bucket
            s.upsert_node(mock.node())
        snap = s.snapshot()
        got = cache.get(snap)
        assert cache.full_builds == 2
        assert_cluster_equal(got, ClusterTensors.build(snap.nodes()))


class TestCacheBehavior:
    def test_same_version_is_identity_hit(self, store):
        cache = IncrementalClusterCache()
        snap = store.snapshot()
        c1 = cache.get(snap)
        assert cache.get(store.snapshot()) is c1
        assert cache.hits == 1

    def test_alloc_churn_does_not_invalidate(self, store):
        """Allocation transitions bump usage.version but not the node
        structure: the node planes must stay cached."""
        cache = IncrementalClusterCache()
        c1 = cache.get(store.snapshot())
        node = store.snapshot().nodes()[0]
        a = mock.alloc(node_id=node.id)
        store.upsert_allocs([a])
        assert cache.get(store.snapshot()) is c1

    def test_older_snapshot_stays_cached_alongside_newer(self, store):
        """A batch still scheduling against an older snapshot must keep
        getting ONE identical object per call (identity sharing is the
        wave launcher's upload layout), even after a newer structure
        version was cached."""
        cache = IncrementalClusterCache()
        old_snap = store.snapshot()
        c_old = cache.get(old_snap)
        store.upsert_node(mock.node())
        new_snap = store.snapshot()
        c_new = cache.get(new_snap)
        assert c_new is not c_old
        # the older version is still served by identity, not rebuilt
        builds_before = cache.full_builds + cache.delta_builds
        assert cache.get(old_snap) is c_old
        assert cache.get(old_snap) is c_old
        assert cache.full_builds + cache.delta_builds == builds_before
        # and the newer one too
        assert cache.get(new_snap) is c_new

    def test_trimmed_log_falls_back_to_full_build(self, store):
        from nomad_tpu.state import usage as usage_mod

        cache = IncrementalClusterCache()
        cache.get(store.snapshot())
        # more structural events than the log holds
        for _ in range(usage_mod.NODE_LOG_MAX // 2 + 4):
            store.upsert_node(mock.node())
            store.delete_node(store.snapshot().nodes()[-1].id)
        snap = store.snapshot()
        got = cache.get(snap)
        assert cache.full_builds == 2
        assert cache.delta_builds == 0
        assert_cluster_equal(got, ClusterTensors.build(snap.nodes()))
