"""Docker driver depth, exercised against a FAKE docker CLI.

The environment has no docker daemon; a PATH-injected stub records
every invocation and simulates the engine, which lets the driver's
operational surface (pull coordination, stats, streaming exec, stop/rm
plumbing) run for real without one. Reference: drivers/docker/
(driver.go, coordinator.go, stats.go).
"""

import json
import os
import stat
import threading
import time
import uuid

import pytest

from nomad_tpu import structs
from nomad_tpu.drivers.docker import DockerDriver, _parse_size
from nomad_tpu.plugins.drivers import TaskConfig

FAKE_DOCKER = r"""#!/bin/sh
# env does not flow through the scrubbed task env: self-locate state
HERE=$(dirname "$0")
LOG="${FAKE_DOCKER_LOG:-$HERE/../invocations.log}"
FAKE_DOCKER_STATE="${FAKE_DOCKER_STATE:-$HERE/../state}"
echo "$@" >> "$LOG"
CONFDIR=""
if [ "$1" = "--config" ]; then CONFDIR="$2"; shift 2; fi
cmd="$1"
case "$cmd" in
  version) echo "24.0.7"; exit 0 ;;
  image)
    # inspect: image exists only after a pull marker appears
    img="$3"
    if [ -f "$FAKE_DOCKER_STATE/pulled-$(echo "$img" | tr '/:' '__')" ]; then
      exit 0
    fi
    exit 1 ;;
  pull)
    img="$2"
    sleep "${FAKE_DOCKER_PULL_DELAY:-0.2}"
    touch "$FAKE_DOCKER_STATE/pulled-$(echo "$img" | tr '/:' '__')"
    if [ -n "$CONFDIR" ] && [ -f "$CONFDIR/config.json" ]; then
      cp "$CONFDIR/config.json" \
        "$FAKE_DOCKER_STATE/auth-$(echo "$img" | tr '/:' '__')"
    fi
    exit 0 ;;
  rmi)
    touch "$FAKE_DOCKER_STATE/removed-$(echo "$2" | tr '/:' '__')"
    exit 0 ;;
  run) exec sleep 30 ;;
  stats) echo '{"CPUPerc":"12.5%","MemUsage":"21.48MiB / 1GiB"}'; exit 0 ;;
  exec)
    shift
    while [ "${1#-}" != "$1" ]; do shift; done   # drop -i/-it flags
    shift                                        # container name
    exec "$@" ;;
  stop|rm) exit 0 ;;
  *) exit 0 ;;
esac
"""


@pytest.fixture()
def fake_docker(tmp_path, monkeypatch):
    bin_dir = tmp_path / "bin"
    state = tmp_path / "state"
    bin_dir.mkdir()
    state.mkdir()
    stub = bin_dir / "docker"
    stub.write_text(FAKE_DOCKER)
    stub.chmod(stub.stat().st_mode | stat.S_IEXEC)
    log = tmp_path / "invocations.log"
    log.touch()
    monkeypatch.setenv("PATH", f"{bin_dir}:{os.environ['PATH']}")
    monkeypatch.setenv("FAKE_DOCKER_LOG", str(log))
    monkeypatch.setenv("FAKE_DOCKER_STATE", str(state))
    return log


def _cfg(tmp_path, image="busybox:1.36", name="web"):
    return TaskConfig(
        id=f"{uuid.uuid4()}-{name}",
        name=name,
        alloc_id=str(uuid.uuid4()),
        driver_config={"image": image},
        resources=structs.Resources(cpu=200, memory_mb=128),
        alloc_dir=str(tmp_path),
    )


def _calls(log, verb):
    return [line for line in log.read_text().splitlines()
            if line.startswith(verb + " ")]


class TestDockerDriver:
    def test_fingerprint_healthy_with_cli(self, fake_docker):
        fp = DockerDriver().fingerprint()
        assert fp.attributes.get("driver.docker.version") == "24.0.7"

    def test_pull_coordination_single_pull(self, fake_docker, tmp_path,
                                           monkeypatch):
        """N concurrent tasks of one image trigger exactly ONE pull
        (coordinator.go singleflight)."""
        monkeypatch.setenv("FAKE_DOCKER_PULL_DELAY", "0.5")
        driver = DockerDriver()
        DockerDriver._pull_locks.clear()
        errors = []

        def start_one(i):
            try:
                driver._ensure_image("busybox:1.36")
            except Exception as e:                 # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=start_one, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        assert len(_calls(fake_docker, "pull")) == 1
        # already-present image: no further pulls
        driver._ensure_image("busybox:1.36")
        assert len(_calls(fake_docker, "pull")) == 1

    def test_run_with_stats_and_stop(self, fake_docker, tmp_path):
        # image GC off: a default 180s removal timer would outlive the
        # PATH monkeypatch and run `docker rmi` against the REAL host
        driver = DockerDriver(options={"docker.cleanup.image": "false"})
        DockerDriver._pull_locks.clear()
        cfg = _cfg(tmp_path)
        driver.start_task(cfg)
        try:
            stats = driver.task_stats(cfg.id)
            assert stats["cpu"]["percent"] == 12.5
            assert stats["memory"]["rss"] == int(21.48 * 1024 * 1024)
            run_calls = _calls(fake_docker, "run")
            assert run_calls and "--memory 128m" in run_calls[0]
            assert "--cpu-shares 200" in run_calls[0]
        finally:
            driver.stop_task(cfg.id, timeout=2)
            driver.destroy_task(cfg.id, force=True)
        assert _calls(fake_docker, "stop")
        assert _calls(fake_docker, "rm")

    def test_streaming_exec_enters_container(self, fake_docker, tmp_path):
        driver = DockerDriver(options={"docker.cleanup.image": "false"})
        DockerDriver._pull_locks.clear()
        cfg = _cfg(tmp_path)
        driver.start_task(cfg)
        try:
            stream = driver.exec_task_streaming(cfg.id, ["cat"])
            stream.write_stdin(b"through-docker-exec\n")
            stream.close_stdin()
            got = b""
            deadline = time.time() + 10
            while time.time() < deadline:
                item = stream.read_output(timeout=0.5)
                if item is None:
                    continue
                name, data = item
                if name == "exited":
                    break
                got += data
            assert b"through-docker-exec" in got
            assert any(line.startswith("exec -i ")
                       for line in fake_docker.read_text().splitlines())
        finally:
            driver.stop_task(cfg.id, timeout=2)
            driver.destroy_task(cfg.id, force=True)


def test_parse_size_units():
    assert _parse_size("21.48MiB") == int(21.48 * 1024 * 1024)
    assert _parse_size("1.5GiB") == int(1.5 * 1024 ** 3)
    assert _parse_size("512kB") == 512 * 1000
    assert _parse_size("") == 0


class TestEngineAPI:
    """Engine-API stats + docklog against a scripted unix-socket
    daemon (drivers/docker/stats.go math; docklog/docklog.go flow)."""

    RAW_STATS = {
        "cpu_stats": {
            "cpu_usage": {"total_usage": 400_000_000},
            "system_cpu_usage": 2_000_000_000,
            "online_cpus": 4,
        },
        "precpu_stats": {
            "cpu_usage": {"total_usage": 200_000_000},
            "system_cpu_usage": 1_000_000_000,
        },
        "memory_stats": {
            "usage": 104_857_600,
            "stats": {"total_inactive_file": 4_857_600},
        },
    }

    def _fake_engine(self, path):
        import http.server
        import json
        import socket
        import socketserver
        import struct
        import threading

        raw = self.RAW_STATS

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):  # noqa: N802
                pass

            def do_GET(self):  # noqa: N802
                if self.path.endswith("/_ping"):
                    body = b"OK"
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif "/stats" in self.path:
                    body = json.dumps(raw).encode()
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif "/logs" in self.path:
                    self.send_response(200)
                    self.end_headers()
                    for stream, data in ((1, b"out-line-1\n"),
                                         (2, b"err-line-1\n"),
                                         (1, b"out-line-2\n")):
                        self.wfile.write(
                            struct.pack(">BBBBI", stream, 0, 0, 0,
                                        len(data)) + data)
                    # close ends the follow
                elif "/version" in self.path:
                    body = json.dumps({"Version": "24.0.0"}).encode()
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_response(404)
                    self.end_headers()

        class UnixHTTPServer(socketserver.ThreadingUnixStreamServer):
            daemon_threads = True

            def get_request(self):
                request, _ = self.socket.accept()
                return request, ("", 0)

        srv = UnixHTTPServer(path, Handler)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return srv

    def test_stats_math(self, tmp_path):
        from nomad_tpu.drivers.docker_api import (
            DockerEngine,
            compute_cpu_percent,
            memory_rss,
        )

        path = str(tmp_path / "docker.sock")
        srv = self._fake_engine(path)
        try:
            engine = DockerEngine(path)
            assert engine.ping()
            raw = engine.stats("c1")
        finally:
            srv.shutdown()
        # delta 0.2e9 over 1e9 across 4 cpus -> 80%
        assert compute_cpu_percent(raw) == pytest.approx(80.0)
        # usage minus reclaimable cache
        assert memory_rss(raw) == 100_000_000

    def test_driver_task_stats_via_engine(self, tmp_path):
        from nomad_tpu.drivers.rawexec import _RawTask

        path = str(tmp_path / "docker.sock")
        srv = self._fake_engine(path)
        drv = DockerDriver()
        drv.engine_socket = path
        c = TaskConfig(id="t1", name="web", alloc_id="a1-xyz",
                       driver_config={"image": "busybox"},
                       resources=structs.Resources())
        task = _RawTask(c)
        drv._tasks[c.id] = task
        try:
            stats = drv.task_stats(c.id)
        finally:
            srv.shutdown()
        assert stats["cpu"]["percent"] == pytest.approx(80.0)
        assert stats["memory"]["rss"] == 100_000_000

    def test_docklog_streams_engine_logs_to_files(self, tmp_path):
        import subprocess
        import sys
        import time

        from nomad_tpu.drivers import docklog as docklog_mod

        path = str(tmp_path / "docker.sock")
        srv = self._fake_engine(path)
        out_file = tmp_path / "stdout"
        err_file = tmp_path / "stderr"
        try:
            proc = subprocess.Popen(
                [sys.executable, "-S", docklog_mod.__file__, path, "c1",
                 str(out_file), str(err_file)],
                start_new_session=True)
            deadline = time.time() + 15
            while time.time() < deadline:
                if proc.poll() is not None:
                    break
                time.sleep(0.1)
        finally:
            srv.shutdown()
        assert out_file.read_bytes() == b"out-line-1\nout-line-2\n"
        assert err_file.read_bytes() == b"err-line-1\n"


class TestImageLifecycle:
    """Registry auth chain + refcounted image GC
    (drivers/docker/driver.go:604, coordinator.go:16)."""

    def test_two_tasks_share_image_removed_after_both_stop(
            self, fake_docker, tmp_path):
        state = os.environ["FAKE_DOCKER_STATE"]
        driver = DockerDriver(options={
            "docker.cleanup.image.delay": "0.3"})
        c1 = _cfg(tmp_path, name="a")
        c2 = _cfg(tmp_path, name="b")
        h1 = driver.start_task(c1)
        h2 = driver.start_task(c2)
        removed = os.path.join(state, "removed-busybox_1.36")
        try:
            driver.destroy_task(c1.id, force=True)
            time.sleep(0.6)
            # second task still holds the reference: no removal
            assert not os.path.exists(removed)
            driver.destroy_task(c2.id, force=True)
            deadline = time.time() + 5
            while time.time() < deadline and not os.path.exists(removed):
                time.sleep(0.05)
            assert os.path.exists(removed), \
                "image not removed after last reference dropped"
        finally:
            driver.images.shutdown()
            for h in (h1, h2):
                try:
                    driver.destroy_task(h.config.id, force=True)
                except Exception:
                    pass

    def test_new_reference_cancels_scheduled_removal(
            self, fake_docker, tmp_path):
        state = os.environ["FAKE_DOCKER_STATE"]
        driver = DockerDriver(options={
            "docker.cleanup.image.delay": "0.4"})
        c1 = _cfg(tmp_path, name="a")
        driver.start_task(c1)
        driver.destroy_task(c1.id, force=True)
        # re-reference inside the removal window
        c2 = _cfg(tmp_path, name="b")
        driver.start_task(c2)
        time.sleep(0.8)
        try:
            assert not os.path.exists(
                os.path.join(state, "removed-busybox_1.36"))
        finally:
            driver.destroy_task(c2.id, force=True)
            driver.images.shutdown()

    def test_pull_uses_task_auth_credentials(self, fake_docker, tmp_path):
        state = os.environ["FAKE_DOCKER_STATE"]
        driver = DockerDriver()
        cfg = _cfg(tmp_path, image="registry.example.com/priv/app:1")
        cfg.driver_config["auth"] = {
            "username": "bob", "password": "hunter2"}
        h = driver.start_task(cfg)
        try:
            auth_file = os.path.join(
                state, "auth-registry.example.com_priv_app_1")
            assert os.path.exists(auth_file), \
                "pull did not carry credentials via --config"
            import base64
            with open(auth_file) as f:
                auths = json.load(f)["auths"]
            token = auths["registry.example.com"]["auth"]
            assert base64.b64decode(token).decode() == "bob:hunter2"
        finally:
            driver.destroy_task(cfg.id, force=True)
            driver.images.shutdown()

    def test_auth_chain_falls_back_to_config_file_then_helper(
            self, fake_docker, tmp_path):
        import base64

        # config-file backend
        cfg_file = tmp_path / "dockercfg.json"
        cfg_file.write_text(json.dumps({"auths": {
            "reg1.example.com": {
                "auth": base64.b64encode(b"alice:pw1").decode()}}}))
        driver = DockerDriver(options={
            "docker.auth.config": str(cfg_file),
            "docker.auth.helper": "test",
        })
        got = driver._resolve_registry_auth("reg1.example.com/app:1")
        assert got == {"username": "alice", "password": "pw1",
                       "server": "reg1.example.com"}

        # helper backend (no config-file entry for this registry)
        helper = tmp_path / "bin" / "docker-credential-test"
        helper.write_text(
            "#!/bin/sh\nread REG\n"
            "echo '{\"Username\":\"carol\",\"Secret\":\"pw2\","
            "\"ServerURL\":\"'$REG'\"}'\n")
        helper.chmod(helper.stat().st_mode | stat.S_IEXEC)
        got = driver._resolve_registry_auth("reg2.example.com/app:1")
        assert got == {"username": "carol", "password": "pw2",
                       "server": "reg2.example.com"}

        # task auth outranks both
        got = driver._resolve_registry_auth(
            "reg1.example.com/app:1", {"username": "dave",
                                       "password": "pw3"})
        assert got["username"] == "dave"
