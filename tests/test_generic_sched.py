"""GenericScheduler end-to-end tests via the Harness.

Modeled on reference scheduler/generic_sched_test.go (6,715 LoC Go);
these port its core scenarios: register, scale, update in-place vs
destructive, failed placement -> blocked eval, drain migration, node
down rescheduling, stopped job, spread/distinct-hosts placement, and
the system scheduler.
"""

import pytest

from nomad_tpu import mock, structs
from nomad_tpu.scheduler.testing import Harness
from nomad_tpu.structs import consts


def make_harness(n_nodes=10):
    h = Harness()
    nodes = [mock.node() for _ in range(n_nodes)]
    for n in nodes:
        h.state.upsert_node(n)
    return h, nodes


def run_eval(h, job, trigger=consts.EVAL_TRIGGER_JOB_REGISTER, sched=None):
    ev = mock.eval(
        job_id=job.id,
        namespace=job.namespace,
        type=job.type,
        triggered_by=trigger,
        priority=job.priority,
    )
    h.state.upsert_evals([ev])
    h.process(sched or job.type, ev)
    return ev


class TestServiceRegister:
    def test_place_all(self):
        # generic_sched_test.go TestServiceSched_JobRegister
        h, nodes = make_harness(10)
        job = mock.simple_job()
        h.state.upsert_job(job)
        run_eval(h, job)

        assert len(h.plans) == 1
        placed = h.placed_allocs()
        assert len(placed) == 10
        # names are unique indexes [0..9]
        names = sorted(a.name for a in placed)
        assert names == sorted(f"{job.id}.web[{i}]" for i in range(10))
        # allocs landed in state
        out = h.state.snapshot().allocs_by_job(job.namespace, job.id)
        assert len(out) == 10
        # resources recorded
        for a in placed:
            assert a.allocated_resources.tasks["web"].cpu.cpu_shares == 500
            assert a.metrics is not None
            assert a.metrics.nodes_evaluated > 0
        # eval marked complete
        assert h.evals[-1].status == consts.EVAL_STATUS_COMPLETE

    def test_anti_affinity_spreads_allocs(self):
        h, nodes = make_harness(5)
        job = mock.simple_job()
        job.task_groups[0].count = 5
        h.state.upsert_job(job)
        run_eval(h, job)
        placed = h.placed_allocs()
        assert len(placed) == 5
        # job anti-affinity should spread 5 allocs across 5 empty nodes
        assert len({a.node_id for a in placed}) == 5

    def test_ports_assigned(self):
        h, nodes = make_harness(3)
        job = mock.job()  # has 2 dynamic ports on the task network
        h.state.upsert_job(job)
        run_eval(h, job)
        placed = h.placed_allocs()
        assert len(placed) == 10
        for a in placed:
            nets = a.allocated_resources.tasks["web"].networks
            assert len(nets) == 1
            ports = [p.value for p in nets[0].dynamic_ports]
            assert len(ports) == 2
            assert all(20000 <= p <= 32000 for p in ports)
        # no two allocs on the same node share a port
        by_node = {}
        for a in placed:
            ports = [
                p.value
                for p in a.allocated_resources.tasks["web"].networks[0].dynamic_ports
            ]
            for p in ports:
                key = (a.node_id, p)
                assert key not in by_node, f"port collision {key}"
                by_node[key] = a.id

    def test_failed_placement_creates_blocked_eval(self):
        # generic_sched_test.go TestServiceSched_JobRegister_CreateBlockedEval
        h, _ = make_harness(2)
        job = mock.simple_job()
        job.task_groups[0].tasks[0].resources.cpu = 100000  # too big
        h.state.upsert_job(job)
        run_eval(h, job)
        assert len(h.placed_allocs()) == 0
        assert len(h.create_evals) == 1
        blocked = h.create_evals[0]
        assert blocked.status == consts.EVAL_STATUS_BLOCKED
        assert "web" in blocked.failed_tg_allocs
        ev = h.evals[-1]
        assert ev.status == consts.EVAL_STATUS_COMPLETE
        assert ev.queued_allocations.get("web") == 10

    def test_partial_placement(self):
        # only some fit -> blocked eval for the rest
        h, nodes = make_harness(2)
        job = mock.simple_job()
        job.task_groups[0].tasks[0].resources.cpu = 3000  # 1 per node fits
        h.state.upsert_job(job)
        run_eval(h, job)
        placed = h.placed_allocs()
        assert len(placed) == 2
        assert len(h.create_evals) == 1
        assert h.evals[-1].queued_allocations.get("web") == 8

    def test_no_nodes(self):
        h = Harness()
        job = mock.simple_job()
        h.state.upsert_job(job)
        run_eval(h, job)
        assert len(h.placed_allocs()) == 0
        assert len(h.create_evals) == 1

    def test_constraint_filters_nodes(self):
        h, nodes = make_harness(4)
        windows = mock.node()
        windows.attributes["kernel.name"] = "windows"
        windows.compute_class()
        h.state.upsert_node(windows)
        job = mock.job()  # constrained to kernel.name = linux
        job.task_groups[0].tasks[0].resources.networks = []
        h.state.upsert_job(job)
        run_eval(h, job)
        placed = h.placed_allocs()
        assert len(placed) == 10
        assert windows.id not in {a.node_id for a in placed}


class TestScaling:
    def _register(self, h, job):
        h.state.upsert_job(job)
        run_eval(h, job)

    def test_scale_up(self):
        h, _ = make_harness(10)
        job = mock.simple_job()
        self._register(h, job)
        assert len(h.placed_allocs()) == 10

        job2 = job.copy()
        job2.task_groups[0].count = 15
        h.state.upsert_job(job2)
        run_eval(h, job2, trigger=consts.EVAL_TRIGGER_SCALING)
        # second plan: 10 in-place updates (job version bumped) plus
        # exactly 5 fresh placements with the next indexes
        plan_allocs = [
            a for allocs in h.plans[-1].node_allocation.values() for a in allocs
        ]
        new = [a for a in plan_allocs if a.index() >= 10]
        assert len(plan_allocs) == 15
        assert sorted(a.index() for a in new) == [10, 11, 12, 13, 14]

    def test_scale_down(self):
        h, _ = make_harness(10)
        job = mock.simple_job()
        self._register(h, job)
        job2 = job.copy()
        job2.task_groups[0].count = 3
        h.state.upsert_job(job2)
        run_eval(h, job2, trigger=consts.EVAL_TRIGGER_SCALING)
        stops = [a for allocs in h.plans[-1].node_update.values() for a in allocs]
        assert len(stops) == 7
        # highest indexes stopped first
        stopped_idx = sorted(a.index() for a in stops)
        assert stopped_idx == list(range(3, 10))

    def test_stop_job(self):
        h, _ = make_harness(5)
        job = mock.simple_job()
        self._register(h, job)
        job2 = job.copy()
        job2.stop = True
        h.state.upsert_job(job2)
        run_eval(h, job2, trigger=consts.EVAL_TRIGGER_JOB_DEREGISTER)
        stops = [a for allocs in h.plans[-1].node_update.values() for a in allocs]
        assert len(stops) == 10


class TestUpdates:
    def test_inplace_update(self):
        # generic_sched_test.go TestServiceSched_JobModify_InPlace
        h, _ = make_harness(10)
        job = mock.simple_job()
        h.state.upsert_job(job)
        run_eval(h, job)

        job2 = job.copy()
        job2.task_groups[0].meta = {"new": "meta"}  # non-destructive change
        h.state.upsert_job(job2)
        run_eval(h, job2)
        plan = h.plans[-1]
        # in-place: allocs re-appended, nothing stopped
        assert not plan.node_update
        updated = [a for allocs in plan.node_allocation.values() for a in allocs]
        assert len(updated) == 10

    def test_destructive_update(self):
        # driver change forces destructive update
        h, _ = make_harness(10)
        job = mock.simple_job()
        h.state.upsert_job(job)
        run_eval(h, job)

        job2 = job.copy()
        job2.task_groups[0].tasks[0].config = {"command": "/bin/other"}
        h.state.upsert_job(job2)
        run_eval(h, job2)
        plan = h.plans[-1]
        stops = [a for allocs in plan.node_update.values() for a in allocs]
        places = [a for allocs in plan.node_allocation.values() for a in allocs]
        # no update stanza -> all 10 replaced at once
        assert len(stops) == 10
        assert len(places) == 10

    def test_destructive_update_respects_max_parallel(self):
        h, _ = make_harness(10)
        job = mock.simple_job()
        h.state.upsert_job(job)
        run_eval(h, job)
        # mark existing allocs healthy/running so update pacing applies
        snap = h.state.snapshot()
        updates = []
        for a in snap.allocs_by_job(job.namespace, job.id):
            b = a.copy_skip_job()
            b.client_status = consts.ALLOC_CLIENT_RUNNING
            updates.append(b)
        h.state.upsert_allocs(updates)

        job2 = job.copy()
        job2.task_groups[0].tasks[0].config = {"command": "/bin/other"}
        job2.task_groups[0].update = structs.UpdateStrategy(max_parallel=3)
        h.state.upsert_job(job2)
        run_eval(h, job2)
        plan = h.plans[-1]
        places = [a for allocs in plan.node_allocation.values() for a in allocs]
        assert len(places) == 3  # limited by max_parallel
        assert plan.deployment is not None


class TestNodeFailures:
    def test_node_drain_migrates(self):
        # generic_sched_test.go TestServiceSched_NodeDrain
        h, nodes = make_harness(4)
        job = mock.simple_job()
        job.task_groups[0].count = 4
        h.state.upsert_job(job)
        run_eval(h, job)
        victim_alloc = h.placed_allocs()[0]
        victim_node = victim_alloc.node_id

        h.state.update_node_drain(victim_node, True)
        # drainer marks allocs for migration
        snap = h.state.snapshot()
        migrating = []
        for a in snap.allocs_by_node(victim_node):
            b = a.copy_skip_job()
            b.desired_transition = structs.DesiredTransition(migrate=True)
            migrating.append(b)
        h.state.upsert_allocs(migrating)

        run_eval(h, job, trigger=consts.EVAL_TRIGGER_NODE_DRAIN)
        plan = h.plans[-1]
        stops = [a for allocs in plan.node_update.values() for a in allocs]
        places = [a for allocs in plan.node_allocation.values() for a in allocs]
        assert len(stops) == len(migrating)
        assert len(places) == len(migrating)
        assert all(a.node_id != victim_node for a in places)

    def test_node_down_reschedules(self):
        h, nodes = make_harness(4)
        job = mock.simple_job()
        job.task_groups[0].count = 4
        h.state.upsert_job(job)
        run_eval(h, job)
        victim = h.placed_allocs()[0].node_id
        n_on_victim = len(h.state.snapshot().allocs_by_node(victim))
        h.state.update_node_status(victim, consts.NODE_STATUS_DOWN)

        run_eval(h, job, trigger=consts.EVAL_TRIGGER_NODE_UPDATE)
        plan = h.plans[-1]
        stops = [a for allocs in plan.node_update.values() for a in allocs]
        places = [a for allocs in plan.node_allocation.values() for a in allocs]
        assert len(stops) == n_on_victim
        assert all(a.client_status == consts.ALLOC_CLIENT_LOST for a in stops)
        assert len(places) == n_on_victim
        assert all(a.node_id != victim for a in places)


class TestRescheduling:
    def test_failed_alloc_rescheduled_with_penalty(self):
        h, nodes = make_harness(3)
        job = mock.simple_job()
        job.task_groups[0].count = 1
        job.task_groups[0].reschedule_policy = structs.ReschedulePolicy(
            attempts=3, interval_s=3600, delay_s=0, delay_function="constant"
        )
        h.state.upsert_job(job)
        run_eval(h, job)
        orig = h.placed_allocs()[0]
        orig_node = orig.node_id

        failed = orig.copy_skip_job()
        failed.client_status = consts.ALLOC_CLIENT_FAILED
        import time

        failed.modify_time_ns = int(time.time() * 1e9)
        h.state.upsert_allocs([failed])

        run_eval(h, job, trigger=consts.EVAL_TRIGGER_RETRY_FAILED_ALLOC)
        plan = h.plans[-1]
        places = [a for allocs in plan.node_allocation.values() for a in allocs]
        assert len(places) == 1
        new = places[0]
        # rescheduled elsewhere (penalty) with tracker chain
        assert new.node_id != orig_node
        assert new.previous_allocation == failed.id
        assert new.reschedule_tracker is not None
        assert new.reschedule_tracker.events[0].prev_node_id == orig_node

    def test_delayed_reschedule_creates_followup(self):
        h, nodes = make_harness(3)
        job = mock.simple_job()
        job.task_groups[0].count = 1
        job.task_groups[0].reschedule_policy = structs.ReschedulePolicy(
            attempts=3, interval_s=3600, delay_s=300, delay_function="constant"
        )
        h.state.upsert_job(job)
        run_eval(h, job)
        orig = h.placed_allocs()[0]
        failed = orig.copy_skip_job()
        failed.client_status = consts.ALLOC_CLIENT_FAILED
        import time

        failed.modify_time_ns = int(time.time() * 1e9)
        h.state.upsert_allocs([failed])

        run_eval(h, job, trigger=consts.EVAL_TRIGGER_RETRY_FAILED_ALLOC)
        # a WaitUntil follow-up eval was created instead of placing now
        followups = [e for e in h.create_evals if e.wait_until_s > 0]
        assert len(followups) == 1
        assert followups[0].wait_until_s > time.time() + 250


class TestSpreadAndDistinct:
    def test_spread_stanza_across_dcs(self):
        h = Harness()
        for dc, cnt in (("dc1", 4), ("dc2", 4)):
            for _ in range(cnt):
                h.state.upsert_node(mock.node(datacenter=dc))
        job = mock.simple_job()
        job.datacenters = ["dc1", "dc2"]
        job.task_groups[0].count = 6
        job.task_groups[0].spreads = [
            structs.Spread(
                attribute="${node.datacenter}",
                weight=100,
                spread_target=[
                    structs.SpreadTarget(value="dc1", percent=50),
                    structs.SpreadTarget(value="dc2", percent=50),
                ],
            )
        ]
        h.state.upsert_job(job)
        run_eval(h, job)
        placed = h.placed_allocs()
        assert len(placed) == 6
        snap = h.state.snapshot()
        by_dc = {}
        for a in placed:
            dc = snap.node_by_id(a.node_id).datacenter
            by_dc[dc] = by_dc.get(dc, 0) + 1
        assert by_dc == {"dc1": 3, "dc2": 3}

    def test_distinct_hosts(self):
        h, _ = make_harness(4)
        job = mock.simple_job()
        job.constraints = [structs.Constraint(operand=consts.CONSTRAINT_DISTINCT_HOSTS)]
        job.task_groups[0].count = 6
        h.state.upsert_job(job)
        run_eval(h, job)
        placed = h.placed_allocs()
        # only 4 nodes -> only 4 placements, 2 blocked
        assert len(placed) == 4
        assert len({a.node_id for a in placed}) == 4
        assert len(h.create_evals) == 1

    def test_affinity_prefers_matching_nodes(self):
        h = Harness()
        big = [mock.node() for _ in range(2)]
        for n in big:
            n.attributes["machine.class"] = "big"
            n.compute_class()
            h.state.upsert_node(n)
        for _ in range(4):
            n = mock.node()
            n.attributes["machine.class"] = "small"
            n.compute_class()
            h.state.upsert_node(n)
        job = mock.simple_job()
        job.task_groups[0].count = 2
        job.affinities = [
            structs.Affinity(
                ltarget="${attr.machine.class}", rtarget="big", operand="=",
                weight=100,
            )
        ]
        h.state.upsert_job(job)
        run_eval(h, job)
        placed = h.placed_allocs()
        big_ids = {n.id for n in big}
        assert len(placed) == 2
        assert all(a.node_id in big_ids for a in placed)


class TestSystemSched:
    def test_system_places_on_all_nodes(self):
        # scheduler_system_test.go TestSystemSched_JobRegister
        h, nodes = make_harness(6)
        job = mock.system_job()
        h.state.upsert_job(job)
        run_eval(h, job)
        placed = h.placed_allocs()
        assert len(placed) == 6
        assert len({a.node_id for a in placed}) == 6

    def test_system_skips_ineligible(self):
        h, nodes = make_harness(4)
        h.state.update_node_drain(nodes[0].id, True)
        h.state.update_node_status(nodes[1].id, consts.NODE_STATUS_DOWN)
        job = mock.system_job()
        h.state.upsert_job(job)
        run_eval(h, job)
        placed = h.placed_allocs()
        assert len(placed) == 2

    def test_system_stops_on_drained(self):
        h, nodes = make_harness(3)
        job = mock.system_job()
        h.state.upsert_job(job)
        run_eval(h, job)
        assert len(h.placed_allocs()) == 3
        h.state.update_node_drain(nodes[0].id, True)
        run_eval(h, job, trigger=consts.EVAL_TRIGGER_NODE_UPDATE)
        stops = [a for allocs in h.plans[-1].node_update.values() for a in allocs]
        assert len(stops) == 1
        assert stops[0].node_id == nodes[0].id


class TestPlanRejection:
    def test_reject_then_blocked(self):
        h, _ = make_harness(2)
        h.reject_plan = True
        job = mock.simple_job()
        h.state.upsert_job(job)
        run_eval(h, job)
        # all attempts rejected -> failed status + blocked eval
        assert h.evals[-1].status == consts.EVAL_STATUS_FAILED
        assert any(
            e.triggered_by == consts.EVAL_TRIGGER_MAX_PLAN_ATTEMPTS
            for e in h.create_evals
        )


class TestLeanStaticPorts:
    """ISSUE 10: static-port lean asks skip the per-slot _NodeAssigner
    (scaffold.lean_ports) — placement proves port freedom from the
    kernel conflict plane + the usage index's live port bitmaps."""

    def _port_job(self, port=8080, count=3):
        job = mock.simple_job()
        tg = job.task_groups[0]
        tg.count = count
        tg.networks = [structs.NetworkResource(
            mode="host",
            reserved_ports=[structs.Port(label="http", value=port)],
        )]
        return job

    def test_static_port_job_is_lean_ports(self):
        from nomad_tpu.scheduler.scaffold import scaffold_for

        job = self._port_job()
        s = scaffold_for(job, job.task_groups[0])
        assert s.lean_ports
        assert not s.lean_assign
        assert s.static_port_mask == 1 << 8080

    def test_placement_skips_assigner(self, monkeypatch):
        from nomad_tpu.scheduler import stack as stack_mod

        calls = []
        orig = stack_mod._NodeAssigner.assign

        def spy(self, tg, score):
            calls.append(tg.name)
            return orig(self, tg, score)

        monkeypatch.setattr(stack_mod._NodeAssigner, "assign", spy)
        h, nodes = make_harness(5)
        job = self._port_job(count=3)
        h.state.upsert_job(job)
        run_eval(h, job)
        placed = h.placed_allocs()
        assert len(placed) == 3
        assert not calls, "static-port ask walked the exact assigner"
        # each placement landed on its own node (one port per node)
        assert len({a.node_id for a in placed}) == 3
        for a in placed:
            shared = a.allocated_resources.shared
            assert [p.value for p in shared.ports] == [8080]
            assert shared.networks and \
                [p.value for p in shared.networks[0].reserved_ports] == [8080]
            # the tasks skeleton is the (job, tg)-shared one
            assert a.allocated_resources.tasks["web"].cpu.cpu_shares == 500

    def test_live_port_occupancy_respected(self):
        """A second job asking the same static port must avoid nodes
        whose LIVE allocs hold it (usage-index bitmaps feed the kernel
        conflict plane and the slot check)."""
        h, nodes = make_harness(4)
        job1 = self._port_job(count=2)
        h.state.upsert_job(job1)
        run_eval(h, job1)
        first_nodes = {a.node_id for a in h.placed_allocs()}
        assert len(first_nodes) == 2

        job2 = self._port_job(count=2)
        h.state.upsert_job(job2)
        run_eval(h, job2)
        placed2 = [a for a in h.placed_allocs() if a.job_id == job2.id]
        assert len(placed2) == 2
        second_nodes = {a.node_id for a in placed2}
        assert not (first_nodes & second_nodes), \
            "same static port double-placed on a node"

    def test_port_exhaustion_fails_placement(self):
        """More asks than nodes: the surplus slot must fail (blocked
        eval), not double-claim a port."""
        h, nodes = make_harness(2)
        job = self._port_job(count=3)
        h.state.upsert_job(job)
        run_eval(h, job)
        placed = h.placed_allocs()
        assert len(placed) == 2
        assert len({a.node_id for a in placed}) == 2
        assert h.create_evals, "surplus ask should create a blocked eval"
