"""Durability-plane unit tests (raft/wal.py, ISSUE 13): CRC framing,
torn-tail vs corruption semantics, segment rotation + post-compaction
deletion, the stable store's monotone hard-state writes, snapshot
keep-last-2 with CRC fallback, fsync policies, and the fail-stop
fault seams."""

import os
import threading

import pytest

from nomad_tpu.raft.log import LogEntry
from nomad_tpu.raft.wal import (
    DurableLogStore,
    SnapshotStore,
    StableStore,
    WalCorruptionError,
    WriteAheadLog,
    frame,
    replay_records,
    wal_stats,
)
from nomad_tpu.utils import faultpoints


@pytest.fixture(autouse=True)
def _clean_plane():
    faultpoints.reset()
    yield
    faultpoints.reset()


def _entries(store):
    return [(e.index, e.term, e.data)
            for e in store.entries_from(store.base_index() + 1, 10_000)]


def _fill(path, n=12, term=1):
    log = DurableLogStore(path)
    for i in range(1, n + 1):
        log.append(LogEntry(index=i, term=term, data=("op", i)))
    log.sync()
    return log


def _segments(path):
    return sorted(f for f in os.listdir(path) if f.endswith(".seg"))


class TestWalRoundtrip:
    def test_append_truncate_compact_replay_bit_identical(self, tmp_path):
        d = str(tmp_path / "wal")
        log = _fill(d, 10)
        log.truncate_from(9)                # conflict resolution
        log.append(LogEntry(index=9, term=2, data=("op", "ninth")))
        log.compact_to(4, 1)
        log.sync()
        before = (_entries(log), log.base_index(), log.last_index(),
                  log.last_term())
        log.close()

        again = DurableLogStore(d)
        assert (_entries(again), again.base_index(), again.last_index(),
                again.last_term()) == before
        assert again.replayed_entries == len(before[0])
        again.close()

    def test_torn_tail_truncates_to_clean_prefix(self, tmp_path):
        d = str(tmp_path / "wal")
        log = _fill(d, 8)
        log.close()
        seg = os.path.join(d, _segments(d)[-1])
        size = os.path.getsize(seg)
        torn0 = wal_stats.snapshot()["torn_truncations"]
        with open(seg, "r+b") as f:
            f.truncate(size - 7)            # half a frame at the tail
        again = DurableLogStore(d)
        # a clean PREFIX: entries 1..7 intact, 8 gone, nothing mangled
        assert _entries(again) == [(i, 1, ("op", i)) for i in range(1, 8)]
        assert wal_stats.snapshot()["torn_truncations"] == torn0 + 1
        # the truncated file appends cleanly again
        again.append(LogEntry(index=8, term=1, data=("op", "redo")))
        again.sync()
        again.close()
        final = DurableLogStore(d)
        assert _entries(final)[-1] == (8, 1, ("op", "redo"))
        final.close()

    def test_midfile_corruption_is_loud_never_silent(self, tmp_path):
        d = str(tmp_path / "wal")
        log = _fill(d, 8)
        log.close()
        seg = os.path.join(d, _segments(d)[-1])
        # flip one byte in the FIRST frame: valid frames follow, so
        # this is corruption, not a torn tail — recovery must refuse
        with open(seg, "r+b") as f:
            f.seek(12)
            byte = f.read(1)
            f.seek(12)
            f.write(bytes([byte[0] ^ 0x40]))
        with pytest.raises(WalCorruptionError):
            DurableLogStore(d)

    def test_sealed_segment_damage_is_loud(self, tmp_path):
        d = str(tmp_path / "wal")
        log = DurableLogStore(d, segment_max_bytes=128)
        for i in range(1, 12):
            log.append(LogEntry(index=i, term=1, data=("op", i)))
        log.sync()
        log.close()
        segs = _segments(d)
        assert len(segs) > 2
        # cut the TAIL of a SEALED (non-newest) segment: rotation
        # fsynced it whole, so a short read there is corruption
        sealed = os.path.join(d, segs[0])
        with open(sealed, "r+b") as f:
            f.truncate(os.path.getsize(sealed) - 3)
        with pytest.raises(WalCorruptionError):
            DurableLogStore(d)

    def test_rotation_and_deletion_after_compaction(self, tmp_path):
        d = str(tmp_path / "wal")
        log = DurableLogStore(d, segment_max_bytes=128)
        for i in range(1, 30):
            log.append(LogEntry(index=i, term=1, data=("op", i)))
        log.sync()
        n_before = len(_segments(d))
        assert n_before > 3
        log.compact_to(25, 1)
        # sealed segments wholly below the snapshot are gone
        assert len(_segments(d)) < n_before
        log.close()
        again = DurableLogStore(d)
        assert again.base_index() == 25
        assert _entries(again) == [(i, 1, ("op", i)) for i in range(26, 30)]
        again.close()

    def test_replay_is_index_keyed_across_deleted_segments(self, tmp_path):
        """Regression: after compaction deletes segments, the retained
        stream starts mid-log; a truncate record recorded BEFORE the
        retained compact record must still aim at the right entries
        (positional replay through the live arithmetic mis-aimed it)."""
        records = [("entry", i, 1, "command", ("op", i))
                   for i in range(40, 50)]
        records.append(("truncate", 48))
        records.append(("entry", 48, 2, "command", ("op", "new48")))
        records.append(("compact", 45, 1))
        base, term, entries = replay_records(records)
        assert (base, term) == (45, 1)
        assert [(e.index, e.term) for e in entries] == [
            (46, 1), (47, 1), (48, 2)]

    def test_torn_write_fault_fail_stops_and_recovers(self, tmp_path):
        d = str(tmp_path / "wal")
        log = _fill(d, 3)
        faultpoints.arm({"wal.frame.torn": {"kind": "error", "nth": 1}})
        with pytest.raises(faultpoints.FaultError):
            log.append(LogEntry(index=4, term=1, data=("op", 4)))
        assert log.wal_failed
        # fail-stop: nothing may be journaled after a torn frame
        with pytest.raises(WalCorruptionError):
            log.append(LogEntry(index=5, term=1, data=("op", 5)))
        with pytest.raises(WalCorruptionError):
            log.sync()
        log.close()
        faultpoints.disarm()
        # recovery truncates the half-written frame: clean 1..3 prefix
        again = DurableLogStore(d)
        assert _entries(again) == [(i, 1, ("op", i)) for i in (1, 2, 3)]
        again.close()

    def test_concurrent_appends_sync_group_coalesced(self, tmp_path):
        d = str(tmp_path / "wal")
        log = DurableLogStore(d, fsync_policy="batch")
        idx_lock = threading.Lock()
        next_idx = [0]
        errors = []

        def writer(k):
            try:
                for _ in range(20):
                    # index assignment + append are one atomic step,
                    # like the raft caller (which does both under its
                    # lock) — the journal must stay ascending; only
                    # the SYNCS race, which is the point
                    with idx_lock:
                        next_idx[0] += 1
                        i = next_idx[0]
                        log.append(LogEntry(index=i, term=1,
                                            data=("op", i)))
                    log.sync()
            except Exception as e:              # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=writer, args=(k,))
                   for k in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=20)
        assert not errors
        log.close()
        again = DurableLogStore(d)
        assert again.last_index() == 80
        assert again.replayed_entries == 80
        again.close()

    def test_always_policy_is_durable_per_record(self, tmp_path):
        d = str(tmp_path / "wal")
        f0 = wal_stats.snapshot()["fsyncs"]
        log = DurableLogStore(d, fsync_policy="always")
        log.append(LogEntry(index=1, term=1, data=("op", 1)))
        assert wal_stats.snapshot()["fsyncs"] > f0
        log.close()
        with pytest.raises(ValueError):
            WriteAheadLog(str(tmp_path / "bad"), fsync_policy="sometimes")


class TestStableStore:
    def test_roundtrip_and_noop_fast_path(self, tmp_path):
        d = str(tmp_path)
        ss = StableStore(d)
        assert ss.load() == (0, None)
        ss.put(3, "cand-a")
        f0 = wal_stats.snapshot()["fsyncs"]
        ss.put(3, "cand-a")                 # unchanged: free
        assert wal_stats.snapshot()["fsyncs"] == f0
        assert StableStore(d).load() == (3, "cand-a")

    def test_monotone_never_regresses(self, tmp_path):
        d = str(tmp_path)
        ss = StableStore(d)
        ss.put(5, "cand-b")
        ss.put(4, "cand-a")                 # stale racer: ignored
        ss.put(5, None)                     # a vote is never un-cast
        assert StableStore(d).load() == (5, "cand-b")
        ss.put(6, None)                     # a NEW term clears the vote
        assert StableStore(d).load() == (6, None)

    def test_corrupt_stable_is_loud(self, tmp_path):
        d = str(tmp_path)
        StableStore(d).put(7, "cand-c")
        with open(os.path.join(d, "stable"), "r+b") as f:
            f.seek(9)
            f.write(b"\xff")
        with pytest.raises(WalCorruptionError):
            StableStore(d).load()


class TestSnapshotStore:
    def test_keep_last_two_and_newest_wins(self, tmp_path):
        d = str(tmp_path)
        sn = SnapshotStore(d)
        for idx, data in ((10, b"ten"), (20, b"twenty"), (30, b"thirty")):
            sn.save(idx, 1, data)
        files = [f for f in os.listdir(d) if f.endswith(".snap")]
        assert len(files) == 2              # keep-last-2
        assert sn.load_newest() == (30, 1, b"thirty")

    def test_corrupt_newest_falls_back_to_older(self, tmp_path):
        d = str(tmp_path)
        sn = SnapshotStore(d)
        sn.save(10, 1, b"older")
        newest = sn.save(20, 2, b"newer")
        size = os.path.getsize(newest)
        with open(newest, "r+b") as f:
            f.seek(size - 1)
            last = f.read(1)
            f.seek(size - 1)
            f.write(bytes([last[0] ^ 0xFF]))
        assert sn.load_newest() == (10, 1, b"older")

    def test_kill_mid_write_leaves_only_ignored_tmp(self, tmp_path):
        d = str(tmp_path)
        sn = SnapshotStore(d)
        sn.save(10, 1, b"good")
        faultpoints.arm(
            {"wal.snapshot.write": {"kind": "error", "nth": 1}})
        with pytest.raises(faultpoints.FaultError):
            sn.save(20, 1, b"never-lands")
        faultpoints.disarm()
        # the failed write never became a .snap: recovery sees 'good'
        assert sn.load_newest() == (10, 1, b"good")


class TestTornTailFuzzMini:
    def test_forty_seeds_never_silently_diverge(self):
        """Tier-1 slice of the ≥200-seed stress fuzz (ISSUE 13
        acceptance): every mutated recovery is a clean prefix or a
        loud WalCorruptionError — zero silent divergences."""
        import os as _os
        import sys as _sys

        _sys.path.insert(0, _os.path.join(_os.path.dirname(
            _os.path.dirname(_os.path.abspath(__file__))), "bench"))
        import trace_report

        r = trace_report.run_torn_tail_fuzz(seeds=40, entries=60)
        assert r["silent_divergences"] == 0, r
        assert r["clean_prefix"] + r["loud_corruption"] == 40
        assert r["clean_prefix"] > 0 and r["loud_corruption"] > 0
