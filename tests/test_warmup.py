"""ISSUE 2: AOT kernel warmup, adaptive wave coalescing, feature-key
canonicalization, wave telemetry, and the donation-warning fix.

The acceptance surface, CI-gated on the CPU backend:
- a steady-state eval loop after manifest warmup records ZERO jit
  cache misses (the compile share of the live path's wall goes to the
  warmup thread instead);
- the adaptive coalescer fires partial waves at its deadline instead
  of parking forever behind members that never arrive;
- plan submission yields the wave rendezvous (pipelining), so a wave
  can fire while another member blocks on the applier;
- near-identical feature sets canonicalize onto one compiled variant;
- ``make_preemption_apply_loop`` no longer asks XLA to donate buffers
  it cannot alias (the warning is promoted to an error in conftest).
"""

import json
import os
import sys
import threading
import time
import warnings

import numpy as np
import pytest

from nomad_tpu import mock, telemetry
from nomad_tpu.ops import warmup as kernel_warmup
from nomad_tpu.telemetry.kernel_profile import profiler

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "bench"))


@pytest.fixture()
def clean_telemetry():
    telemetry.enable()
    telemetry.reset()
    yield
    telemetry.disable()
    telemetry.reset()


def _register_jobs(server, n_jobs, count=3):
    jobs = []
    for _ in range(n_jobs):
        j = mock.simple_job()
        j.task_groups[0].count = count
        jobs.append(j)
        server.job_register(j)
    return jobs


def _drain_worker(server, batch_size=8):
    """Deterministic eval loop: a manual batching worker drains the
    broker (jobs registered first, so batches are full-size)."""
    from nomad_tpu.server.worker import Worker

    w = Worker(server, 0, batch_size=batch_size)
    while w.run_once(timeout=0.0):
        pass
    return w


def _clear_kernel_caches():
    from nomad_tpu.ops.kernel import (
        place_taskgroup_jit,
        place_taskgroup_topk_jit,
        place_taskgroups_joint_jit,
    )

    from nomad_tpu.ops.pallas_kernel import fused_wave_place_jit

    place_taskgroups_joint_jit.clear_cache()
    place_taskgroup_topk_jit.clear_cache()
    place_taskgroup_jit.clear_cache()
    fused_wave_place_jit.clear_cache()


class TestManifest:
    def test_roundtrip_and_merge(self, tmp_path):
        e1 = {"kernel": "joint", "wave": 16, "steps": 64, "nodes": 64,
              "shared": True, "neutral_shared": False,
              "features": {"n_spreads": 0, "with_topk": True}}
        e2 = {"kernel": "single_topk", "nodes": 64, "steps": 16,
              "features": {"n_spreads": 0}}
        path = str(tmp_path / "warmup.json")
        assert kernel_warmup.save_manifest([e1], path) == 1
        # merge unions and dedupes
        assert kernel_warmup.save_manifest([e1, e2], path) == 2
        got = kernel_warmup.load_manifest(path)
        assert len(got) == 2
        data = json.loads(open(path).read())
        assert data["version"] == kernel_warmup.MANIFEST_VERSION

    def test_expand_lattice_covers_waves_layouts_and_singles(self):
        e = {"kernel": "joint", "wave": 32, "steps": 512, "nodes": 64,
             "shared": True, "neutral_shared": False,
             "features": {"n_spreads": 0}}
        out = kernel_warmup.expand_lattice([e])
        joint = [x for x in out if x["kernel"] == "joint"]
        waves = sorted({x["wave"] for x in joint})
        assert waves == [1, 4, 16, 32]
        # observed per-member step count (512/32 = 16) is preserved at
        # every wave bucket, and the follow-up-eval floor bucket (8)
        # rides along
        steps_at = lambda w: {x["steps"] for x in joint  # noqa: E731
                              if x["wave"] == w}
        assert {256, 128} <= steps_at(16)
        assert {64, 32} <= steps_at(4)
        assert {16, 8} <= steps_at(1)
        # 1-waves force the fully-shared layout (a lone member shares
        # every field with itself); multi-member waves also cover the
        # all-stacked retry layout
        assert all(x["shared"] and x["neutral_shared"]
                   for x in joint if x["wave"] == 1)
        assert any(x["wave"] == 16 and not x["shared"]
                   and not x["neutral_shared"] for x in joint)
        # the rescheduling feature variant (penalties + preferred) is
        # covered alongside the observed one
        assert any(x["features"].get("with_step_penalties")
                   and x["features"].get("with_preferred")
                   for x in joint)
        # direct (1-eval batch) dispatch programs are covered too
        singles = {x["kernel"] for x in out if x["kernel"] != "joint"}
        assert singles == {"single_topk", "single_full"}
        assert {x["steps"] for x in out
                if x["kernel"] == "single_topk"} == {8, 16}

    def test_expand_lattice_up_to_max_wave(self):
        e = {"kernel": "joint", "wave": 4, "steps": 32, "nodes": 64,
             "shared": True, "neutral_shared": False,
             "features": {"n_spreads": 0}}
        out = kernel_warmup.expand_lattice([e], max_wave=32)
        waves = sorted({x["wave"] for x in out
                        if x["kernel"] == "joint"})
        assert waves == [1, 4, 16, 32]

    def test_manifest_from_profiler_skips_sharded(self, clean_telemetry):
        from nomad_tpu.ops.kernel import LEAN_FEATURES

        profiler.call("joint", lambda *a: 0, (), (),
                      (16, 64, 64, True, False, LEAN_FEATURES))
        profiler.call("joint_sharded", lambda *a: 0, (), (),
                      (16, 64, 64, True, False, LEAN_FEATURES, ("d0",)))
        entries = kernel_warmup.manifest_from_profiler(profiler)
        assert [e["kernel"] for e in entries] == ["joint"]


class TestAOTWarmupSteadyState:
    def test_zero_jit_misses_after_manifest_warmup(
            self, tmp_path, clean_telemetry):
        """The tentpole claim: record a burst's bucket keys, clear the
        jit caches (a fresh process), warm from the manifest, and a
        steady-state eval loop compiles NOTHING."""
        from nomad_tpu.server.server import Server, ServerConfig

        # adaptive deadline off for THIS test: wave sizes must be
        # deterministic so the recording run observes exactly the
        # buckets the steady-state run launches (deadline-fired
        # partial waves are covered by TestAdaptiveCoalescer and the
        # lattice expansion)
        server = Server(ServerConfig(num_workers=0, heartbeat_ttl=3600.0,
                                     coalesce_adaptive=False))
        server.start()
        try:
            for _ in range(40):
                server.node_register(mock.node())
            jobs = _register_jobs(server, 8)
            _drain_worker(server)
            snap = server.state.snapshot()
            placed = sum(len(snap.allocs_by_job(j.namespace, j.id))
                         for j in jobs)
            assert placed == 24

            path = str(tmp_path / "warmup.json")
            entries = kernel_warmup.manifest_from_profiler(profiler)
            assert entries, "profiler recorded no bucket keys"
            kernel_warmup.save_manifest(entries, path)

            # fresh-process simulation: drop every compiled program
            _clear_kernel_caches()
            profiler.reset()
            compiled, failed = kernel_warmup.warmup_from_manifest(path)
            assert compiled >= len(entries)
            assert failed == 0

            profiler.reset()
            jobs2 = _register_jobs(server, 8)
            _drain_worker(server)
            snap = server.state.snapshot()
            placed2 = sum(len(snap.allocs_by_job(j.namespace, j.id))
                          for j in jobs2)
            assert placed2 == 24
            s = profiler.summary()
            assert s["Launches"] >= 1
            assert s["JitCacheMisses"] == 0, s["PerKey"]
        finally:
            server.shutdown()

    def test_server_persists_and_warms_manifest(
            self, tmp_path, clean_telemetry):
        """Lifecycle: a server with a manifest path persists observed
        keys on shutdown; the next server start warms them (background
        thread)."""
        from nomad_tpu.server.server import Server, ServerConfig

        path = str(tmp_path / "warmup.json")
        server = Server(ServerConfig(
            num_workers=0, heartbeat_ttl=3600.0,
            warmup_manifest_path=path))
        server.start()
        try:
            for _ in range(20):
                server.node_register(mock.node())
            _register_jobs(server, 4)
            _drain_worker(server, batch_size=4)
        finally:
            server.shutdown()
        assert os.path.exists(path)
        assert kernel_warmup.load_manifest(path)

        server2 = Server(ServerConfig(
            num_workers=0, heartbeat_ttl=3600.0,
            warmup_manifest_path=path))
        server2.start()
        try:
            t = server2._warmup_thread
            assert t is not None
            t.join(timeout=120)
            assert not t.is_alive()
        finally:
            server2.shutdown()


class TestConfigKnobs:
    def test_agent_config_file_parses_warmup_and_window(self, tmp_path):
        from nomad_tpu.api.config_file import load_config_files

        p = tmp_path / "agent.hcl"
        p.write_text('''
server {
  enabled                = true
  kernel_warmup          = true
  warmup_manifest        = "/var/lib/nomad_tpu/warmup.json"
  coalesce_adaptive      = false
  coalesce_window_min_ms = 2
  coalesce_window_max_ms = 80
}
''')
        cfg = load_config_files([str(p)])
        assert cfg.kernel_warmup is True
        assert cfg.warmup_manifest == "/var/lib/nomad_tpu/warmup.json"
        assert cfg.coalesce_adaptive is False
        assert cfg.coalesce_window_min_ms == 2.0
        assert cfg.coalesce_window_max_ms == 80.0

    def test_knobs_thread_through_to_server_config(self, tmp_path):
        from nomad_tpu.api.agent import Agent, AgentConfig

        a = Agent(AgentConfig(
            serf_enabled=False, kernel_warmup=False,
            warmup_manifest=str(tmp_path / "m.json"),
            coalesce_window_min_ms=3.0, coalesce_window_max_ms=77.0))
        a.start()
        try:
            sc = a.server.config
            assert sc.kernel_warmup is False
            assert sc.warmup_manifest_path.endswith("m.json")
            assert sc.coalesce_window_min_ms == 3.0
            assert sc.coalesce_window_max_ms == 77.0
        finally:
            a.shutdown()


class TestAdaptiveCoalescer:
    def test_partial_wave_fires_at_deadline(self, monkeypatch):
        """Two of four participants park; the wave must fire at the
        window deadline with just those two — no waiting on members
        that never arrive."""
        from nomad_tpu.parallel import coalesce

        fired = []

        def stub_launch_wave(kins, k_steps, features, mesh=None):
            fired.append(len(kins))
            return [object() for _ in kins]

        monkeypatch.setattr(coalesce, "launch_wave", stub_launch_wave)
        # deadlines only arm once a wave-latency sample exists (a cold
        # process parks for full waves); seed one for the test
        monkeypatch.setattr(coalesce, "wave_latency_ewma",
                            coalesce._LatencyEWMA())
        coalesce.wave_latency_ewma.update(0.02)

        class KinStub:
            class _Arr:
                shape = (8,)
            cap_cpu = _Arr()

        c = coalesce.LaunchCoalescer(4, window_min_s=0.01,
                                     window_max_s=0.01)
        results = {}

        def member(i):
            results[i] = c.launch(KinStub(), 1, None)

        t0 = time.perf_counter()
        threads = [threading.Thread(target=member, args=(i,))
                   for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        dt = time.perf_counter() - t0
        assert fired == [2]
        assert results[0] is not None and results[1] is not None
        assert dt < 5.0, "deadline never fired"
        assert c.deadline_launches == 1
        for _ in range(4):
            c.done()

    def test_cold_start_parks_for_full_waves(self, monkeypatch):
        """Without a wave-latency sample (cold process, first compiles
        in flight) deadlines stay disarmed: firing partial waves then
        would spray cold compiles across fresh wave buckets."""
        from nomad_tpu.parallel import coalesce

        fired = []

        def stub_launch_wave(kins, k_steps, features, mesh=None):
            fired.append(len(kins))
            return [object() for _ in kins]

        monkeypatch.setattr(coalesce, "launch_wave", stub_launch_wave)
        monkeypatch.setattr(coalesce, "wave_latency_ewma",
                            coalesce._LatencyEWMA())   # no sample

        class KinStub:
            class _Arr:
                shape = (8,)
            cap_cpu = _Arr()

        c = coalesce.LaunchCoalescer(3, window_min_s=0.001,
                                     window_max_s=0.001)
        out = {}

        def member(i):
            out[i] = c.launch(KinStub(), 1, None)

        threads = [threading.Thread(target=member, args=(i,))
                   for i in range(2)]
        for t in threads:
            t.start()
        time.sleep(0.2)
        assert fired == [], "deadline fired without a latency sample"
        c.done()                       # the third member finishes: the
        for t in threads:              # rendezvous completes the wave
            t.join(timeout=10)
        assert fired == [2]
        assert len(out) == 2
        for _ in range(2):
            c.done()

    def test_full_wave_still_fires_immediately(self, monkeypatch):
        from nomad_tpu.parallel import coalesce

        def stub_launch_wave(kins, k_steps, features, mesh=None):
            return [object() for _ in kins]

        monkeypatch.setattr(coalesce, "launch_wave", stub_launch_wave)

        class KinStub:
            class _Arr:
                shape = (8,)
            cap_cpu = _Arr()

        c = coalesce.LaunchCoalescer(2, window_min_s=30.0,
                                     window_max_s=30.0)
        out = {}

        def member(i):
            out[i] = c.launch(KinStub(), 1, None)
            c.done()

        t0 = time.perf_counter()
        threads = [threading.Thread(target=member, args=(i,))
                   for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        # rendezvous completed far below the 30s window
        assert time.perf_counter() - t0 < 5.0
        assert c.deadline_launches == 0
        assert len(out) == 2

    def test_suspended_member_does_not_block_wave(self, monkeypatch):
        """Pipelined plan submit: a participant inside its plan window
        (suspend) must not hold up the remaining members' wave."""
        from nomad_tpu.parallel import coalesce

        def stub_launch_wave(kins, k_steps, features, mesh=None):
            return [object() for _ in kins]

        monkeypatch.setattr(coalesce, "launch_wave", stub_launch_wave)

        class KinStub:
            class _Arr:
                shape = (8,)
            cap_cpu = _Arr()

        c = coalesce.LaunchCoalescer(3, window_min_s=30.0,
                                     window_max_s=30.0, adaptive=False)
        c.suspend()                      # member 2 is off at the applier
        out = {}

        def member(i):
            out[i] = c.launch(KinStub(), 1, None)

        threads = [threading.Thread(target=member, args=(i,))
                   for i in range(2)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        assert time.perf_counter() - t0 < 5.0
        assert len(out) == 2 and all(v is not None for v in out.values())
        c.resume()
        for _ in range(3):
            c.done()

    def test_wave_stats_and_exporter_gauges(self, monkeypatch):
        from nomad_tpu.parallel import coalesce
        from nomad_tpu.telemetry.exporter import prometheus_text

        def stub_launch_wave(kins, k_steps, features, mesh=None):
            return [object() for _ in kins]

        monkeypatch.setattr(coalesce, "launch_wave", stub_launch_wave)
        coalesce.wave_stats.reset()

        class KinStub:
            class _Arr:
                shape = (8,)
            cap_cpu = _Arr()

        c = coalesce.LaunchCoalescer(2, window_min_s=30.0,
                                     window_max_s=30.0)

        def member():
            c.launch(KinStub(), 1, None)
            c.done()

        threads = [threading.Thread(target=member) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        snap = coalesce.wave_stats.snapshot()
        assert snap["launches"] == 1
        assert snap["full_launches"] == 1
        assert 0.0 < snap["fill_ratio"] <= 1.0
        text = prometheus_text()
        assert "nomad_tpu_wave_fill_ratio" in text
        assert 'nomad_tpu_wave_park_latency_seconds{quantile="0.99"}' \
            in text
        assert 'nomad_tpu_wave_launches_total{fired="deadline"}' in text


class TestFeatureCanonicalization:
    def test_near_identical_features_share_a_variant(self):
        from nomad_tpu.ops.kernel import KernelFeatures, canonical_features
        from nomad_tpu.parallel.coalesce import union_features
        from nomad_tpu.tensors.schema import MAX_SPREADS

        a = KernelFeatures(n_spreads=1, with_step_penalties=True,
                           with_preferred=False)
        b = KernelFeatures(n_spreads=3, with_step_penalties=False,
                           with_preferred=True)
        ca, cb = canonical_features(a), canonical_features(b)
        assert ca == cb
        assert ca.n_spreads == MAX_SPREADS
        assert ca.with_step_penalties and ca.with_preferred
        # the wave union canonicalizes too
        assert union_features([a]) == union_features([b])

    def test_canonicalization_keeps_lean_lean(self):
        from nomad_tpu.ops.kernel import LEAN_FEATURES, canonical_features

        assert canonical_features(LEAN_FEATURES) == LEAN_FEATURES

    def test_canonical_features_preserve_placements(self):
        """Rounding a feature set UP must not change what the kernel
        chooses (neutral planes are no-ops by definition)."""
        from nomad_tpu.ops.kernel import (
            build_kernel_in,
            canonical_features,
            infer_features,
            pad_steps,
            place_taskgroup_jit,
        )
        from nomad_tpu.scheduler.context import EvalContext
        from nomad_tpu.scheduler.stack import XLAGenericStack
        from nomad_tpu.structs.eval_plan import Plan
        from nomad_tpu.tensors.schema import ClusterTensors
        from nomad_tpu.state.store import StateStore

        s = StateStore()
        for _ in range(6):
            s.upsert_node(mock.node())
        job = mock.job()
        s.upsert_job(job)
        snap = s.snapshot()
        c = ClusterTensors.build(snap.nodes())
        ctx = EvalContext(snap, Plan())
        st = XLAGenericStack(False, ctx, c)
        st.set_job(job)
        tg = job.task_groups[0]
        ev = st._build_eval_tensors(tg, np.zeros(c.n_pad, bool))
        kin = build_kernel_in(c, ev, 3)
        feats = infer_features(ev)
        kp = pad_steps(3)
        lean = place_taskgroup_jit(kin, kp, feats)
        canon = place_taskgroup_jit(kin, kp, canonical_features(feats))
        assert (np.asarray(lean.chosen) == np.asarray(canon.chosen)).all()
        assert np.allclose(np.asarray(lean.scores),
                           np.asarray(canon.scores), atol=1e-6)


class TestDonationAlignment:
    def test_preemption_loop_emits_no_donation_warning(self):
        """The seed's preemption cell warned 'Some donated buffers were
        not usable' (pre_cpu/pre_mem were donated but never returned).
        conftest promotes that warning to an error suite-wide; this
        test exercises the loop so the promotion has teeth."""
        import jax
        import jax.numpy as jnp

        from nomad_tpu.ops.kernel import build_kernel_in
        from nomad_tpu.parallel.batching import (
            device_put_shared,
            make_preemption_apply_loop,
        )
        from nomad_tpu.parallel.synthetic import (
            synthetic_cluster,
            synthetic_eval,
        )

        cluster = synthetic_cluster(100, cpu=3900.0, mem=7936.0,
                                    disk=98304.0, seed=7)
        ev0 = synthetic_eval(cluster, desired_count=4)
        shared = device_put_shared(build_kernel_in(cluster, ev0, 4))
        z = jnp.zeros(cluster.n_pad, jnp.float32)
        rng = np.random.default_rng(0)
        ac = jnp.asarray(rng.choice([250.0, 500.0], (2, 4))
                         .astype(np.float32))
        am = jnp.asarray(rng.choice([128.0, 256.0], (2, 4))
                         .astype(np.float32))
        ns = jnp.asarray(np.full(4, 4, np.int32))
        loop = make_preemption_apply_loop(4)
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            out = loop(shared, z + 0, z + 0, z + 1000.0, z + 1000.0,
                       z + 0.5, ac, am, ns)
            jax.block_until_ready(out)


class TestDecomposeDedupe:
    def test_overlapping_wall_intervals_count_once(self):
        """Two pipelined compiles overlapping on the clock must not sum
        past wall (the seed artifact's attributed_share was 1.0267)."""
        import trace_report
        from nomad_tpu.telemetry.trace import Span

        wall = 2.0
        stage_totals = {
            "kernel.compile": {"count": 2, "total_s": 2.4,
                               "exclusive_s": 2.4, "cpu_s": 0.0,
                               "exclusive_cpu_s": 0.0},
            "eval.schedule": {"count": 10, "total_s": 1.5,
                              "exclusive_s": 1.5, "cpu_s": 1.5,
                              "exclusive_cpu_s": 1.5},
        }
        # two compile spans overlapping 1.2s-1.2s => union 1.4s
        spans = [
            Span("kernel.compile", "t", 1, 0, 0.0, 1.2, 0, 0, 0, "a"),
            Span("kernel.compile", "t", 2, 0, 0.2, 1.2, 0, 0, 0, "b"),
        ]
        out = trace_report.decompose(stage_totals, wall, 10, spans=spans)
        assert out["attributed_share"] <= 1.0
        # raw sums stay honest and the overlap is reported
        assert out["attributed_raw_s"] == pytest.approx(3.9)
        assert out["parallel_overlap_s"] > 0
        # compile's share reflects the deduped interval, not the sum
        assert out["stages"]["compile"]["share_of_wall"] \
            == pytest.approx(1.4 / 2.0, abs=0.01)

    def test_no_spans_keeps_raw_attribution(self):
        import trace_report

        stage_totals = {
            "kernel.execute": {"count": 1, "total_s": 0.5,
                               "exclusive_s": 0.5, "cpu_s": 0.0,
                               "exclusive_cpu_s": 0.0},
        }
        out = trace_report.decompose(stage_totals, 1.0, 10)
        assert out["attributed_share"] == pytest.approx(0.5)
