"""Out-of-process driver plugin tests.

Modeled on reference plugins/drivers tests + go-plugin lifecycle
coverage: handshake, RPC roundtrip through a real subprocess, plugin
directory loading, crash handling, and a job running end-to-end on an
external driver.
"""

import os
import shutil
import sys
import time

import pytest

import nomad_tpu.plugins.demo_sleep_driver as demo_mod
from nomad_tpu import mock
from nomad_tpu.client.client import Client, ClientConfig, InProcessRPC
from nomad_tpu.plugins.drivers import HEALTH_HEALTHY, HEALTH_UNHEALTHY
from nomad_tpu.plugins.external import (
    ExternalDriver,
    PluginCrashed,
    load_plugin_dir,
)
from nomad_tpu.server.server import Server, ServerConfig
from nomad_tpu.structs import consts

ARGV = [sys.executable, "-m", "nomad_tpu.plugins.demo_sleep_driver"]


def _wait(fn, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(0.05)
    return False


@pytest.fixture()
def driver():
    drv = ExternalDriver(ARGV)
    yield drv
    drv.shutdown()


class TestProtocol:
    def test_handshake_and_info(self, driver):
        assert driver.name == "sleep"
        info = driver.plugin_info()
        assert info.name == "sleep" and info.type == "driver"
        fp = driver.fingerprint()
        assert fp.health == HEALTH_HEALTHY
        assert fp.attributes["driver.sleep"] == "1"

    def test_task_lifecycle_through_subprocess(self, driver):
        from nomad_tpu.plugins.drivers import TaskConfig

        cfg = TaskConfig(id="t1", name="t",
                         driver_config={"duration": "0.2s"})
        handle = driver.start_task(cfg)
        assert handle.driver == "sleep"
        assert handle.driver_state["pid"] > 0
        status = driver.inspect_task("t1")
        assert status.state in ("running", "exited")
        res = driver.wait_task("t1", timeout=10)
        assert res is not None and res.successful()
        driver.destroy_task("t1")

    def test_exit_code_propagates(self, driver):
        from nomad_tpu.plugins.drivers import TaskConfig

        driver.start_task(TaskConfig(
            id="t2", driver_config={"duration": "0.05s", "exit_code": 3}))
        res = driver.wait_task("t2", timeout=10)
        assert res.exit_code == 3 and not res.successful()

    def test_errors_cross_the_boundary(self, driver):
        # KeyError crosses typed: task_runner's force-destroyed
        # contract (task_runner.py wait loop) depends on it
        with pytest.raises(KeyError):
            driver.wait_task("no-such-task", timeout=1)

    def test_nested_dataclasses_survive_roundtrip(self, driver):
        from nomad_tpu.plugins.drivers import TaskConfig

        cfg = TaskConfig(id="t9", driver_config={"duration": "0.05s"})
        handle = driver.start_task(cfg)
        assert isinstance(handle.config, TaskConfig)
        assert handle.config.id == "t9"
        driver.wait_task("t9", timeout=10)
        status = driver.inspect_task("t9")
        assert status.exit_result is not None
        assert status.exit_result.successful()

    def test_crash_detected(self, driver):
        driver._proc.kill()
        driver._proc.wait()
        fp = driver.fingerprint()
        assert fp.health == HEALTH_UNHEALTHY
        with pytest.raises(PluginCrashed):
            driver.plugin_info()


class TestPluginDir:
    def test_load_plugin_dir(self, tmp_path):
        shutil.copy(demo_mod.__file__, tmp_path / "sleep_plugin.py")
        (tmp_path / "notes.txt").write_text("not a plugin")
        drivers = load_plugin_dir(str(tmp_path))
        try:
            assert list(drivers) == ["sleep"]   # handshake name wins
            assert drivers["sleep"].fingerprint().health == HEALTH_HEALTHY
        finally:
            for d in drivers.values():
                d.shutdown()

    def test_bad_plugin_skipped(self, tmp_path):
        (tmp_path / "broken.py").write_text("print('not a handshake')\n")
        assert load_plugin_dir(str(tmp_path)) == {}


class TestEndToEnd:
    def test_job_runs_on_external_driver(self, tmp_path):
        plugin_dir = tmp_path / "plugins"
        plugin_dir.mkdir()
        shutil.copy(demo_mod.__file__, plugin_dir / "sleep_plugin.py")
        server = Server(ServerConfig(num_workers=1))
        server.start()
        client = Client(
            InProcessRPC(server),
            ClientConfig(data_dir=str(tmp_path / "data"),
                         plugin_dir=str(plugin_dir)),
        )
        client.start()
        try:
            # the external driver fingerprints onto the node
            assert "sleep" in client.drivers
            job = mock.job()
            job.type = consts.JOB_TYPE_BATCH
            job.task_groups[0].count = 1
            task = job.task_groups[0].tasks[0]
            task.driver = "sleep"
            task.config = {"duration": "0.3s"}
            server.job_register(job)
            assert _wait(lambda: any(
                a.client_status == consts.ALLOC_CLIENT_COMPLETE
                for a in server.state.snapshot().allocs_by_job(
                    job.namespace, job.id))), "task never completed"
        finally:
            client.shutdown()
            server.shutdown()
