"""Multi-process scheduler workers (ISSUE 17).

Tier-1 coverage for the process split: the framed IPC channel (WAL
CRC framing over a socketpair), cross-process generation leases (the
explicit strong pin the weak root registry needs once a reader lives
in another process), the snapshot transport property — a replica
reconstructed from one bootstrap frame plus ``(gen, delta)`` frames is
BIT-IDENTICAL to the owner's root at the same generation, usage planes
included — and the live plane: a server running ``scheduler_workers=2``
places real jobs through real worker processes, and a pinned-seed
SIGKILL mid-lease converges through supervisor lease recovery.

The full 3-node worker-kill chaos schedule runs in the stress tier
(tests/test_stress.py::TestChaosCell via bench/trace_report
``worker-kill-mid-lease``).
"""

import gc
import pickle
import time

import pytest

from test_mvcc_store import _apply, _gen_ops

from nomad_tpu import mock
from nomad_tpu.state.store import (
    StateStore,
    _TABLE_NAMES,
    apply_frame,
    bootstrap_frame,
    delta_frame,
    expire_generation_leases,
    lease_generation,
    leased_generation_count,
    release_owner_leases,
    renew_owner_leases,
    snapshot_at,
    store_stats,
)
from nomad_tpu.state.usage import usage_rebuild_diff
from nomad_tpu.structs import consts
from nomad_tpu.utils import faultpoints
from nomad_tpu.utils.ipc import Channel, FrameError, channel_pair, socket_pair


@pytest.fixture(autouse=True)
def _clean_plane():
    faultpoints.reset()
    yield
    faultpoints.reset()


def _wait(fn, timeout=10.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return
        time.sleep(0.02)
    raise AssertionError(f"timeout waiting for {msg}")


# ---------------------------------------------------------------------------
# framed channel


class TestChannel:
    def test_roundtrip_and_order(self):
        a, b = channel_pair()
        try:
            a.send({"t": "x", "n": 1})
            # well under the socketpair buffer: send blocks (by
            # design, flow control) once the peer stops draining
            a.send(["big", b"\x00" * 65_536])
            assert b.recv() == {"t": "x", "n": 1}
            assert b.recv() == ["big", b"\x00" * 65_536]
            b.send("reply")
            assert a.recv() == "reply"
        finally:
            a.close()
            b.close()

    def test_recv_timeout_returns_none(self):
        a, b = channel_pair()
        try:
            assert b.recv(timeout=0.05) is None
        finally:
            a.close()
            b.close()

    def test_closed_peer_raises_eof(self):
        a, b = channel_pair()
        a.close()
        try:
            with pytest.raises(EOFError):
                b.recv()
        finally:
            b.close()

    def test_corrupt_frame_raises_frame_error(self):
        import struct
        import zlib

        raw_a, raw_b = socket_pair()
        chan = Channel(raw_b)
        try:
            payload = pickle.dumps({"k": "v"})
            bad_crc = (zlib.crc32(payload) ^ 0xDEAD) & 0xFFFFFFFF
            raw_a.sendall(struct.pack(">II", len(payload), bad_crc)
                          + payload)
            with pytest.raises(FrameError):
                chan.recv()
        finally:
            raw_a.close()
            chan.close()


# ---------------------------------------------------------------------------
# generation leases


class TestGenerationLeases:
    def test_lease_pins_root_past_reader_release(self):
        store = StateStore()
        store.upsert_node(mock.node())
        snap = store.snapshot()
        gen = store.current_generation()
        assert lease_generation(gen, "test-owner")
        store.upsert_node(mock.node())     # advance past the leased gen
        del snap
        gc.collect()
        # the weak registry alone would have freed it; the lease pins
        assert snapshot_at(gen) is not None
        assert leased_generation_count() >= 1
        st = store_stats.snapshot()
        assert st["live_roots_leased"] >= 1
        assert st["live_roots"] == (st["live_roots_leased"]
                                    + st["live_roots_in_process"])
        release_owner_leases("test-owner")
        gc.collect()
        assert snapshot_at(gen) is None

    def test_ttl_expiry_and_renewal(self):
        store = StateStore()
        store.upsert_node(mock.node())
        gen = store.current_generation()
        assert lease_generation(gen, "ttl-owner", ttl_s=0.08)
        store.upsert_node(mock.node())
        assert renew_owner_leases("ttl-owner", ttl_s=0.08) == 1
        time.sleep(0.12)
        # liveness-bounded: no heartbeat -> the sweep drops the pin
        assert expire_generation_leases() >= 1
        gc.collect()
        assert snapshot_at(gen) is None
        assert release_owner_leases("ttl-owner") == 0

    def test_lease_on_dead_generation_refuses(self):
        store = StateStore()
        store.upsert_node(mock.node())
        gen = store.current_generation()
        store.upsert_node(mock.node())
        gc.collect()
        assert not lease_generation(gen, "late-owner")


# ---------------------------------------------------------------------------
# snapshot transport frames: the bit-identity property


def _ship(frame):
    """Frames cross a pickle boundary in production; make the test
    cross it too (catches identity-dependent encodings, e.g. the
    TOMBSTONE sentinel)."""
    return pickle.loads(pickle.dumps(frame,
                                     protocol=pickle.HIGHEST_PROTOCOL))


def _assert_replica_identical(owner, replica):
    ro, rr = owner._root, replica._root
    assert rr.generation == ro.generation
    assert rr.index == ro.index
    assert rr.table_indexes == ro.table_indexes
    for name in _TABLE_NAMES:
        ot = ro.tables[name].to_dict()
        nt = rr.tables[name].to_dict()
        assert sorted(ot) == sorted(nt), f"table {name} keys diverged"
        for k, row in ot.items():
            if isinstance(row, (set, frozenset)):
                # index-table rows are sets: bucket layout (and so
                # pickle bytes) depends on insertion/removal history,
                # content equality is the invariant
                assert nt[k] == row, f"table {name} row {k!r} diverged"
            else:
                # struct rows have identity __eq__; serialized-bytes
                # equality is the bit-identity check
                assert pickle.dumps(nt[k]) == pickle.dumps(row), \
                    f"table {name} row {k!r} diverged"
    # the replica's usage planes were advanced by replaying the same
    # transitions the owner took — same oracle as the owner's invariant
    assert usage_rebuild_diff(replica) == []


def _run_frame_reconstruction(seed, n_ops=60):
    ops = _gen_ops(seed, n_ops=n_ops)
    owner = StateStore()
    _apply(owner, ops[: n_ops // 2])

    replica = StateStore()
    apply_frame(replica, _ship(bootstrap_frame(
        owner, pin_owner=f"prop-{seed}")))
    _assert_replica_identical(owner, replica)

    synced = owner.current_generation()
    rest = ops[n_ops // 2:]
    step = 5
    for i in range(0, len(rest), step):
        _apply(owner, rest[i:i + step])
        frame = delta_frame(owner, synced, pin_owner=f"prop-{seed}")
        if frame is None:
            # nothing changed (or base lost — must not happen while
            # our own pin holds it)
            assert owner.current_generation() == synced
            continue
        apply_frame(replica, _ship(frame))
        synced = frame["generation"]
        _assert_replica_identical(owner, replica)
    release_owner_leases(f"prop-{seed}")


class TestFrameReconstruction:
    @pytest.mark.parametrize("seed", range(25))
    def test_delta_reconstructed_replica_bit_identical(self, seed):
        """The CI sweep: 25 seeds; after every delta frame the worker-
        side replica is bit-identical to the owner root at the same
        generation (rows, indexes, usage planes)."""
        _run_frame_reconstruction(seed)

    @pytest.mark.slow
    def test_delta_reconstruction_200_seed_sweep(self):
        for seed in range(25, 200):
            _run_frame_reconstruction(seed, n_ops=40)

    def test_out_of_order_delta_raises(self):
        owner = StateStore()
        owner.upsert_node(mock.node())
        replica = StateStore()
        apply_frame(replica, _ship(bootstrap_frame(owner)))
        base = owner.current_generation()
        snap = owner.snapshot()             # pin base for the diff
        owner.upsert_node(mock.node())
        frame = delta_frame(owner, base)
        assert frame is not None
        apply_frame(replica, _ship(frame))
        with pytest.raises(ValueError, match="out-of-order"):
            apply_frame(replica, _ship(frame))   # replay: base moved on
        del snap

    def test_delta_none_when_base_root_gone(self):
        owner = StateStore()
        owner.upsert_node(mock.node())
        base = owner.current_generation()
        owner.upsert_node(mock.node())
        gc.collect()
        assert delta_frame(owner, base) is None   # bootstrap fallback


# ---------------------------------------------------------------------------
# the live plane: real worker processes


def _make_server(scheduler_workers=2, **kw):
    from nomad_tpu.server.server import Server, ServerConfig

    cfg = ServerConfig(
        num_workers=1, worker_batch_size=4, heartbeat_ttl=60.0,
        nack_timeout=2.0, scheduler_workers=scheduler_workers, **kw)
    server = Server(cfg)
    server.start()
    return server


def _submit_jobs(server, n, count=2):
    jobs = []
    for _ in range(n):
        job = mock.simple_job()
        job.task_groups[0].count = count
        server.job_register(job)
        jobs.append(job)
    return jobs


def _converged(server, jobs, want_per_job=2):
    snap = server.state.snapshot()
    live = sum(1 for j in jobs
               for a in snap.allocs_by_job(j.namespace, j.id)
               if not a.terminal_status())
    if live != len(jobs) * want_per_job:
        return False
    if any(e.status in (consts.EVAL_STATUS_PENDING,
                        consts.EVAL_STATUS_BLOCKED)
           for e in snap.evals_iter()):
        return False
    b = server.eval_broker.stats()
    return (b["total_ready"] == 0 and b["total_unacked"] == 0
            and b["total_waiting"] == 0)


class TestWorkerProcesses:
    def test_end_to_end_scheduling_through_worker_processes(self):
        """scheduler_workers=2: jobs place through real worker
        processes (dequeue → replica snapshot → plan-build → submit
        over IPC), in-process workers shrink to the core queue, and
        the usage planes stay rebuild-identical."""
        server = _make_server()
        try:
            assert server.worker_supervisor is not None
            # the in-process workers serve ONLY the core (GC) queue
            assert all(w.schedulers == [consts.JOB_TYPE_CORE]
                       for w in server.workers)
            for _ in range(8):
                server.node_register(mock.node())
            jobs = _submit_jobs(server, 6)
            _wait(lambda: _converged(server, jobs), timeout=90.0,
                  msg="jobs placed through worker processes")
            wp = server.stats()["worker_procs"]
            assert wp["workers"] == 2 and wp["alive"] == 2
            assert wp["acked"] >= len(jobs)
            assert wp["outstanding"] == 0
            assert wp["lease_reissues"] == 0
            assert usage_rebuild_diff(server.state) == []
            # exact placement: no duplicate live slots
            snap = server.state.snapshot()
            for j in jobs:
                names = [a.name for a in
                         snap.allocs_by_job(j.namespace, j.id)
                         if not a.terminal_status()]
                assert len(set(names)) == len(names) == 2
        finally:
            server.shutdown()
        # shutdown released every worker generation lease
        assert leased_generation_count() == 0

    def test_sigkill_mid_lease_recovers_pinned_seed(self):
        """ISSUE 17 satellite: REAL process death. The pinned-seed
        schedule SIGKILLs one worker process right after it receives a
        lease (evals held, replica synced, no chance to ack/nack or
        unwind) — the supervisor's liveness monitor must re-enqueue
        the dead worker's lease ledger, respawn the process, and the
        burst must converge to exact placement anyway."""
        server = _make_server()
        try:
            for _ in range(8):
                server.node_register(mock.node())
            faultpoints.arm(
                {"workerproc.kill": {"kind": "error", "nth": 2}},
                seed=17017)
            jobs = _submit_jobs(server, 6)
            _wait(lambda: _converged(server, jobs), timeout=120.0,
                  msg="burst converged through worker SIGKILL")
            assert faultpoints.stats()["workerproc.kill"]["fires"] == 1
            faultpoints.disarm()
            wp = server.stats()["worker_procs"]
            assert wp["respawns"] >= 1, wp
            assert wp["lease_reissues"] >= 1, wp
            assert wp["alive"] == 2, wp
            assert wp["outstanding"] == 0, wp
            assert usage_rebuild_diff(server.state) == []
            snap = server.state.snapshot()
            for j in jobs:
                names = [a.name for a in
                         snap.allocs_by_job(j.namespace, j.id)
                         if not a.terminal_status()]
                assert len(set(names)) == len(names) == 2, \
                    "placement must be exact through the kill"
        finally:
            server.shutdown()
        assert leased_generation_count() == 0


class TestStalePlanToken:
    """plan_endpoint.go Submit token-check parity, found by the
    worker-kill-mid-lease chaos schedule: a dead worker's in-flight
    plan can reach the applier AFTER the supervisor re-enqueued its
    lease — committing it would race the redelivered eval (scheduling
    from a pre-commit snapshot) into duplicate live slots. A plan is
    valid only while its worker still holds the eval lease."""

    def test_stale_token_plan_rejected_live_token_accepted(self):
        from nomad_tpu.server.server import Server, ServerConfig
        from nomad_tpu.structs.eval_plan import Plan

        server = Server(ServerConfig(num_workers=1))
        broker = server.eval_broker
        broker.set_enabled(True)
        ev = mock.eval()
        broker.enqueue(ev)
        out, token = broker.dequeue([ev.type], timeout=1)
        assert out.id == ev.id
        plan = Plan(eval_id=ev.id, eval_token=token)
        # lease held: the plan is valid
        assert server._validate_plan_token(plan) is None
        # the lease is re-enqueued (dead worker recovery / auto-nack
        # deadline) — the old token goes stale
        broker.nack(ev.id, token)
        with pytest.raises(ValueError, match="stale eval token"):
            server.submit_plan(plan)
        # token-less plans (tests, synchronous harnesses) skip the check
        assert server._validate_plan_token(Plan(eval_id=ev.id)) is None
